"""Test harness: force an 8-device virtual CPU mesh *before* jax imports.

The TPU analog of the reference's ``SparkContext("local[*]")``
(``Graphframes.py:12``): run the real pjit/shard_map code paths on fake
devices on one host (SURVEY §4, "multi-chip-without-a-cluster").
"""

import os
import sys

# The session environment routes every Python process to the real TPU via a
# sitecustomize hook (PALLAS_AXON_POOL_IPS -> axon backend registration at
# interpreter start), which wins over any in-process JAX_PLATFORMS setting.
# Tests need 8 virtual CPU devices, so pytest re-execs itself once with the
# hook disabled (from pytest_configure, after restoring captured fds, so the
# replacement process inherits the real stdout). Set GRAPHMINE_TEST_TPU=1 to
# run tests on the real device instead. The scrub recipe itself is shared
# with __graft_entry__.dryrun_multichip via graphmine_tpu/_envscrub.py,
# loaded by file path so the jax-importing package __init__ never runs here.


_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)


def _envscrub():
    # Reuse the single loader in __graft_entry__ (imports only numpy/stdlib,
    # never jax) so the scrub bootstrap exists in exactly one place.
    import __graft_entry__

    return __graft_entry__._load_envscrub()


# Decided at import time, BEFORE the in-process scrub below blanks
# PALLAS_AXON_POOL_IPS (the hook already fired at interpreter start, so the
# scrub can't save *this* process — only a re-exec can).
_REEXEC_NEEDED = bool(
    os.environ.get("PALLAS_AXON_POOL_IPS")
    and os.environ.get("GRAPHMINE_TEST_TPU") != "1"
    and os.environ.get("_GRAPHMINE_TEST_REEXEC") != "1"
)


def _needs_reexec() -> bool:
    return _REEXEC_NEEDED


def _invoked_as_pytest_cli() -> bool:
    # Only rebuild the command line from sys.argv when pytest owns it;
    # under programmatic pytest.main() the argv belongs to the caller.
    argv0 = os.path.basename(sys.argv[0])
    return argv0 in ("pytest", "py.test") or argv0 == "__main__.py"


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running; excluded from the tier-1 pass"
    )
    config.addinivalue_line(
        "markers",
        "faults: fault-injection resilience suite (tests/test_resilience.py "
        "plus the tripwire/reshard cases in tests/test_sharded.py); runs in "
        "the default CPU pass — select with -m faults or "
        "tools/run_tier1.sh --faults-only",
    )
    config.addinivalue_line(
        "markers",
        "obs: tracing/telemetry suite (tests/test_obs.py: spans, record "
        "schema, heartbeat, superstep telemetry, obs_report e2e); runs in "
        "the default CPU pass — select with -m obs or "
        "tools/run_tier1.sh --obs-only",
    )
    config.addinivalue_line(
        "markers",
        "ann: approximate-kNN suite (tests/test_ann.py + "
        "tests/test_lof_policy.py: IVF contract/recall, the LOF "
        "auto-policy crossover, recall/AUROC regression gates); runs in "
        "the default CPU pass — select with -m ann or "
        "tools/run_tier1.sh --ann-only",
    )
    config.addinivalue_line(
        "markers",
        "serve: serving-layer suite (tests/test_serve.py: versioned "
        "snapshots, delta ingest + warm-start repair equivalence, the "
        "batched query engine, live-swap HTTP server); runs in the "
        "default CPU pass — select with -m serve or "
        "tools/run_tier1.sh --serve-only",
    )
    config.addinivalue_line(
        "markers",
        "blocking: propagation-blocking superstep suite "
        "(tests/test_blocking.py: blocked-vs-sort bit parity for "
        "LPA/CC/PageRank fused + sharded, the crossover policy owner, "
        "plan_build records, the blocking bench-tier smoke); runs in the "
        "default CPU pass — select with -m blocking or "
        "tools/run_tier1.sh --blocking-only",
    )
    config.addinivalue_line(
        "markers",
        "admission: write-path admission-control suite "
        "(tests/test_admission.py: the accept/queue/coalesce/shed policy "
        "owner, order-exact delta coalescing, deadline shedding, the "
        "LOF-defer rung, and the overload chaos acceptance test); runs "
        "in the default CPU pass — select with -m admission or "
        "tools/run_tier1.sh --admission-only",
    )
    config.addinivalue_line(
        "markers",
        "fleet: replicated-serving-fleet suite (tests/test_fleet.py: "
        "per-replica circuit breakers, quorum committed-version "
        "routing, writer loss = read-only, zero-downtime rolling "
        "reload, the reload-vs-inflight-delta rebase, serve_cli client "
        "retries, and the 3-replica kill+slow+roll chaos acceptance "
        "test); runs in the default CPU pass — select with -m fleet or "
        "tools/run_tier1.sh --fleet-only",
    )
    config.addinivalue_line(
        "markers",
        "wal: durable-write-path suite (tests/test_wal.py: write-ahead "
        "log framing/torn-tail/rotation/compaction, writer-epoch "
        "fencing, WAL-durable 202 acknowledgements + kill/restart "
        "replay, duplicate-submit idempotency, log-shipped standby + "
        "replication lag, fenced promotion, and the 2-writer/3-replica "
        "writer-SIGKILL chaos acceptance test); runs in the default "
        "CPU pass — select with -m wal or tools/run_tier1.sh "
        "--wal-only",
    )
    config.addinivalue_line(
        "markers",
        "trace: cross-process observability suite (tests/test_trace.py: "
        "traceparent propagation + span adoption, per-delta "
        "time-to-visible stages, the merged router histogram, "
        "trace_stitch/obs_report/schema_lint gates, POST /profilez, and "
        "the chaos-run shard-stitch acceptance test); runs in the "
        "default CPU pass — select with -m trace or tools/run_tier1.sh "
        "--trace-only",
    )
    config.addinivalue_line(
        "markers",
        "perf: compute-plane performance-observability suite "
        "(tests/test_costmodel.py: analytical cost model exact against "
        "hand-computed plans, superstep_timing achieved-vs-model "
        "attribution e2e, bench_diff regression gate + trajectory "
        "self-check over the committed BENCH_*.json, the silicon-capture "
        "manifest, obs_report roofline section); runs in the default CPU "
        "pass — select with -m perf or tools/run_tier1.sh --perf-only",
    )
    config.addinivalue_line(
        "markers",
        "quality: result-quality observability suite "
        "(tests/test_quality.py: quantile-sketch merge associativity/"
        "commutativity, PSI drift hand-computed exactness, partition-"
        "matched churn, canary probe recall + injected scorer "
        "regression, alert firing/resolve/flap sequences, /alertz + "
        "fleet sketch-merge e2e, the obs_report quality timeline and "
        "its exit-4 canary gate); runs in the default CPU pass — "
        "select with -m quality or tools/run_tier1.sh --quality-only",
    )
    config.addinivalue_line(
        "markers",
        "sharded2d: 2D-edge-partition neighbor-exchange suite "
        "(tests/test_sharded2d.py: LPA/CC bit-parity vs the sort oracle "
        "over power-law/ring/self-loop/isolated/duplicate-edge graphs "
        "fused + virtual-mesh sharded (weighted included), per-peer "
        "boundary index-table exactness on hand-built 3-shard graphs, "
        "the planner ladder + env-override policy pins, costmodel/"
        "memmodel exact-arithmetic pins, plan-time per-peer-buffer "
        "pre-degrade, the serve warm-repair 2D e2e and the exchange "
        "bench-tier smoke); runs in the default CPU pass — select with "
        "-m sharded2d or tools/run_tier1.sh --sharded2d-only",
    )
    config.addinivalue_line(
        "markers",
        "mem: memory-plane observability suite (tests/test_memmodel.py: "
        "the analytical HBM footprint inventory exact against "
        "hand-computed tiny plans, the planner byte-constant "
        "derivation, memory_watermark emission e2e + the fault-injected "
        "OOM degrade join, serve /statusz + /profilez memory surfaces, "
        "the obs_report memory waterfall and the bench_diff memory "
        "gate); runs in the default CPU pass — select with -m mem or "
        "tools/run_tier1.sh --mem-only",
    )
    config.addinivalue_line(
        "markers",
        "tenancy: multi-tenant serving suite (tests/test_tenancy.py: "
        "namespaced snapshot store round-trip, hostile tenant-id "
        "refusal, per-tenant admission bounds + weighted-fair apply, "
        "tenant-scoped WAL replay/dedupe, per-tenant alert planes and "
        "the noisy-neighbor chaos acceptance); runs in the default CPU "
        "pass — select with -m tenancy or tools/run_tier1.sh "
        "--tenancy-only",
    )
    config.addinivalue_line(
        "markers",
        "shardplane: sharded-write-plane suite (tests/test_shardplane.py: "
        "vertex-range plan ownership, deterministic delta-splitter "
        "bit-parity vs sequential whole-batch apply, epoch "
        "stage/commit/recover incl. the torn-publish drill, per-range "
        "failover and the 3-shard/2-tenant shard-kill chaos acceptance "
        "test); runs in the default CPU pass — select with -m shardplane "
        "or tools/run_tier1.sh --shardplane-only",
    )
    config.addinivalue_line(
        "markers",
        "slo: serving-SLO observability suite (tests/test_slo.py: "
        "bucket histograms + merge associativity, live /metrics and "
        "/statusz under the query hammer, quantile agreement vs the "
        "access_log JSONL, repair-debt accounting, request tracing); "
        "runs in the default CPU pass — select with -m slo or "
        "tools/run_tier1.sh --slo-only",
    )
    if not (_needs_reexec() and _invoked_as_pytest_cli()):
        return
    cap = config.pluginmanager.getplugin("capturemanager")
    if cap is not None:
        cap.stop_global_capturing()
    env = _envscrub().virtual_cpu_env(8, override_count=False)
    env["_GRAPHMINE_TEST_REEXEC"] = "1"
    os.execve(sys.executable, [sys.executable, "-m", "pytest", *sys.argv[1:]], env)


if os.environ.get("GRAPHMINE_TEST_TPU") != "1":
    # Same scrub in-process (covers programmatic pytest.main() runs where
    # the re-exec path doesn't fire; an existing explicit device-count
    # flag is respected).
    os.environ.update(_envscrub().virtual_cpu_env(8, override_count=False))

import numpy as np  # noqa: E402
import pytest  # noqa: E402

REFERENCE_PARQUET = "/root/reference/CommunityDetection/data/outlinks_pq"


def cached_edgelist(prefix: str, text: str) -> str:
    """Persist generated test edge-list ``text`` at a content-addressed,
    per-user path in the shared tempdir and return the path.

    Reused across pytest runs instead of leaking one temp dir per
    invocation — but never trusted blindly: the digest in the name
    invalidates the cache whenever the generator changes, and the
    read-back check means a stale or foreign file (shared /tmp) can't be
    consumed. If the shared path isn't writable, falls back to a private
    directory.
    """
    import hashlib
    import tempfile

    digest = hashlib.sha1(text.encode()).hexdigest()[:12]
    p = os.path.join(
        tempfile.gettempdir(), f"{prefix}_{os.getuid()}_{digest}.txt"
    )
    try:
        with open(p) as f:
            cached_ok = f.read() == text
    except OSError:
        cached_ok = False
    if not cached_ok:
        try:
            tmp = f"{p}.{os.getpid()}"
            with open(tmp, "w") as f:
                f.write(text)
            os.replace(tmp, p)
        except OSError:
            p = os.path.join(
                tempfile.mkdtemp(prefix=f"{prefix}_"), "edges.txt"
            )
            with open(p, "w") as f:
                f.write(text)
    return p


@pytest.fixture(scope="session")
def bundled_edges():
    from graphmine_tpu.io.edges import load_parquet_edges

    if not os.path.isdir(REFERENCE_PARQUET):
        pytest.skip("bundled reference parquet not available")
    return load_parquet_edges(REFERENCE_PARQUET)


@pytest.fixture(scope="session")
def bundled_graph(bundled_edges):
    from graphmine_tpu.graph.container import graph_from_edge_table

    return graph_from_edge_table(bundled_edges)


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(0)
