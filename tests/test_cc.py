"""Connected components vs goldens (34 WCCs, giant 4,440 — BASELINE.md) and a
networkx union-find oracle on random graphs.
"""

import networkx as nx
import numpy as np

from graphmine_tpu.graph.container import build_graph, graph_from_edge_table
from graphmine_tpu.ops.cc import connected_components


def test_bundled_wcc_golden(bundled_edges, bundled_graph):
    labels = np.asarray(connected_components(bundled_graph))
    _, counts = np.unique(labels, return_counts=True)
    assert len(counts) == 34
    assert counts.max() == 4440


def test_cc_matches_networkx_oracle(rng):
    for trial in range(5):
        v = int(rng.integers(10, 200))
        e = int(rng.integers(5, 400))
        src = rng.integers(0, v, e)
        dst = rng.integers(0, v, e)
        g = build_graph(src, dst, num_vertices=v)
        labels = np.asarray(connected_components(g))
        nxg = nx.Graph()
        nxg.add_nodes_from(range(v))
        nxg.add_edges_from(zip(src.tolist(), dst.tolist()))
        for comp in nx.connected_components(nxg):
            comp = sorted(comp)
            assert len(set(labels[comp].tolist())) == 1
            assert labels[comp[0]] == comp[0]  # label = smallest member


def test_long_chain_converges():
    # Pointer jumping keeps iterations ~log(V) rather than V; correctness check.
    v = 500
    src = np.arange(v - 1)
    dst = np.arange(1, v)
    g = build_graph(src, dst, num_vertices=v)
    labels = np.asarray(connected_components(g))
    assert (labels == 0).all()
