"""Connected components vs goldens (34 WCCs, giant 4,440 — BASELINE.md) and a
networkx union-find oracle on random graphs.
"""

import networkx as nx
import numpy as np

from graphmine_tpu.graph.container import build_graph, graph_from_edge_table
from graphmine_tpu.ops.cc import connected_components


def test_bundled_wcc_golden(bundled_edges, bundled_graph):
    labels = np.asarray(connected_components(bundled_graph))
    _, counts = np.unique(labels, return_counts=True)
    assert len(counts) == 34
    assert counts.max() == 4440


def test_cc_matches_networkx_oracle(rng):
    for trial in range(5):
        v = int(rng.integers(10, 200))
        e = int(rng.integers(5, 400))
        src = rng.integers(0, v, e)
        dst = rng.integers(0, v, e)
        g = build_graph(src, dst, num_vertices=v)
        labels = np.asarray(connected_components(g))
        nxg = nx.Graph()
        nxg.add_nodes_from(range(v))
        nxg.add_edges_from(zip(src.tolist(), dst.tolist()))
        for comp in nx.connected_components(nxg):
            comp = sorted(comp)
            assert len(set(labels[comp].tolist())) == 1
            assert labels[comp[0]] == comp[0]  # label = smallest member


def test_long_chain_converges():
    # Pointer jumping keeps iterations ~log(V) rather than V; correctness check.
    v = 500
    src = np.arange(v - 1)
    dst = np.arange(1, v)
    g = build_graph(src, dst, num_vertices=v)
    labels = np.asarray(connected_components(g))
    assert (labels == 0).all()


def test_bucketed_cc_matches_segment_path(rng):
    """r5: the bucketed-min CC superstep (cc_superstep_bucketed) is the
    min-reduce twin of the fused LPA kernel — labels must match the
    segment_min path BIT-FOR-BIT every superstep, across random graphs
    and a >2048-degree mega-hub (the histogram-path shape class), and
    the fixpoint runs must agree in labels AND iteration counts."""
    import jax.numpy as jnp

    from graphmine_tpu.ops.bucketed_mode import build_graph_and_plan
    from graphmine_tpu.ops.cc import cc_superstep, cc_superstep_bucketed

    def check(src, dst, v):
        g, plan = build_graph_and_plan(src, dst, num_vertices=v)
        labels = jnp.arange(v, dtype=jnp.int32)
        for _ in range(4):  # per-superstep equality, not just fixpoint
            want = cc_superstep(labels, g)
            got = cc_superstep_bucketed(labels, plan)
            np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
            labels = want
        want, it_w = connected_components(g, return_iterations=True)
        got, it_g = connected_components(g, return_iterations=True, plan=plan)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        assert int(it_g) == int(it_w)

    for v, e in ((97, 400), (500, 3000), (64, 80)):
        check(rng.integers(0, v, e).astype(np.int32),
              rng.integers(0, v, e).astype(np.int32), v)
    # mega-hub star + a disjoint path: hist path plus multiple components
    n = 2600
    src = np.concatenate([np.zeros(n, np.int32),
                          np.arange(n + 1, n + 4, dtype=np.int32)])
    dst = np.concatenate([np.arange(1, n + 1, dtype=np.int32),
                          np.arange(n + 2, n + 5, dtype=np.int32)])
    check(src, dst, n + 5)


def test_cc_auto_plan_policy(rng):
    """r5: plan="auto" reuses LPA's cached fused plan above the message
    threshold and must agree with the forced segment path; tiny graphs
    stay on segment_min (no plan build)."""
    from graphmine_tpu.ops import lpa as lpa_mod

    v, e = 300, 40_000  # 80K messages > the 1<<16 auto threshold
    src = rng.integers(0, v, e).astype(np.int32)
    dst = rng.integers(0, v, e).astype(np.int32)
    g = build_graph(src, dst, num_vertices=v)
    auto = np.asarray(connected_components(g))
    seg = np.asarray(connected_components(g, plan=None))
    np.testing.assert_array_equal(auto, seg)
    # the auto path populated the shared LPA plan cache for this graph
    assert any(
        ref() is g.msg_ptr for ref, _ in lpa_mod._auto_plan_cache.values()
    )
