"""End-to-end pipeline tests: full run on bundled data, config validation,
checkpoint/resume, backend gating."""

import numpy as np
import pytest

from graphmine_tpu.pipeline.config import PipelineConfig, parse_args
from graphmine_tpu.pipeline.driver import run_pipeline
from graphmine_tpu.pipeline import checkpoint as ckpt

import os

from conftest import REFERENCE_PARQUET

needs_data = pytest.mark.skipif(
    not os.path.isdir(REFERENCE_PARQUET),
    reason="bundled reference parquet not available",
)


@needs_data
def test_full_pipeline_bundled(tmp_path):
    cfg = PipelineConfig(
        outlier_method="both",
        checkpoint_dir=str(tmp_path / "ckpt"),
    )
    res = run_pipeline(cfg)
    assert res.edge_table.num_rows_raw == 18399
    assert res.graph.num_vertices == 4613
    assert 550 <= res.num_communities <= 750
    assert res.outliers is not None and res.lof is not None
    assert res.lof.shape == (4613,)
    # metrics: one record per LPA iteration with the headline metric
    iters = [r for r in res.metrics.records if r["phase"] == "lpa_iter"]
    assert len(iters) == 5
    assert all(r["edges_per_sec_per_chip"] > 0 for r in iters)


@needs_data
def test_resume_from_checkpoint(tmp_path):
    ckdir = str(tmp_path / "ck")
    cfg = PipelineConfig(max_iter=3, outlier_method="none", checkpoint_dir=ckdir)
    res1 = run_pipeline(cfg)
    saved = ckpt.load_labels(ckdir)
    assert saved is not None and saved[1] == 3
    # resume with a higher max_iter: picks up at iteration 3
    cfg2 = PipelineConfig(
        max_iter=5, outlier_method="none", checkpoint_dir=ckdir, resume=True
    )
    res2 = run_pipeline(cfg2)
    iters = [r for r in res2.metrics.records if r["phase"] == "lpa_iter"]
    assert [r["iteration"] for r in iters] == [4, 5]
    # equals an uninterrupted 5-iteration run
    res_full = run_pipeline(PipelineConfig(max_iter=5, outlier_method="none"))
    np.testing.assert_array_equal(res2.labels, res_full.labels)


@needs_data
def test_multi_device_pipeline():
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    cfg = PipelineConfig(num_devices=8, outlier_method="none")
    res8 = run_pipeline(cfg)
    res1 = run_pipeline(PipelineConfig(num_devices=1, outlier_method="none"))
    np.testing.assert_array_equal(res8.labels, res1.labels)


@needs_data
def test_multi_device_lof_matches_single_device():
    """r2: with >1 device the pipeline's LOF phase runs the ring-sharded
    distributed path; scores must match the single-device all-pairs path."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    multi = run_pipeline(PipelineConfig(num_devices=8, outlier_method="lof"))
    single = run_pipeline(PipelineConfig(num_devices=1, outlier_method="lof"))
    # Discrete graph features produce many identical rows; tied neighbor
    # sets legitimately differ between the ring merge and the single
    # top_k (measured: 5/4613 scores off by <8e-4 on the bundled data),
    # so scores agree to tie-noise tolerance and the outlier ranking's
    # head must be identical.
    np.testing.assert_allclose(multi.lof, single.lof, rtol=5e-3, atol=2e-3)
    top_m = set(np.argsort(multi.lof)[::-1][:10])
    top_s = set(np.argsort(single.lof)[::-1][:10])
    assert top_m == top_s


@needs_data
def test_ring_schedule_pipeline():
    """--schedule ring reaches ring_label_propagation from the product
    surface (VERDICT r1: the memory-scalable path was unreachable) and
    produces the same labels as the replicated schedule."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    ring = run_pipeline(
        PipelineConfig(num_devices=8, schedule="ring", outlier_method="none")
    )
    rep = run_pipeline(PipelineConfig(num_devices=8, outlier_method="none"))
    np.testing.assert_array_equal(ring.labels, rep.labels)
    part = [r for r in ring.metrics.records if r["phase"] == "partition"]
    assert part and part[0]["schedule"] == "ring"


@needs_data
def test_louvain_pipeline():
    res = run_pipeline(
        PipelineConfig(community_method="louvain", outlier_method="none")
    )
    comm_rec = [r for r in res.metrics.records if r["phase"] == "communities"][0]
    assert comm_rec["modularity"] > 0.5  # Louvain >> LPA's ~0.05 on this data
    assert 0 < res.num_communities < 1000


def test_weighted_edgelist_pipeline(tmp_path):
    """r2: --data-format edgelist --edge-weight-col N runs weighted LPA
    end-to-end through the pipeline, and the weights change the result."""
    p = tmp_path / "w.txt"
    # two triangles bridged by one edge; the bridge weight decides whether
    # the communities merge under LPA's weighted mode
    lines = ["a b 4", "b c 4", "c a 4", "x y 4", "y z 4", "z x 4", "a x 0.5"]
    p.write_text("\n".join(lines) + "\n")
    cfg = PipelineConfig(
        data_path=str(p), data_format="edgelist", edge_weight_col=2,
        outlier_method="none", num_devices=1,
    )
    res = run_pipeline(cfg)
    assert res.num_communities >= 2  # weak bridge: triangles stay apart
    assert res.edge_table.weights is not None

    with pytest.raises(ValueError, match="edgelist"):
        PipelineConfig(edge_weight_col=2).validate()  # parquet default
    with pytest.raises(ValueError, match="unweighted"):
        PipelineConfig(
            data_format="edgelist", edge_weight_col=2, backend="graphframes"
        ).validate()

    # a weighted run's checkpoint is not interchangeable with an
    # unweighted run over the same topology
    from graphmine_tpu.pipeline.checkpoint import graph_fingerprint

    et = res.edge_table
    assert graph_fingerprint(et.src, et.dst, et.weights) != graph_fingerprint(
        et.src, et.dst
    )


def test_kitchen_sink_weighted_ring_checkpoint(tmp_path):
    """Integration: every r2 feature in one run — weighted edge list, ring
    schedule on 8 devices, checkpoint mid-run + resume, both outlier
    methods — and the resumed result matches an uninterrupted run."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    rng = np.random.default_rng(4)
    v, e = 120, 900
    src = rng.integers(0, v, e)
    dst = rng.integers(0, v, e)
    w = rng.integers(1, 8, e) / 2.0
    p = tmp_path / "wg.txt"
    p.write_text("".join(f"n{s} n{d} {x}\n" for s, d, x in zip(src, dst, w)))

    def cfg(**kw):
        base = dict(
            data_path=str(p), data_format="edgelist", edge_weight_col=2,
            # lof_k=15 == ceil(V/8): the LARGEST k that still routes
            # through the ring-sharded LOF path (asserted below)
            num_devices=8, schedule="ring", max_iter=4, lof_k=15,
        )
        base.update(kw)
        return PipelineConfig(**base)

    full = run_pipeline(cfg(outlier_method="both"))
    assert full.lof is not None and full.outliers is not None
    lof_rec = [r for r in full.metrics.records if r["phase"] == "outliers_lof"]
    assert lof_rec and lof_rec[0]["devices"] == 8  # sharded path taken

    # interrupt at iteration 2, then resume to 4
    ck = str(tmp_path / "ck")
    run_pipeline(cfg(outlier_method="none", max_iter=2, checkpoint_dir=ck))
    resumed = run_pipeline(
        cfg(outlier_method="none", checkpoint_dir=ck, resume=True)
    )
    np.testing.assert_array_equal(resumed.labels, full.labels)
    resume_events = [r for r in resumed.metrics.records if r["phase"] == "resume"]
    assert resume_events and resume_events[0]["iteration"] == 2


def _write_random_edgelist(tmp_path, v=800, e=6000, seed=0):
    rng = np.random.default_rng(seed)
    src, dst = rng.integers(0, v, e), rng.integers(0, v, e)
    p = tmp_path / "edges.txt"
    p.write_text("".join(f"n{s} n{d}\n" for s, d in zip(src, dst)))
    return str(p)


def test_lof_auto_policy_deploys_through_driver(tmp_path, monkeypatch):
    """r6 acceptance: the e2e pipeline deploys IVF planner/driver-selected,
    not via an opt-in string — lof_impl stays 'auto', only the measured
    crossover (lowered via its env override to run at test scale) decides.
    Both directions pinned, with the impl_selected record through the
    metrics sink and the degradation ladder built the matching way."""
    p = _write_random_edgelist(tmp_path)

    def cfg():
        return PipelineConfig(
            data_path=p, data_format="edgelist", outlier_method="lof",
            num_devices=1, lof_k=32,
        )

    res = run_pipeline(cfg())
    # the driver now also records the LPA superstep-family selection
    # (r7, op="lpa_superstep"); the LOF assertion keys on its op
    sel = [
        r for r in res.metrics.records
        if r["phase"] == "impl_selected" and r["op"] == "lof_knn"
    ]
    assert sel and sel[0]["impl"] == "exact" and sel[0]["requested"] == "auto"
    assert res.lof is not None and res.lof.shape == (800,)

    monkeypatch.setenv("GRAPHMINE_LOF_IVF_MIN_N", "500")
    res2 = run_pipeline(cfg())
    sel2 = [
        r for r in res2.metrics.records
        if r["phase"] == "impl_selected" and r["op"] == "lof_knn"
    ]
    assert sel2 and sel2[0]["impl"] == "ivf"
    assert res2.lof is not None
    # approximate scores track the exact run
    close = np.abs(res2.lof - res.lof) < 0.05 * np.abs(res.lof) + 0.01
    assert close.mean() > 0.95


def test_lof_ivf_degrades_to_exact_rung(tmp_path, monkeypatch):
    """The IVF→exact degradation rung (r6): when the planner-selected IVF
    scorer dies with a resource-exhaustion error, the ladder steps to the
    exact path and the phase still completes, with the degrade record
    naming the lof_exact rung."""
    from graphmine_tpu.testing.faults import FaultInjector, oom_error

    p = _write_random_edgelist(tmp_path, seed=1)
    monkeypatch.setenv("GRAPHMINE_LOF_IVF_MIN_N", "500")
    inj = FaultInjector().add("outliers_lof", oom_error, at=1)
    with inj.installed():
        res = run_pipeline(PipelineConfig(
            data_path=p, data_format="edgelist", outlier_method="lof",
            num_devices=1, lof_k=32,
        ))
    assert inj.fired("outliers_lof") == 1
    assert res.lof is not None
    deg = [r for r in res.metrics.records if r["phase"] == "degrade"]
    assert deg and deg[0]["to"] == "lof_exact"
    # the rung's scorer records the exact path it actually ran
    sel = [r for r in res.metrics.records if r["phase"] == "impl_selected"]
    assert sel and sel[-1]["impl"] == "exact" and sel[-1]["requested"] == "xla"


def test_config_validation():
    with pytest.raises(ValueError):
        PipelineConfig(backend="spark").validate()
    with pytest.raises(ValueError):
        PipelineConfig(decile=1.5).validate()
    with pytest.raises(ValueError):
        PipelineConfig(data_format="csv").validate()


def test_cli_parsing():
    cfg = parse_args(["--max-iter", "7", "--backend", "jax", "--outlier-method", "lof"])
    assert cfg.max_iter == 7 and cfg.outlier_method == "lof"


def test_graphframes_backend_gated(bundled_edges):
    from graphmine_tpu.pipeline.backends import GraphFramesUnavailable, lpa_graphframes

    try:
        import pyspark  # noqa: F401

        pytest.skip("pyspark installed; gate not testable")
    except ImportError:
        pass
    with pytest.raises(GraphFramesUnavailable, match="backend='jax'"):
        lpa_graphframes(bundled_edges, 5)


def test_graphframes_bridge_edge_cap():
    """The legacy bridge refuses graphs that would OOM its driver-side row
    lists (the reference's own cliff, Graphframes.py:100-118) — before
    touching pyspark, so the guard holds in any environment."""
    from graphmine_tpu.io.edges import from_arrays
    from graphmine_tpu.pipeline.backends import MAX_BRIDGE_EDGES, lpa_graphframes

    n = MAX_BRIDGE_EDGES + 1
    big = from_arrays(np.zeros(n, np.int32), np.ones(n, np.int32))
    with pytest.raises(ValueError, match="capped"):
        lpa_graphframes(big, 5)


def test_orbax_checkpoint_roundtrip(tmp_path):
    from graphmine_tpu.pipeline.checkpoint import load_sharded, save_sharded

    save_sharded(str(tmp_path), np.arange(16, dtype=np.int32), 7)
    out = load_sharded(str(tmp_path))
    assert out is not None
    labels, it = out
    np.testing.assert_array_equal(np.asarray(labels), np.arange(16))
    assert it == 7
    assert load_sharded(str(tmp_path), tag="missing") is None

    # The sharding-aware restore path: labels land device-resident with
    # the requested placement, no host bounce.
    import jax
    from jax.sharding import SingleDeviceSharding

    sharding = SingleDeviceSharding(jax.devices()[0])
    labels, it = load_sharded(str(tmp_path), sharding=sharding)
    assert it == 7
    assert labels.sharding == sharding
    np.testing.assert_array_equal(np.asarray(labels), np.arange(16))


def test_checkpoint_fingerprint_guards_resume(tmp_path):
    """A checkpoint written for one graph/id-assignment must refuse to
    resume another (e.g. bulk vs batch_rows ingestion permute vertex ids)."""
    from graphmine_tpu.pipeline.checkpoint import (
        graph_fingerprint,
        load_labels,
        save_labels,
    )

    src = np.array([0, 1, 2], np.int32)
    dst = np.array([1, 2, 0], np.int32)
    fp = graph_fingerprint(src, dst)
    save_labels(str(tmp_path), np.arange(3, dtype=np.int32), 2, fingerprint=fp)

    labels, it = load_labels(str(tmp_path), fingerprint=fp)
    assert it == 2

    fp_other = graph_fingerprint(dst, src)  # permuted id roles
    assert fp_other != fp
    with pytest.raises(ValueError, match="different graph"):
        load_labels(str(tmp_path), fingerprint=fp_other)

    # legacy checkpoints (no fingerprint recorded) still load
    save_labels(str(tmp_path), np.arange(3, dtype=np.int32), 1, tag="old")
    assert load_labels(str(tmp_path), tag="old", fingerprint=fp)[1] == 1


def test_spark_crosscheck_skips_cleanly_without_pyspark():
    """tools/spark_crosscheck.py (r3): in this no-JVM sandbox it must exit
    3 with a parseable skip record; in a pyspark+graphframes environment it
    runs the real JVM labelPropagation through backends.lpa_graphframes and
    asserts canonical-partition agreement within the tie envelope."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    p = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "spark_crosscheck.py")],
        capture_output=True, text=True, timeout=600,
    )
    try:
        import graphframes  # noqa: F401
        import pyspark  # noqa: F401

        have_spark = True
    except ImportError:
        have_spark = False
    have_data = os.path.exists(
        "/root/reference/CommunityDetection/data/outlinks_pq"
    )
    # returncode first: a crash must surface the captured output, not an
    # IndexError/JSONDecodeError from parsing empty stdout
    assert p.returncode in (0, 3), p.stdout + p.stderr
    rec = json.loads(p.stdout.strip().splitlines()[-1])
    if have_spark and have_data:
        assert p.returncode == 0, p.stdout + p.stderr
        assert rec["crosscheck"] == "agree"
    elif not have_spark:
        assert p.returncode == 3, p.stdout + p.stderr
        assert rec["crosscheck"] == "skipped" and "pyspark" in rec["reason"]
    else:  # spark present, default data absent: clean skip, not a failure
        assert p.returncode == 3, p.stdout + p.stderr
        assert rec["crosscheck"] == "skipped" and "data not found" in rec["reason"]


@needs_data
def test_crosscheck_envelope_criterion_validated_without_jvm(bundled_edges):
    """VERDICT r3 item 8: the tie-envelope pass criterion itself, tested
    in both directions with no JVM. A simulated legitimate JVM — the
    GraphX-structure oracle under a seeded random-among-modes tie rule,
    i.e. an arbitrary machine-dependent tie order — must be ACCEPTED
    across seeds; a deliberately broken engine (the same labels with the
    vertex->label mapping shuffled) must be REJECTED."""
    import os
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    from tools.spark_crosscheck import evaluate_crosscheck

    from graphmine_tpu.graph.container import build_graph
    from graphmine_tpu.ops.lpa import canonicalize, label_propagation
    from graphmine_tpu.oracle import graphx_label_propagation

    et = bundled_edges
    g = build_graph(et.src, et.dst, num_vertices=et.num_vertices)
    eng = np.asarray(canonicalize(label_propagation(g, max_iter=5)))

    for seed in (0, 1, 2):
        sim_jvm = graphx_label_propagation(
            et.src, et.dst, et.num_vertices, max_iter=5,
            tie="random", seed=seed,
        )
        ok, fields = evaluate_crosscheck(
            sim_jvm, eng, et.src, et.dst, et.num_vertices, 5
        )
        assert ok, (seed, fields)
        # the envelope is doing real work here (not vacuously 1.0 ... and
        # not so loose it means nothing)
        assert fields["tie_envelope_ari"] < 0.999
        assert fields["ari_jvm_vs_engine"] >= fields["tie_envelope_ari"]

    # broken engine: same partition sizes, vertex->label map shuffled
    rng = np.random.default_rng(7)
    perm = rng.permutation(et.num_vertices)
    broken = eng[perm]
    ok, fields = evaluate_crosscheck(
        sim_jvm, broken, et.src, et.dst, et.num_vertices, 5
    )
    assert not ok, fields
    assert fields["ari_jvm_vs_engine"] < fields["tie_envelope_ari"]
