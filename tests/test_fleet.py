"""Replicated serving fleet suite (marker ``fleet``):
tools/run_tier1.sh --fleet-only.

The acceptance pins (ISSUE 9):

- per-replica circuit breakers: error/timeout-rate threshold opens,
  decorrelated-jitter backoff, half-open single-probe recovery — every
  transition a ``breaker_transition`` record;
- committed-version routing: reads route ONLY to replicas at the max
  version held by a read quorum (monotonic), every response echoes
  ``X-Pinned-Version``, and a replica that swapped mid-flight answers
  409 to the router's pin so one client session never observes mixed
  versions;
- single-writer forwarding: writer loss flips the fleet READ-ONLY with
  a loud ``fleet_degraded`` record (no failover, no split-brain);
- zero-downtime rolling reload: drain → /reload → re-probe → rejoin one
  replica at a time, aborting below ``min_healthy``;
- THE chaos test: a 3-replica fleet under a live read hammer survives
  ``replica_kill``, ``replica_slow`` (breaker open→half-open→close,
  router p99 bounded) and a full rolling reload with ZERO failed reads
  and ZERO mixed-version responses;
- the /reload-vs-inflight-delta race on a single server: a delta racing
  an unseen external publish REBASES onto it instead of clobbering it
  (the contract the fleet prober's reload cadence leans on);
- serve_cli client-side resilience: bounded decorrelated-jitter retries
  honoring Retry-After, ``--deadline-ms`` → ``X-Deadline-Ms``.
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from graphmine_tpu.graph.container import build_graph
from graphmine_tpu.obs.schema import validate_records
from graphmine_tpu.obs.spans import Tracer
from graphmine_tpu.pipeline.checkpoint import graph_fingerprint
from graphmine_tpu.pipeline.metrics import MetricsSink
from graphmine_tpu.serve import (
    DeltaIngestor,
    EdgeDelta,
    SnapshotStore,
)
from graphmine_tpu.serve.delta import cold_recompute
from graphmine_tpu.serve.fleet import (
    BREAKER_CLOSED,
    BREAKER_HALF_OPEN,
    BREAKER_OPEN,
    DEGRADED,
    DOWN,
    DRAINING,
    HEALTHY,
    JOINING,
    CircuitBreaker,
    FleetConfig,
    FleetRouter,
    ReplicaSet,
    ReplicaSpec,
)
from graphmine_tpu.serve.server import SnapshotServer
from graphmine_tpu.testing import faults

pytestmark = pytest.mark.fleet


# ---- fixtures -------------------------------------------------------------


def _clique(lo, hi):
    ids = np.arange(lo, hi)
    s, d = np.meshgrid(ids, ids)
    m = s.ravel() < d.ravel()
    return s.ravel()[m], d.ravel()[m]


def _community_graph():
    parts = [_clique(0, 12), _clique(12, 26), _clique(26, 40)]
    src = np.concatenate([p[0] for p in parts]).astype(np.int32)
    dst = np.concatenate([p[1] for p in parts]).astype(np.int32)
    return src, dst, 40


def _sink():
    return MetricsSink(tracer=Tracer())


def _publish_base(tmp_path, sink=None):
    src, dst, v = _community_graph()
    g = build_graph(src, dst, num_vertices=v)
    labels, cc, _ = cold_recompute(g)
    store = SnapshotStore(str(tmp_path / "snap"))
    store.publish(
        {
            "src": src, "dst": dst, "labels": labels, "cc_labels": cc,
            "lof": np.zeros(v, np.float32),
        },
        fingerprint=graph_fingerprint(src, dst),
        sink=sink,
    )
    return store, src, dst, v


def _post(host, port, path, payload, timeout=60, headers=None):
    req = urllib.request.Request(
        f"http://{host}:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def _get(host, port, path, timeout=30):
    with urllib.request.urlopen(
        f"http://{host}:{port}{path}", timeout=timeout
    ) as r:
        return json.loads(r.read())


def _fast_config(**overrides):
    """A CPU-test FleetConfig: tight probe cadence, short data-plane
    timeout, quick breaker backoff — everything the chaos clock needs
    to converge in seconds instead of minutes."""
    kv = dict(
        probe_interval_s=0.08,
        probe_timeout_s=4.0,
        read_timeout_s=0.4,
        down_after_probes=2,
        reload_cadence_s=0.1,
        rejoin_timeout_s=15.0,
        breaker_window=6,
        breaker_open_failures=3,
        breaker_open_rate=0.5,
        breaker_backoff_base_s=0.3,
        breaker_backoff_max_s=1.0,
        retry_after_s=1.0,
        default_deadline_ms=5000,
    )
    kv.update(overrides)
    return FleetConfig(**kv)


class _Fleet:
    """One in-process 3-replica fleet + router, for the HTTP tests.
    Each replica is a real SnapshotServer on its own port — the router
    genuinely speaks HTTP to them."""

    def __init__(self, store, n=3, config=None, sink=None,
                 start_prober=True):
        self.store = store
        self.sink = sink
        self.servers = [SnapshotServer(store) for _ in range(n)]
        self.addrs = [s.start() for s in self.servers]
        self.specs = [
            ReplicaSpec(f"r{i}", h, p) for i, (h, p) in enumerate(self.addrs)
        ]
        self.config = config if config is not None else _fast_config()
        self.router = FleetRouter(
            self.specs, writer="r0", sink=sink, config=self.config,
        )
        if start_prober:
            self.host, self.port = self.router.start()
        else:
            # no HTTP router / prober thread: tests drive probe_once()
            self.host = self.port = None

    def wait_committed(self, version=None, timeout=10.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            c = self.router.replica_set.committed_version()
            if c is not None and (version is None or c >= version):
                return c
            time.sleep(0.02)
        raise AssertionError(
            f"fleet never committed "
            f"{'any version' if version is None else f'v{version}'} "
            f"(state: {self.router.replica_set.snapshot()})"
        )

    def restart_replica(self, i):
        """'Restart the process': a fresh SnapshotServer on the same
        port (the spec's address is the replica's identity). The bind
        retries briefly — under a full-suite run another socket can
        transiently hold the freed ephemeral port (an outgoing
        connection's tuple in TIME_WAIT), exactly like a real restart
        racing the OS."""
        host, port = self.addrs[i]
        self.servers[i] = SnapshotServer(self.store, host=host, port=port)
        deadline = time.monotonic() + 10.0
        while True:
            try:
                self.servers[i].start()
                break
            except OSError:
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.2)
        return self.servers[i]

    def stop(self):
        self.router.stop()
        for s in self.servers:
            try:
                s.stop()
            except Exception:  # noqa: BLE001 — killed replicas
                pass


# ---- circuit breaker unit -------------------------------------------------


def test_breaker_open_half_open_close():
    """The full episode: rate threshold opens, backoff gates the
    half-open probe, one clean probe closes — every transition fired."""
    from graphmine_tpu.pipeline.resilience import ResilienceConfig

    now = [100.0]
    seen = []
    b = CircuitBreaker(
        "r1", window=6, open_failures=3, open_rate=0.5,
        backoff=ResilienceConfig(backoff_base_s=0.5, backoff_max_s=4.0),
        on_transition=lambda f, t, r: seen.append((f, t, r)),
        clock=lambda: now[0],
    )
    assert b.allow_request() and b.state == BREAKER_CLOSED
    b.record_failure("timeout 1")
    b.record_failure("timeout 2")
    assert b.state == BREAKER_CLOSED  # below the count threshold
    b.record_failure("timeout 3")
    assert b.state == BREAKER_OPEN and not b.allow_request()
    assert seen[-1][0] == BREAKER_CLOSED and seen[-1][1] == BREAKER_OPEN
    assert "3 failures" in seen[-1][2]
    # not due until the backoff elapses
    assert not b.probe_due()
    now[0] += 10.0
    assert b.probe_due()
    assert b.state == BREAKER_HALF_OPEN and not b.allow_request()
    assert not b.probe_due()  # one probe granted per episode
    # failed probe -> re-open with a LONGER backoff (attempt 2)
    b.probe_result(False, "still slow")
    assert b.state == BREAKER_OPEN
    snap = b.snapshot()
    assert snap["open_episodes"] == 2
    now[0] += 10.0
    assert b.probe_due()
    b.probe_result(True, "answered fast")
    assert b.state == BREAKER_CLOSED and b.allow_request()
    # escalation memory: a probe-close DECAYS the episode counter (2->1)
    # rather than zeroing it, so a flapping replica re-opens with a
    # longer backoff; only a full clean window resets it
    assert b.snapshot()["open_episodes"] == 1
    for _ in range(6):  # window=6 of straight successes
        b.record_success()
    assert b.snapshot()["open_episodes"] == 0
    states = [(f, t) for f, t, _ in seen]
    assert states == [
        (BREAKER_CLOSED, BREAKER_OPEN),
        (BREAKER_OPEN, BREAKER_HALF_OPEN),
        (BREAKER_HALF_OPEN, BREAKER_OPEN),
        (BREAKER_OPEN, BREAKER_HALF_OPEN),
        (BREAKER_HALF_OPEN, BREAKER_CLOSED),
    ]


def test_breaker_rate_threshold_needs_rate_and_count():
    """Interleaved successes keep the failure RATE below the bar: no
    open, even past the absolute failure count."""
    b = CircuitBreaker("r1", window=8, open_failures=3, open_rate=0.5)
    for _ in range(3):
        b.record_success()
        b.record_success()
        b.record_failure("blip")
    assert b.state == BREAKER_CLOSED  # 3 failures but rate 3/8 < 0.5


# ---- committed version / quorum -------------------------------------------


def _manual_set(versions_states, writer="a"):
    specs = [ReplicaSpec(chr(ord("a") + i), "h", i) for i in
             range(len(versions_states))]
    rs = ReplicaSet(specs, writer=writer, config=_fast_config())
    for spec, (version, state) in zip(specs, versions_states):
        rep = rs.replica(spec.id)
        rep.version = version
        rep.state = state
    rs._recompute()
    return rs


def test_committed_version_is_quorum_max_and_monotonic():
    """Committed = max version held by a read quorum; DOWN replicas
    hold nothing; quorum loss never rolls it backwards."""
    rs = _manual_set([(1, HEALTHY), (1, HEALTHY), (1, HEALTHY)])
    assert rs.quorum == 2 and rs.committed_version() == 1
    # one replica ahead: quorum still at 1
    rs = _manual_set([(2, HEALTHY), (1, HEALTHY), (1, HEALTHY)])
    assert rs.committed_version() == 1
    # two ahead: committed advances
    rs = _manual_set([(2, HEALTHY), (2, HEALTHY), (1, HEALTHY)])
    assert rs.committed_version() == 2
    # a DOWN replica's version doesn't count toward quorum
    rs = _manual_set([(2, HEALTHY), (2, DOWN), (1, HEALTHY)])
    assert rs.committed_version() == 1
    # monotonic: losing quorum keeps the last committed (unavailable-
    # consistent), never time-travels
    rs = _manual_set([(2, HEALTHY), (2, HEALTHY), (1, HEALTHY)])
    assert rs.committed_version() == 2
    rs.replica("a").state = DOWN
    rs.replica("b").state = DOWN
    rs._recompute()
    assert rs.committed_version() == 2
    # and pick() finds nothing at v2 -> the router 503s rather than
    # serving v1 to a session that has seen v2
    assert rs.pick(2) is None


def test_pick_prefers_healthy_skips_breakers_and_wrong_versions():
    rs = _manual_set([(1, HEALTHY), (1, DEGRADED), (2, HEALTHY)])
    picks = {rs.pick(1).spec.id for _ in range(8)}
    assert picks == {"a"}  # healthy preferred over degraded; c is at v2
    # exclude the healthy one -> the degraded replica is the fallback
    assert rs.pick(1, exclude=("a",)).spec.id == "b"
    # an open breaker removes eligibility entirely
    for _ in range(6):
        rs.replica("a").breaker.record_failure("x")
    assert rs.replica("a").breaker.state == BREAKER_OPEN
    assert rs.pick(1).spec.id == "b"


# ---- router HTTP: consistent-version routing ------------------------------


def test_router_consistent_version_routing_and_pin_echo(tmp_path):
    """Reads serve exactly the committed version with an
    X-Pinned-Version echo; committed advances only when a quorum holds
    the new version; a session pinned AHEAD of the fleet is refused
    rather than handed an older version."""
    sink = _sink()
    store, *_ = _publish_base(tmp_path)
    fleet = _Fleet(store, sink=sink)
    try:
        assert fleet.wait_committed() == 1
        code, body, headers = _post(
            fleet.host, fleet.port, "/query", {"vertices": [0, 13, 27]}
        )
        assert code == 200 and body["version"] == 1
        assert headers["X-Pinned-Version"] == "1"
        assert headers["X-Fleet-Replica"] in {"r0", "r1", "r2"}

        # external publish v2 + ONE replica reloads: quorum still at v1
        ext = DeltaIngestor(store, lof_k=4, check_samples=8)
        ext.apply(EdgeDelta.from_pairs(insert=[(40, 12), (40, 13)]))
        h1, p1 = fleet.addrs[1]
        assert _post(h1, p1, "/reload", {})[1]["swapped"] is True
        time.sleep(0.3)  # several probe passes
        assert fleet.router.replica_set.committed_version() == 1
        for _ in range(6):
            code, body, headers = _post(
                fleet.host, fleet.port, "/query", {"vertices": [0]}
            )
            assert code == 200
            assert body["version"] == 1 == int(headers["X-Pinned-Version"])

        # second replica reloads -> quorum at v2 -> committed advances
        h2, p2 = fleet.addrs[2]
        _post(h2, p2, "/reload", {})
        fleet.wait_committed(2)
        code, body, headers = _post(
            fleet.host, fleet.port, "/query", {"vertices": [40]}
        )
        assert code == 200
        assert body["version"] == 2 == int(headers["X-Pinned-Version"])
        # a stale session pin (<= committed) is fine: monotonic reads
        code, body, _ = _post(
            fleet.host, fleet.port, "/query", {"vertices": [0]},
            headers={"X-Pinned-Version": "1"},
        )
        assert code == 200 and body["version"] == 2
        # a pin AHEAD of the fleet is refused, never downgraded
        code, body, headers = _post(
            fleet.host, fleet.port, "/query", {"vertices": [0]},
            headers={"X-Pinned-Version": "9"},
        )
        assert code == 503 and "pinned v9" in body["reason"]
        assert int(headers["Retry-After"]) >= 1
    finally:
        fleet.stop()
    assert validate_records(sink.records) == []
    served = [
        r for r in sink.records
        if r["phase"] == "fleet_route" and r["verdict"] == "served"
    ]
    assert served and all(r["attempts"] >= 1 for r in served)
    assert any(
        r["phase"] == "fleet_route" and r["verdict"] == "stale_pin"
        for r in sink.records
    )


def test_replica_version_pin_409_on_mismatch(tmp_path):
    """The replica side of the mixed-version guard: an X-Serve-Version
    pin that doesn't match the engine answers 409 (and a matching one
    serves normally)."""
    store, *_ = _publish_base(tmp_path)
    server = SnapshotServer(store)
    host, port = server.start()
    try:
        code, body, _ = _post(
            host, port, "/query", {"vertices": [0]},
            headers={"X-Serve-Version": "1"},
        )
        assert code == 200 and body["version"] == 1
        code, body, _ = _post(
            host, port, "/query", {"vertices": [0]},
            headers={"X-Serve-Version": "7"},
        )
        assert code == 409
        assert body["version"] == 1 and body["requested"] == 7
        assert _get(host, port, "/vertex?v=0")["vertex"] == 0  # unpinned ok
    finally:
        server.stop()


def test_router_retries_onto_live_replica_and_503_when_none(tmp_path):
    """A dead replica mid-rotation costs a retry, not a failed read;
    with every replica dead the router answers 503 + Retry-After inside
    the propagated deadline."""
    sink = _sink()
    store, *_ = _publish_base(tmp_path)
    fleet = _Fleet(store, sink=sink)
    try:
        fleet.wait_committed()
        faults.replica_kill(fleet.servers[2])
        # before the prober can mark it DOWN, reads must still succeed
        # (the router eats the connection error and retries elsewhere)
        for _ in range(6):
            code, body, _ = _post(
                fleet.host, fleet.port, "/query", {"vertices": [0]}
            )
            assert code == 200 and body["version"] == 1
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if fleet.router.replica_set.replica("r2").state == DOWN:
                break
            time.sleep(0.05)
        assert fleet.router.replica_set.replica("r2").state == DOWN

        faults.replica_kill(fleet.servers[0])
        faults.replica_kill(fleet.servers[1])
        t0 = time.monotonic()
        code, body, headers = _post(
            fleet.host, fleet.port, "/query", {"vertices": [0]},
            headers={"X-Deadline-Ms": "800"},
        )
        elapsed = time.monotonic() - t0
        assert code == 503 and "no eligible replica" in body["reason"]
        assert int(headers["Retry-After"]) >= 1
        assert elapsed < 3.0  # bounded by the deadline, not by timeouts
    finally:
        fleet.stop()
    assert validate_records(sink.records) == []
    assert any(
        r["phase"] == "fleet_route" and r["verdict"] == "no_replica"
        for r in sink.records
    )


def test_stale_replica_never_serves_reads(tmp_path):
    """replica_stale: a version-pinned replica falls behind the fleet
    and silently leaves the read rotation — zero mixed-version answers,
    no error surfaced to readers."""
    store, *_ = _publish_base(tmp_path)
    fleet = _Fleet(store)
    try:
        fleet.wait_committed()
        faults.replica_stale(fleet.servers[2])
        ext = DeltaIngestor(store, lof_k=4, check_samples=8)
        ext.apply(EdgeDelta.from_pairs(insert=[(40, 12)]))
        # roll the other two via their own /reload (writer + r1)
        for i in (0, 1):
            h, p = fleet.addrs[i]
            _post(h, p, "/reload", {})
        fleet.wait_committed(2)
        for _ in range(10):
            code, body, headers = _post(
                fleet.host, fleet.port, "/query", {"vertices": [0]}
            )
            assert code == 200
            assert body["version"] == 2 == int(headers["X-Pinned-Version"])
            assert headers["X-Fleet-Replica"] in {"r0", "r1"}
        assert fleet.servers[2].engine.version == 1  # genuinely stale
    finally:
        fleet.stop()


def test_self_drained_replica_leaves_read_rotation(tmp_path):
    """A replica drained at ITS OWN /drain endpoint (ready: false,
    draining: true) must receive no reads — the prober honors the
    operator's drain instead of demoting it to a still-routable
    degraded state — and rejoins after /undrain."""
    store, *_ = _publish_base(tmp_path)
    fleet = _Fleet(store)

    def wait_state(rid, state, timeout=8.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if fleet.router.replica_set.replica(rid).state == state:
                return
            time.sleep(0.03)
        raise AssertionError(
            f"{rid} never reached {state}: "
            f"{fleet.router.replica_set.snapshot()}"
        )

    try:
        fleet.wait_committed()
        h2, p2 = fleet.addrs[2]
        _post(h2, p2, "/drain", {})
        wait_state("r2", DRAINING)
        for _ in range(8):
            code, body, headers = _post(
                fleet.host, fleet.port, "/query", {"vertices": [0]}
            )
            assert code == 200
            assert headers["X-Fleet-Replica"] in {"r0", "r1"}
        _post(h2, p2, "/undrain", {})
        wait_state("r2", HEALTHY)
    finally:
        fleet.stop()


# ---- writer forwarding / read-only ----------------------------------------


def test_writer_forwarding_and_prober_reload_cadence(tmp_path):
    """POST /delta through the router lands on the writer; the prober's
    reload cadence walks the other replicas up to the writer's version
    and committed follows — no client ever sees a mixed version on the
    way."""
    sink = _sink()
    store, *_ = _publish_base(tmp_path)
    fleet = _Fleet(store, sink=sink)
    try:
        fleet.wait_committed()
        code, body, headers = _post(
            fleet.host, fleet.port, "/delta",
            {"insert": [[0, 13], [0, 14]]},
        )
        assert code == 200 and body["version"] == 2
        assert headers["X-Fleet-Replica"] == "r0"
        assert fleet.servers[0].engine.version == 2
        fleet.wait_committed(2)  # the cadence reloaded r1/r2
        assert fleet.servers[1].engine.version == 2
        assert fleet.servers[2].engine.version == 2
        code, body, _ = _post(
            fleet.host, fleet.port, "/query", {"vertices": [0]}
        )
        assert code == 200 and body["version"] == 2
    finally:
        fleet.stop()
    assert validate_records(sink.records) == []
    fwd = [
        r for r in sink.records
        if r["phase"] == "fleet_route" and r["verdict"] == "forwarded"
    ]
    assert fwd and fwd[0]["endpoint"] == "delta"


def test_writer_loss_degrades_to_read_only_and_recovers(tmp_path):
    """Writer down → loud fleet_degraded record, writes 503, reads keep
    serving; the SAME writer returning restores writes (no election)."""
    sink = _sink()
    store, *_ = _publish_base(tmp_path)
    fleet = _Fleet(store, sink=sink)
    try:
        fleet.wait_committed()
        faults.replica_kill(fleet.servers[0])
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not fleet.router.replica_set.read_only:
            time.sleep(0.05)
        assert fleet.router.replica_set.read_only
        code, body, headers = _post(
            fleet.host, fleet.port, "/delta", {"insert": [[0, 13]]}
        )
        assert code == 503 and "read-only" in body["reason"]
        assert int(headers["Retry-After"]) >= 1
        # reads still fine at the committed version
        code, body, _ = _post(
            fleet.host, fleet.port, "/query", {"vertices": [0]}
        )
        assert code == 200 and body["version"] == 1
        # router healthz says read_only; fleetz shows the writer down
        h = _get(fleet.host, fleet.port, "/healthz")
        assert h["read_only"] is True and h["ok"] is True
        fz = _get(fleet.host, fleet.port, "/fleetz")
        writer_row = next(r for r in fz["replicas"] if r["writer"])
        assert writer_row["state"] == DOWN

        fleet.restart_replica(0)
        deadline = time.monotonic() + 8
        while time.monotonic() < deadline and fleet.router.replica_set.read_only:
            time.sleep(0.05)
        assert not fleet.router.replica_set.read_only
        code, body, _ = _post(
            fleet.host, fleet.port, "/delta", {"insert": [[0, 15]]}
        )
        assert code == 200 and body["version"] == 2
    finally:
        fleet.stop()
    assert validate_records(sink.records) == []
    flips = [r for r in sink.records if r["phase"] == "fleet_degraded"]
    assert [r["read_only"] for r in flips] == [True, False]
    assert "split-brain" in flips[0]["reason"]


# ---- rolling reload -------------------------------------------------------


def test_rolling_reload_walks_fleet_to_new_version(tmp_path):
    """An external publish + /roll takes every replica (writer last)
    through drain → reload → rejoin; committed lands on the new
    version."""
    sink = _sink()
    store, *_ = _publish_base(tmp_path)
    fleet = _Fleet(store, sink=sink)
    try:
        fleet.wait_committed()
        ext = DeltaIngestor(store, lof_k=4, check_samples=8)
        ext.apply(EdgeDelta.from_pairs(insert=[(40, 12), (40, 13)]))
        code, out, _ = _post(fleet.host, fleet.port, "/roll", {})
        assert code == 200 and out["ok"], out
        assert [r["version"] for r in out["rolled"]] == [2, 2, 2]
        # writer rolls LAST
        assert out["rolled"][-1]["id"] == "r0"
        assert out["committed_version"] == 2
        for s in fleet.servers:
            assert s.engine.version == 2
        code, body, _ = _post(
            fleet.host, fleet.port, "/query", {"vertices": [40]}
        )
        assert code == 200 and body["version"] == 2
    finally:
        fleet.stop()
    assert validate_records(sink.records) == []
    # drain/rejoin transitions were recorded per replica
    health = [r for r in sink.records if r["phase"] == "replica_health"]
    assert sum(1 for r in health if r["to_state"] == DRAINING) == 3
    assert sum(
        1 for r in health
        if r["from_state"] == DRAINING and r["to_state"] == HEALTHY
    ) == 3


def test_rolling_reload_aborts_below_min_healthy(tmp_path):
    """With min_healthy == replica count, draining anyone would dip
    below the floor: the roll refuses up front and leaves every replica
    serving."""
    store, *_ = _publish_base(tmp_path)
    fleet = _Fleet(store, config=_fast_config(min_healthy=3))
    try:
        fleet.wait_committed()
        code, out, _ = _post(fleet.host, fleet.port, "/roll", {})
        assert code == 409 and not out["ok"]
        assert "min_healthy" in out["aborted"]
        assert out["rolled"] == []
        states = {
            r["id"]: r["state"]
            for r in fleet.router.fleetz()["replicas"]
        }
        assert set(states.values()) == {HEALTHY}
    finally:
        fleet.stop()


# ---- the /reload-vs-inflight-delta rebase (satellite) ---------------------


def test_delta_rebases_onto_unseen_external_publish(tmp_path):
    """The r7 contract pinned under the fleet prober's reload cadence:
    a delta whose apply races ahead of /reload must REBASE onto the
    store's newest (externally published) snapshot, not clobber it by
    chaining a version on top of the stale served state."""
    sink = _sink()
    store, src, dst, v = _publish_base(tmp_path, sink=sink)
    server = SnapshotServer(store, sink=sink)
    host, port = server.start()
    try:
        # external publish v2 lands; the server still serves v1 and no
        # /reload has fired (the prober hasn't gotten there yet)
        ext = DeltaIngestor(store, lof_k=4, check_samples=8)
        ext.apply(EdgeDelta.from_pairs(insert=[(v, 0), (v, 1)]))
        assert server.engine.version == 1
        # a delta arrives FIRST: its apply must rebase onto v2
        code, out, _ = _post(host, port, "/delta", {"insert": [[0, 13]]})
        assert code == 200 and out["version"] == 3
        eng = server.engine
        edges = set(
            zip(np.asarray(eng.snapshot["src"]).tolist(),
                np.asarray(eng.snapshot["dst"]).tolist())
        )
        assert (v, 0) in edges and (v, 1) in edges  # external kept
        assert (0, 13) in edges                     # delta applied
        assert _get(host, port, "/vertex?v=40")["label"] == 0
    finally:
        server.stop()
    assert validate_records(sink.records) == []


def test_reload_during_held_apply_then_queued_delta(tmp_path):
    """The interleaving the prober's cadence produces: a /reload lands
    while the apply worker is mid-publish with another batch queued
    behind it — nothing is lost, versions chain, and the queued batch
    builds on everything before it."""
    sink = _sink()
    store, src, dst, v = _publish_base(tmp_path, sink=sink)
    server = SnapshotServer(store, sink=sink)
    host, port = server.start()
    results, reloads = [], []
    inj = faults.FaultInjector()
    inj.add("delta_repair", faults.slow_repair(0.8), at=1, repeat=1)

    def fire(payload):
        results.append(_post(host, port, "/delta", payload))

    try:
        with inj.installed():
            t0 = threading.Thread(target=fire, args=({"insert": [[0, 13]]},))
            t0.start()
            time.sleep(0.25)  # batch A mid-apply, holding the lock
            t1 = threading.Thread(target=fire, args=({"insert": [[0, 14]]},))
            t1.start()
            time.sleep(0.1)   # batch B queued behind A
            # the prober-cadence reload, racing both
            reloads.append(_post(host, port, "/reload", {}))
            t0.join(timeout=60)
            t1.join(timeout=60)
        assert [r[0] for r in results] == [200, 200]
        versions = sorted(r[1]["version"] for r in results)
        assert versions == [2, 3]
        eng = server.engine
        assert eng.version == 3
        edges = set(
            zip(np.asarray(eng.snapshot["src"]).tolist(),
                np.asarray(eng.snapshot["dst"]).tolist())
        )
        assert (0, 13) in edges and (0, 14) in edges
        assert reloads[0][0] == 200
    finally:
        server.stop()
    assert validate_records(sink.records) == []


# ---- liveness vs readiness (satellite) ------------------------------------


def test_healthz_ready_vs_ok(tmp_path):
    """The liveness/readiness split: ok stays true (alive) while ready
    flips false on drain or a stale-beyond-bound snapshot."""
    store, *_ = _publish_base(tmp_path)
    server = SnapshotServer(store)
    host, port = server.start()
    try:
        h = _get(host, port, "/healthz")
        assert h["ok"] is True and h["ready"] is True
        assert h["draining"] is False
        code, h, _ = _post(host, port, "/drain", {})
        assert code == 200 and h["ready"] is False and h["ok"] is True
        assert h["not_ready_reason"] == "draining"
        code, h, _ = _post(host, port, "/undrain", {})
        assert h["ready"] is True
    finally:
        server.stop()


def test_healthz_ready_false_when_stale_beyond_bound(tmp_path):
    store, *_ = _publish_base(tmp_path)
    server = SnapshotServer(store, ready_max_age_s=1e-6)
    host, port = server.start()
    try:
        h = _get(host, port, "/healthz")
        assert h["ok"] is True and h["ready"] is False
        assert "snapshot_age" in h["not_ready_reason"]
    finally:
        server.stop()


def test_ready_max_age_env(tmp_path, monkeypatch):
    monkeypatch.setenv("GRAPHMINE_READY_MAX_AGE_S", "123.5")
    store, *_ = _publish_base(tmp_path)
    server = SnapshotServer(store)
    assert server.ready_max_age_s == 123.5
    monkeypatch.setenv("GRAPHMINE_READY_MAX_AGE_S", "soon")
    with pytest.raises(ValueError, match="GRAPHMINE_READY_MAX_AGE_S"):
        SnapshotServer(store)


def test_delta_deadline_header_narrows_budget(tmp_path):
    """X-Deadline-Ms end-to-end on a single server: a queued batch past
    the client's (smaller) budget sheds with the structured 503."""
    sink = _sink()
    store, *_ = _publish_base(tmp_path, sink=sink)
    server = SnapshotServer(store, sink=sink)
    host, port = server.start()
    inj = faults.FaultInjector()
    inj.add("delta_repair", faults.slow_repair(1.2), at=1, repeat=1)
    results = []

    def fire(payload, headers=None):
        results.append(
            _post(host, port, "/delta", payload, headers=headers)
        )

    try:
        with inj.installed():
            t0 = threading.Thread(target=fire, args=({"insert": [[0, 13]]},))
            t0.start()
            time.sleep(0.3)  # slow apply in flight
            t1 = threading.Thread(
                target=fire,
                args=({"insert": [[0, 14]]},),
                kwargs={"headers": {"X-Deadline-Ms": "400"}},
            )
            t1.start()
            t0.join(timeout=60)
            t1.join(timeout=60)
        codes = sorted(r[0] for r in results)
        assert codes == [200, 503]
        shed = next(r for r in results if r[0] == 503)
        assert "deadline 0.4s" in shed[1]["reason"]
    finally:
        server.stop()
    assert validate_records(sink.records) == []


# ---- serve_cli client-side resilience (satellite) -------------------------


class _FlakyHandler(BaseHTTPRequestHandler):
    """Stub server: sheds the first N POSTs with 503 + Retry-After,
    then answers 200 — recording every request's X-Deadline-Ms."""

    sheds_left = 0
    retry_after = "1"
    seen_deadlines: list = []

    def log_message(self, fmt, *args):  # noqa: A003
        pass

    def do_POST(self):  # noqa: N802
        self.rfile.read(int(self.headers.get("Content-Length", 0)))
        type(self).seen_deadlines.append(
            self.headers.get("X-Deadline-Ms")
        )
        if type(self).sheds_left > 0:
            type(self).sheds_left -= 1
            body = json.dumps({"verdict": "shed", "reason": "test"}).encode()
            self.send_response(503)
            self.send_header("Retry-After", type(self).retry_after)
        else:
            body = json.dumps({"version": 2}).encode()
            self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


def _stub_server(sheds, retry_after="1"):
    class H(_FlakyHandler):
        sheds_left = sheds
        seen_deadlines = []
    H.retry_after = retry_after
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    host, port = httpd.server_address[:2]
    return httpd, H, f"http://{host}:{port}"


def test_serve_cli_retries_honor_retry_after():
    import sys
    sys.path.insert(
        0, os.path.join(os.path.dirname(__file__), "..", "tools")
    )
    import serve_cli

    httpd, H, url = _stub_server(sheds=2, retry_after="3")
    slept = []
    try:
        out = serve_cli.request_with_retries(
            f"{url}/delta", {"insert": [[1, 2]]}, max_retries=4,
            sleep=slept.append,
        )
    finally:
        httpd.shutdown()
        httpd.server_close()
    assert out["status"] == 200 and out["attempts"] == 3
    assert out["body"]["version"] == 2
    # every backoff obeyed the server's Retry-After floor
    assert len(slept) == 2 and all(s >= 3.0 for s in slept)


def test_serve_cli_deadline_bounds_retries_and_propagates():
    import sys
    sys.path.insert(
        0, os.path.join(os.path.dirname(__file__), "..", "tools")
    )
    import serve_cli

    httpd, H, url = _stub_server(sheds=100, retry_after="1")

    def sleeper(s):
        time.sleep(min(s, 0.2))

    try:
        t0 = time.monotonic()
        out = serve_cli.request_with_retries(
            f"{url}/delta", {"insert": [[1, 2]]}, deadline_ms=600,
            max_retries=50, sleep=sleeper,
        )
        elapsed = time.monotonic() - t0
    finally:
        httpd.shutdown()
        httpd.server_close()
    assert out["status"] == 503
    assert elapsed < 5.0  # the deadline stopped the retry loop
    # the budget rode every attempt, shrinking
    deadlines = [int(d) for d in H.seen_deadlines if d]
    assert deadlines and deadlines == sorted(deadlines, reverse=True)
    assert deadlines[0] <= 600


def test_serve_cli_exhausts_retries_with_jitter_backoff():
    import sys
    sys.path.insert(
        0, os.path.join(os.path.dirname(__file__), "..", "tools")
    )
    import serve_cli

    httpd, H, url = _stub_server(sheds=100, retry_after="")
    slept = []
    try:
        out = serve_cli.request_with_retries(
            f"{url}/delta", {}, max_retries=3, sleep=slept.append,
        )
    finally:
        httpd.shutdown()
        httpd.server_close()
    assert out["status"] == 503 and out["attempts"] == 4
    assert len(slept) == 3
    assert all(s > 0 for s in slept)


# ---- THE fleet chaos acceptance test --------------------------------------


def test_fleet_chaos_kill_slow_roll(tmp_path):
    """ISSUE 9 acceptance: a 3-replica fleet under a live read hammer
    survives (a) replica_slow on r1 — breaker open → half-open → close,
    router p99 bounded while the replica crawls; (b) replica_kill of r2
    + restart — reads never fail while it is dead, it rejoins after;
    (c) a full rolling reload to an externally published snapshot
    version; (d) writer kill — loud fleet_degraded, fleet serves
    read-only. Throughout: ZERO failed client reads and ZERO
    mixed-version responses (every body's version equals its
    X-Pinned-Version echo, monotonic per client)."""
    sink = _sink()
    store, src, dst, v = _publish_base(tmp_path)
    fleet = _Fleet(store, sink=sink)
    hammer_errors: list = []
    lat_lock = threading.Lock()
    latencies: list = []
    per_thread_versions: dict = {}
    stop = threading.Event()

    def hammer(tid):
        seen = per_thread_versions.setdefault(tid, [])
        while not stop.is_set():
            t0 = time.perf_counter()
            try:
                code, body, headers = _post(
                    fleet.host, fleet.port, "/query",
                    {"vertices": [0, 13, 27]}, timeout=30,
                )
                dt = time.perf_counter() - t0
                if code != 200:
                    raise AssertionError(
                        f"read failed: HTTP {code} {body}"
                    )
                if body["version"] != int(headers["X-Pinned-Version"]):
                    raise AssertionError(
                        f"MIXED VERSION: body v{body['version']} != pin "
                        f"{headers['X-Pinned-Version']}"
                    )
                if len(body["label"]) != 3:
                    raise AssertionError(f"torn body: {body}")
                seen.append(body["version"])
                with lat_lock:
                    latencies.append(dt)
            except Exception as e:  # noqa: BLE001 — collect, assert later
                hammer_errors.append(e)
                return
            time.sleep(0.01)

    def wait_breaker(state, timeout=12.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if fleet.router.replica_set.replica("r1").breaker.state == state:
                return
            time.sleep(0.03)
        raise AssertionError(
            f"breaker never reached {state}: "
            f"{fleet.router.replica_set.replica('r1').breaker.snapshot()}"
        )

    threads = [
        threading.Thread(target=hammer, args=(i,)) for i in range(3)
    ]
    try:
        fleet.wait_committed()
        for t in threads:
            t.start()
        time.sleep(0.5)  # steady-state reads before any chaos

        # (a) SLOW: r1 crawls at 1.5s/request; the router's 0.4s read
        # timeout turns every attempt into a breaker failure while the
        # generous 4s probe keeps the replica "alive" — exactly the
        # split the breaker exists for.
        faults.replica_slow(fleet.servers[1], 1.5)
        wait_breaker(BREAKER_OPEN)
        # while open, reads keep flowing off the healthy replicas
        time.sleep(0.6)
        faults.replica_slow(fleet.servers[1], 0.0)  # heal
        wait_breaker(BREAKER_CLOSED, timeout=15.0)

        # (b) KILL r2, serve through it, restart, rejoin
        faults.replica_kill(fleet.servers[2])
        deadline = time.monotonic() + 6
        while time.monotonic() < deadline:
            if fleet.router.replica_set.replica("r2").state == DOWN:
                break
            time.sleep(0.05)
        assert fleet.router.replica_set.replica("r2").state == DOWN
        time.sleep(0.4)  # reads continue on 2 replicas
        fleet.restart_replica(2)
        deadline = time.monotonic() + 8
        while time.monotonic() < deadline:
            if fleet.router.replica_set.replica("r2").state == HEALTHY:
                break
            time.sleep(0.05)
        assert fleet.router.replica_set.replica("r2").state == HEALTHY

        # (c) ROLLING RELOAD to an externally published v2, hammer live
        ext = DeltaIngestor(store, lof_k=4, check_samples=8)
        ext.apply(EdgeDelta.from_pairs(insert=[(v, 12), (v, 13)]))
        code, out, _ = _post(fleet.host, fleet.port, "/roll", {},
                             timeout=120)
        assert code == 200 and out["ok"], out
        assert out["committed_version"] == 2
        time.sleep(0.4)  # reads at v2

        # (d) WRITER KILL: read-only fleet, loud record, reads keep going
        faults.replica_kill(fleet.servers[0])
        deadline = time.monotonic() + 6
        while time.monotonic() < deadline and not fleet.router.replica_set.read_only:
            time.sleep(0.05)
        assert fleet.router.replica_set.read_only
        code, body, _ = _post(
            fleet.host, fleet.port, "/delta", {"insert": [[0, 13]]}
        )
        assert code == 503 and "read-only" in body["reason"]
        time.sleep(0.4)

        stop.set()
        for t in threads:
            t.join(timeout=30)

        # ZERO failed reads, ZERO mixed versions (checked in-loop),
        # versions monotonic per client session
        assert hammer_errors == [], hammer_errors[:3]
        total_reads = sum(len(vs) for vs in per_thread_versions.values())
        assert total_reads > 50
        for tid, vs in per_thread_versions.items():
            assert vs == sorted(vs), f"thread {tid} saw versions go back"
            assert set(vs) <= {1, 2}
        assert any(2 in set(vs) for vs in per_thread_versions.values())

        # p99 bounded: even through the slow phase, the breaker +
        # bounded retry kept the tail under the slow replica's 1.5s
        # crawl (one timed-out attempt + a fast retry, not a pile-up)
        lat = sorted(latencies)
        p99 = lat[min(len(lat) - 1, int(0.99 * len(lat)))]
        assert p99 < 1.5, f"router p99 {p99:.3f}s not bounded"

        # breaker episode fully observed
        transitions = [
            (r["from_state"], r["to_state"])
            for r in sink.records
            if r["phase"] == "breaker_transition" and r["replica"] == "r1"
        ]
        assert (BREAKER_CLOSED, BREAKER_OPEN) in transitions
        assert (BREAKER_OPEN, BREAKER_HALF_OPEN) in transitions
        assert (BREAKER_HALF_OPEN, BREAKER_CLOSED) in transitions

        # writer loss was loud
        flips = [r for r in sink.records if r["phase"] == "fleet_degraded"]
        assert flips and flips[-1]["read_only"] is True

        # replica lifecycle visible: r2 died and rejoined
        r2_states = [
            (r["from_state"], r["to_state"])
            for r in sink.records
            if r["phase"] == "replica_health" and r["replica"] == "r2"
        ]
        assert (HEALTHY, DOWN) in r2_states or (DEGRADED, DOWN) in r2_states
        assert (DOWN, JOINING) in r2_states
        assert (JOINING, HEALTHY) in r2_states
    finally:
        stop.set()
        fleet.stop()
    assert validate_records(sink.records) == []

    # the offline report renders the fleet section from the JSONL alone
    import sys
    sys.path.insert(
        0, os.path.join(os.path.dirname(__file__), "..", "tools")
    )
    import obs_report

    report = obs_report.build_report(sink.records)
    assert "-- fleet (replica health / breakers / routing) --" in report
    assert "breaker timeline:" in report
    assert "FLEET READ-ONLY" in report
    assert "route verdicts:" in report


# ---- fleet_cli (multi-process smoke) --------------------------------------


def test_fleet_cli_up_multiprocess_smoke(tmp_path):
    """The first multi-process path in the tree: fleet_cli spawns real
    replica PROCESSES (serve_cli serve, one port each) + the router,
    and a client query round-trips through the whole stack."""
    import socket
    import subprocess
    import sys

    store, *_ = _publish_base(tmp_path)

    def free_port():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    router_port, base_port = free_port(), free_port()
    repo = os.path.join(os.path.dirname(__file__), "..")
    proc = subprocess.Popen(
        [
            sys.executable, os.path.join(repo, "tools", "fleet_cli.py"),
            "up", "--store", str(tmp_path / "snap"), "--replicas", "2",
            "--port", str(router_port),
            "--replica-base-port", str(base_port),
        ],
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    try:
        deadline = time.monotonic() + 120
        ready = False
        while time.monotonic() < deadline:
            try:
                h = _get("127.0.0.1", router_port, "/healthz", timeout=2)
                if h.get("ready"):
                    ready = True
                    break
            except Exception:  # noqa: BLE001 — still starting
                pass
            time.sleep(0.5)
        assert ready, "fleet never became ready"
        code, body, headers = _post(
            "127.0.0.1", router_port, "/query", {"vertices": [0, 13]}
        )
        assert code == 200 and body["version"] == 1
        assert headers["X-Pinned-Version"] == "1"
    finally:
        proc.terminate()
        proc.wait(timeout=30)
