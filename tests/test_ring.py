"""Ring-sharded (fully distributed labels) == single-device parity.

Same virtual-device harness as test_sharded.py; additionally asserts the
ring schedule — ppermute rotation of label chunks instead of a replicated
label vector — produces bit-identical results.
"""

import jax
import numpy as np
import pytest

from graphmine_tpu.graph.container import build_graph
from graphmine_tpu.ops.cc import connected_components
from graphmine_tpu.ops.lpa import label_propagation
from graphmine_tpu.parallel import make_mesh
from graphmine_tpu.parallel.ring import (
    ring_connected_components,
    ring_label_propagation,
)
from graphmine_tpu.parallel.sharded import partition_graph, shard_graph_arrays


def _random_graph(rng, v, e):
    return rng.integers(0, v, e).astype(np.int32), rng.integers(0, v, e).astype(np.int32)


@pytest.fixture(scope="module")
def mesh8():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return make_mesh(8)


def test_ring_lpa_matches_single_device(mesh8, rng):
    for v, e in [(50, 200), (97, 513), (8, 8)]:
        src, dst = _random_graph(rng, v, e)
        g = build_graph(src, dst, num_vertices=v)
        want = np.asarray(label_propagation(g, max_iter=4))
        sg = shard_graph_arrays(partition_graph(g, mesh=mesh8), mesh8)
        got = np.asarray(ring_label_propagation(sg, mesh8, max_iter=4))
        np.testing.assert_array_equal(got, want)


def test_ring_cc_matches_single_device(mesh8, rng):
    for v, e in [(50, 60), (200, 150), (64, 32)]:
        src, dst = _random_graph(rng, v, e)
        g = build_graph(src, dst, num_vertices=v)
        want = np.asarray(connected_components(g))
        sg = shard_graph_arrays(partition_graph(g, mesh=mesh8), mesh8)
        got = np.asarray(ring_connected_components(sg, mesh8))
        np.testing.assert_array_equal(got, want)


def test_ring_bundled_parity(mesh8, bundled_graph):
    want = np.asarray(label_propagation(bundled_graph, max_iter=5))
    sg = shard_graph_arrays(partition_graph(bundled_graph, mesh=mesh8), mesh8)
    got = np.asarray(ring_label_propagation(sg, mesh8, max_iter=5))
    np.testing.assert_array_equal(got, want)


def test_ring_labels_stay_sharded(mesh8, rng):
    """The label carry must stay sharded over the mesh, not replicated —
    the whole point of the ring schedule. Asserted on the compiled HLO:
    the program's only collective is the chunk-rotation ppermute."""
    src, dst = _random_graph(rng, 64, 256)
    sg = shard_graph_arrays(partition_graph(src, dst, num_vertices=64, mesh=mesh8), mesh8)
    txt = ring_label_propagation.lower(sg, mesh8, max_iter=2).compile().as_text()
    assert "collective-permute" in txt
    assert "all-gather" not in txt and "all-reduce" not in txt


def test_ring_mesh_size_one(rng):
    mesh = make_mesh(1)
    src, dst = _random_graph(rng, 30, 100)
    g = build_graph(src, dst, num_vertices=30)
    sg = partition_graph(g, mesh=mesh)
    got = np.asarray(ring_label_propagation(sg, mesh, max_iter=3))
    want = np.asarray(label_propagation(g, max_iter=3))
    np.testing.assert_array_equal(got, want)


def test_ring_pagerank_matches_single_and_sharded(mesh8, rng):
    """r2: PageRank joins the ring family — parity with both the
    single-device kernel and the replicated sharded path."""
    from graphmine_tpu.graph.container import build_graph
    from graphmine_tpu.ops.degrees import out_degrees
    from graphmine_tpu.ops.pagerank import pagerank
    from graphmine_tpu.parallel.ring import ring_pagerank
    from graphmine_tpu.parallel.sharded import (
        partition_graph,
        shard_graph_arrays,
        sharded_pagerank,
    )

    v, e = 200, 1400
    src = rng.integers(0, v, e).astype(np.int32)
    dst = rng.integers(0, v, e).astype(np.int32)
    g = build_graph(src, dst, num_vertices=v, symmetric=False)
    od = out_degrees(g)
    want = np.asarray(pagerank(g, max_iter=60))
    sg = shard_graph_arrays(partition_graph(g, mesh=mesh8), mesh8)
    shard = np.asarray(sharded_pagerank(sg, mesh8, od, max_iter=60))
    ring = np.asarray(ring_pagerank(sg, mesh8, od, max_iter=60))
    np.testing.assert_allclose(ring, want, rtol=2e-4, atol=1e-7)
    np.testing.assert_allclose(ring, shard, rtol=2e-4, atol=1e-7)
    assert abs(ring.sum() - 1.0) < 1e-4


def test_weighted_pagerank_sharded_and_ring_parity(mesh8, rng):
    """r2: weighted PageRank on both distributed schedules — rank splits
    across out-edges in proportion to weight, matching the single-device
    ops.pagerank(weights=...) semantics."""
    import jax.numpy as jnp

    from graphmine_tpu.graph.container import build_graph
    from graphmine_tpu.ops.pagerank import pagerank
    from graphmine_tpu.parallel.ring import ring_pagerank
    from graphmine_tpu.parallel.sharded import (
        partition_graph,
        shard_graph_arrays,
        sharded_pagerank,
    )
    import jax

    v, e = 150, 1100
    src = rng.integers(0, v, e).astype(np.int32)
    dst = rng.integers(0, v, e).astype(np.int32)
    w = rng.uniform(0.2, 4.0, e).astype(np.float32)
    g = build_graph(src, dst, num_vertices=v, symmetric=False, edge_weights=w)
    want = np.asarray(pagerank(g, max_iter=60, weights=jnp.asarray(w)))
    # weights change the answer on this graph
    assert not np.allclose(want, np.asarray(pagerank(g, max_iter=60)), atol=1e-5)

    from graphmine_tpu.ops.degrees import out_degrees, out_weights

    out_w = out_weights(g)
    sg = shard_graph_arrays(partition_graph(g, mesh=mesh8), mesh8)
    assert sg.msg_weight is not None
    shard = np.asarray(sharded_pagerank(sg, mesh8, out_w, max_iter=60))
    ring = np.asarray(ring_pagerank(sg, mesh8, out_w, max_iter=60))
    np.testing.assert_allclose(shard, want, rtol=2e-4, atol=1e-7)
    np.testing.assert_allclose(ring, want, rtol=2e-4, atol=1e-7)

    # the silent-mixture trap is rejected: int out-degrees + weighted graph
    import pytest
    with pytest.raises(ValueError, match="out_weights"):
        sharded_pagerank(sg, mesh8, out_degrees(g), max_iter=5)
    with pytest.raises(ValueError, match="out_weights"):
        ring_pagerank(sg, mesh8, out_degrees(g), max_iter=5)
    # weighted=False opts back into unweighted ranks on the same graph
    unw = np.asarray(sharded_pagerank(sg, mesh8, out_degrees(g), max_iter=60,
                                      weighted=False))
    np.testing.assert_allclose(
        unw, np.asarray(pagerank(g, max_iter=60)), rtol=2e-4, atol=1e-7)


def test_sharded_ppr_matches_single_device(mesh8, rng):
    """r2: source-axis data parallelism for parallelPersonalizedPageRank —
    column parity with the single-device batched op, incl. a source count
    that doesn't divide the mesh (padding columns sliced away)."""
    from graphmine_tpu.ops.pagerank import parallel_personalized_pagerank
    from graphmine_tpu.parallel.ppr import sharded_personalized_pagerank

    v, e = 120, 800
    src = rng.integers(0, v, e).astype(np.int32)
    dst = rng.integers(0, v, e).astype(np.int32)
    g = build_graph(src, dst, num_vertices=v, symmetric=False)
    sources = np.array([3, 77, 5, 41, 99, 0], np.int32)  # 6 % 8 != 0
    want = np.asarray(parallel_personalized_pagerank(g, sources, max_iter=60))
    got = np.asarray(sharded_personalized_pagerank(g, sources, mesh8, max_iter=60))
    assert got.shape == (v, 6)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=1e-7)

    assert sharded_personalized_pagerank(g, [], mesh8).shape == (v, 0)
    with pytest.raises(ValueError, match="out of range"):
        sharded_personalized_pagerank(g, [v + 1], mesh8)


def test_ring_rejects_multislice_mesh(rng):
    """Ring schedules ppermute one axis; a 2-D mesh must be rejected with
    a clear error, not a cryptic trace failure."""
    from graphmine_tpu.parallel.mesh import make_multislice_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh2 = make_multislice_mesh(2, 4)
    src = rng.integers(0, 40, 200).astype(np.int32)
    dst = rng.integers(0, 40, 200).astype(np.int32)
    g = build_graph(src, dst, num_vertices=40)
    sg = shard_graph_arrays(partition_graph(g, mesh=mesh2), mesh2)
    with pytest.raises(ValueError, match="1-D"):
        ring_label_propagation(sg, mesh2, max_iter=2)
