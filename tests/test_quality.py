"""Result-quality observability suite (marker ``quality``, ISSUE 13):
quantile sketches + PSI drift, the publish-time quality pass, the canary
probe, the alert rule engine, ``/alertz``/``/explain``, the fleet sketch
merge and the obs_report quality gate — tools/run_tier1.sh
--quality-only.

The acceptance pins:

- sketch merge is associative/commutative over random observation sets
  on one ladder (the ``Histogram.merge`` contract), mismatched ladders
  refuse, and the ROUTER's fleet-merged sketch equals the counter-wise
  per-replica merge done by hand;
- PSI drift distance and partition-matched churn are EXACT against
  hand-computed values;
- two publishes with an injected scorer regression between them produce
  schema-valid, span-joined ``quality_drift`` + ``canary_score``
  records, an alert firing→resolved transition observable on
  ``/alertz``, and an ``obs_report`` that renders the quality timeline
  from the JSONL alone with a non-zero exit while the canary alert is
  still firing.
"""

import json
import math
import os
import sys
import urllib.error
import urllib.request

import numpy as np
import pytest

from graphmine_tpu.graph.container import build_graph
from graphmine_tpu.obs.alerts import AlertManager, AlertRule, default_rules
from graphmine_tpu.obs.quality import (
    CanaryProbe,
    QualityState,
    partition_churn,
    quality_drift,
    run_quality_pass,
)
from graphmine_tpu.obs.schema import validate_record, validate_records
from graphmine_tpu.obs.sketch import (
    DEFAULT_SCORE_LADDER,
    PSI_EPS,
    QuantileSketch,
    log_ladder,
    psi_distance,
)
from graphmine_tpu.obs.spans import Tracer
from graphmine_tpu.pipeline.checkpoint import graph_fingerprint
from graphmine_tpu.pipeline.metrics import MetricsSink
from graphmine_tpu.serve.delta import cold_recompute
from graphmine_tpu.serve.query import QueryEngine
from graphmine_tpu.serve.server import SnapshotServer
from graphmine_tpu.serve.snapshot import SnapshotStore
from graphmine_tpu.testing import faults

pytestmark = pytest.mark.quality

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_TOOLS = os.path.join(_REPO, "tools")
if _TOOLS not in sys.path:
    sys.path.insert(0, _TOOLS)


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}") as r:
        return json.loads(r.read())


def _post(port, path, payload):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(), method="POST",
    )
    with urllib.request.urlopen(req) as r:
        return json.loads(r.read())


def _sbm_store(tmp_path, sink=None, v_per_block=40, blocks=8,
               lof="linspace"):
    from graphmine_tpu.datasets import sbm

    src, dst, _ = sbm([v_per_block] * blocks, 0.2, 0.002, seed=3)
    v = v_per_block * blocks
    g = build_graph(src, dst, num_vertices=v)
    labels, cc, _ = cold_recompute(g)
    store = SnapshotStore(str(tmp_path / "snap"))
    if lof == "linspace":
        lof_col = np.linspace(0.5, 1.4, v).astype(np.float32)
    else:
        lof_col = np.zeros(v, np.float32)
    store.publish(
        {"src": src, "dst": dst, "labels": labels, "cc_labels": cc,
         "lof": lof_col},
        fingerprint=graph_fingerprint(src, dst), sink=sink,
    )
    return store, v


# ---- sketches -------------------------------------------------------------


def test_log_ladder_shape_and_refusals():
    lad = log_ladder(1.0, 8.0, steps_per_octave=1)
    assert lad == (1.0, 2.0, 4.0, 8.0)
    assert log_ladder(1.0, 7.9)[-1] >= 7.9  # covers hi
    with pytest.raises(ValueError):
        log_ladder(0.0, 8.0)
    with pytest.raises(ValueError):
        log_ladder(8.0, 1.0)
    with pytest.raises(ValueError):
        log_ladder(1.0, 8.0, steps_per_octave=0)


def test_sketch_state_roundtrip_and_add_counts():
    sk = QuantileSketch(buckets=(1.0, 2.0, 4.0))
    sk.observe(0.5)   # <= first bound -> bucket 0
    sk.observe(3.0)
    sk.observe(100.0)  # overflow
    state = sk.to_state()
    assert state["counts"] == [1, 0, 1, 1]
    assert state["count"] == 3
    back = QuantileSketch.from_state(state)
    assert back.to_state() == state
    # JSON round-trip is exact (the /alertz wire path)
    wired = QuantileSketch.from_state(json.loads(json.dumps(state)))
    assert wired.to_state() == state
    with pytest.raises(ValueError):
        sk.add_counts([1, 2])          # wrong bucket count
    with pytest.raises(ValueError):
        sk.add_counts([1, -1, 0, 0])   # negative
    with pytest.raises(ValueError):
        QuantileSketch.from_state({"bounds": [1.0]})  # torn payload
    with pytest.raises(ValueError):  # non-numeric count element: still
        # ValueError, so a router merging replica payloads skips it
        # instead of 500ing (the review-pinned torn-payload contract)
        QuantileSketch.from_state({"bounds": [1.0], "counts": [None, 0]})


def test_sketch_merge_associative_commutative():
    """The r11 Histogram.merge property suite applied to sketches:
    random observation sets, every grouping/order lands on identical
    counters."""
    rng = np.random.default_rng(7)
    sets = [rng.gamma(2.0, 1.0, size=rng.integers(5, 60)) for _ in range(3)]

    def sketch(*obs_sets):
        sk = QuantileSketch(buckets=DEFAULT_SCORE_LADDER)
        for obs in obs_sets:
            for x in obs:
                sk.observe(float(x))
        return sk

    a, b, c = (sketch(s) for s in sets)
    ab_c = sketch(sets[0]).merge(sketch(sets[1])).merge(sketch(sets[2]))
    a_bc = sketch(sets[0]).merge(sketch(sets[1]).merge(sketch(sets[2])))
    cba = sketch(sets[2]).merge(sketch(sets[1])).merge(sketch(sets[0]))
    want = sketch(*sets).to_state()
    for got in (ab_c, a_bc, cba):
        st = got.to_state()
        assert st["counts"] == want["counts"]
        assert st["count"] == want["count"]
        assert st["sum"] == pytest.approx(want["sum"])
    # mismatched ladders refuse (merge AND psi)
    other = QuantileSketch(buckets=(1.0, 2.0))
    with pytest.raises(ValueError, match="ladder"):
        a.merge(other)
    with pytest.raises(ValueError, match="ladder"):
        psi_distance(a, other)


def test_psi_hand_computed_exact():
    """PSI against the literal hand formula on a 2-bound ladder: all
    mass moving from bucket 0 to bucket 1."""
    a = QuantileSketch(buckets=(1.0, 2.0))
    a.add_counts([10, 0, 0])
    b = QuantileSketch(buckets=(1.0, 2.0))
    b.add_counts([0, 10, 0])
    # buckets: (1, eps, eps) vs (eps, 1, eps) ->
    # 2 * (1 - eps) * ln(1 / eps), third term zero
    want = 2 * (1.0 - PSI_EPS) * math.log(1.0 / PSI_EPS)
    assert psi_distance(a, b) == pytest.approx(want, rel=1e-12)
    # symmetric, zero on identity, zero on two empties
    assert psi_distance(b, a) == pytest.approx(want, rel=1e-12)
    assert psi_distance(a, a) == 0.0
    empty = QuantileSketch(buckets=(1.0, 2.0))
    assert psi_distance(empty, QuantileSketch(buckets=(1.0, 2.0))) == 0.0
    # a 30/70 -> 50/50 shift, by hand
    c = QuantileSketch(buckets=(1.0, 2.0))
    c.add_counts([3, 7, 0])
    d = QuantileSketch(buckets=(1.0, 2.0))
    d.add_counts([5, 5, 0])
    want = (0.3 - 0.5) * math.log(0.3 / 0.5) + (0.7 - 0.5) * math.log(0.7 / 0.5)
    assert psi_distance(c, d) == pytest.approx(want, rel=1e-12)
    # state-dict operands work too (the obs_report path)
    assert psi_distance(c.to_state(), d.to_state()) == pytest.approx(
        want, rel=1e-12
    )


def test_partition_churn_hand_computed():
    # identical up to renumbering: zero churn
    assert partition_churn([0, 0, 1, 1], [9, 9, 4, 4]) == 0.0
    # one vertex moved: child comm 0 = {v0,v1,v2}, majority parent 0,
    # v2 (parent 1) churned -> 1/4
    assert partition_churn([0, 0, 1, 1], [0, 0, 0, 1]) == 0.25
    # empty edge case
    assert partition_churn([], []) == 0.0
    # growth: only the common prefix is compared
    assert partition_churn([0, 0], [5, 5, 7, 7]) == 0.0


def test_quality_state_and_drift_fields():
    labels = np.array([0, 0, 0, 3, 3, 7])
    lof = np.array([0.5, 0.8, 1.0, 1.2, 2.0, 9.0], np.float32)
    st = QualityState.from_arrays(labels, lof, version=4, threshold=1.5)
    assert st.num_communities == 3
    assert st.largest_community == 3
    assert st.anomaly_count == 2
    assert st.anomaly_rate == pytest.approx(2 / 6)
    assert st.lof_sketch.count == 6
    assert st.size_sketch.count == 3
    # drift against a renamed-but-identical partition: no churn, no PSI
    st2 = QualityState.from_arrays(
        np.array([9, 9, 9, 4, 4, 5]), lof, version=5, threshold=1.5
    )
    d = quality_drift(st, st2, labels, [9, 9, 9, 4, 4, 5])
    assert d["churn_frac"] == 0.0
    assert d["lof_psi"] == 0.0
    assert d["size_psi"] == 0.0
    assert d["anomaly_rate_delta"] == 0.0
    # id-chain diagnostics see the renumbering (documented noise)
    assert d["new_communities"] == 3 and d["dissolved_communities"] == 3


# ---- canary probe ---------------------------------------------------------


def test_canary_deterministic_and_healthy_recall():
    p1 = CanaryProbe.generate(seed=11)
    p2 = CanaryProbe.generate(seed=11)
    assert np.array_equal(np.asarray(p1.features), np.asarray(p2.features))
    assert np.array_equal(
        np.asarray(p1.is_anomaly), np.asarray(p2.is_anomaly)
    )
    out = p1.score()
    assert out["recall_at_k"] == 1.0
    assert out["mean_rank_frac"] < 0.05
    assert out["num_anomalies"] == p1.num_anomalies > 0


def test_canary_detects_injected_scorer_regression():
    probe = CanaryProbe.generate(seed=11)

    def corrupt(**ctx):
        st = ctx["state"]
        st["scores"] = np.zeros_like(np.asarray(st["scores"]))
        return None

    corrupt.wants_ctx = True
    inj = faults.FaultInjector().add("canary_probe", corrupt)
    with inj.installed():
        out = probe.score()
    assert inj.fired("canary_probe") == 1
    assert out["recall_at_k"] < 0.7  # the default alert threshold trips


def test_canary_snapshot_roundtrip(tmp_path):
    probe = CanaryProbe.generate(seed=5)
    store = SnapshotStore(str(tmp_path / "s"))
    arrays = {
        "labels": np.zeros(4, np.int32),
        **probe.arrays(),
    }
    store.publish(arrays, extra_meta={"canary": probe.meta()})
    snap = store.load()
    back = CanaryProbe.from_snapshot(snap)
    assert back is not None
    assert np.array_equal(
        np.asarray(back.features), np.asarray(probe.features)
    )
    assert back.k == probe.k and back.seed == probe.seed
    # a snapshot with no probe yields None, not a crash
    store2 = SnapshotStore(str(tmp_path / "s2"))
    store2.publish({"labels": np.zeros(4, np.int32)})
    assert CanaryProbe.from_snapshot(store2.load()) is None


# ---- alert engine ---------------------------------------------------------


def test_alert_fire_resolve_flap_sequence():
    clock = {"t": 0.0}
    mgr = AlertManager(
        rules=[AlertRule("r", "x", ">", 1.0)], clock=lambda: clock["t"]
    )
    # below threshold: nothing
    assert mgr.evaluate({"x": 0.5}) == []
    assert mgr.firing() == []
    # above: pending -> firing in one pass (for_s=0)
    trans = mgr.evaluate({"x": 2.0})
    assert trans and trans[-1][2] == "firing"
    assert mgr.firing() == ["r"]
    # still above: no new transition
    assert mgr.evaluate({"x": 3.0}) == []
    # below: resolved
    trans = mgr.evaluate({"x": 0.1})
    assert [t for _, _, t in trans][-1] == "resolved"
    assert mgr.firing() == []
    # flap: fires again
    mgr.evaluate({"x": 5.0})
    assert mgr.firing() == ["r"]
    snap = mgr.snapshot()
    rule = snap["rules"][0]
    assert rule["times_fired"] == 2 and rule["times_resolved"] == 1
    assert snap["firing"] == 1


def test_alert_for_duration_and_missing_metric():
    clock = {"t": 0.0}
    mgr = AlertManager(
        rules=[AlertRule("lag", "lag_s", ">", 10.0, for_s=5.0)],
        clock=lambda: clock["t"],
    )
    assert mgr.evaluate({"lag_s": 20.0}) != []       # -> pending
    assert mgr.firing() == []
    clock["t"] = 3.0
    mgr.evaluate({"lag_s": 20.0})                     # sustained, < for_s
    assert mgr.firing() == []
    # a pass with the metric ABSENT leaves state untouched
    mgr.evaluate({})
    clock["t"] = 6.0
    mgr.evaluate({"lag_s": 20.0})                     # sustained past for_s
    assert mgr.firing() == ["lag"]
    # a dip resets: pending must restart the clock
    mgr.evaluate({"lag_s": 1.0})
    clock["t"] = 7.0
    mgr.evaluate({"lag_s": 20.0})
    assert mgr.firing() == []                         # pending again, not firing


def test_alert_records_and_env_overrides(monkeypatch):
    sink = MetricsSink(tracer=Tracer())
    monkeypatch.setenv("GRAPHMINE_ALERT_CANARY_RECALL", "0.9")
    rules = {r.name: r for r in default_rules()}
    assert rules["canary_recall_low"].threshold == 0.9
    assert rules["canary_recall_low"].severity == "page"
    mgr = AlertManager(rules=list(rules.values()), sink=sink)
    mgr.evaluate({"canary_recall": 0.5})
    mgr.evaluate({"canary_recall": 1.0})
    recs = [r for r in sink.records if r.get("phase") == "alert"]
    assert [r["state"] for r in recs] == ["firing", "resolved"]
    assert all(validate_record(r) == [] for r in recs)
    # malformed env raises loudly at rule construction
    monkeypatch.setenv("GRAPHMINE_ALERT_LOF_PSI", "not-a-float")
    with pytest.raises(ValueError, match="GRAPHMINE_ALERT_LOF_PSI"):
        default_rules()
    # malformed rule fields refuse
    with pytest.raises(ValueError):
        AlertRule("bad", "m", ">=", 1.0)
    with pytest.raises(ValueError):
        AlertRule("bad", "m", ">", 1.0, severity="critical")
    with pytest.raises(ValueError, match="duplicate"):
        AlertManager(rules=[AlertRule("a", "m", ">", 1.0),
                            AlertRule("a", "m", "<", 1.0)])


# ---- quality pass + schema ------------------------------------------------


def test_run_quality_pass_records_schema_valid():
    sink = MetricsSink(tracer=Tracer())
    rng = np.random.default_rng(0)
    parent = rng.integers(0, 20, 500)
    labels = parent.copy()
    labels[:30] = 21
    lof = rng.random(500).astype(np.float32)
    rep = run_quality_pass(
        labels, lof, 2, parent_labels=parent, parent_lof=lof,
        parent_version=1, canary=CanaryProbe.generate(seed=3), sink=sink,
    )
    assert rep.drift is not None and rep.canary is not None
    assert rep.seconds > 0
    phases = [r["phase"] for r in sink.records]
    for want in ("quality_snapshot", "quality_drift", "canary_score"):
        assert want in phases
    assert validate_records(sink.records) == []


def test_schema_sketch_subrecord_all_or_nothing():
    ok = {
        "phase": "quality_snapshot", "t": 1.0, "version": 1,
        "num_vertices": 4, "num_communities": 1, "anomaly_rate": 0.0,
        "lof_threshold": 1.5, "seconds": 0.1,
        "lof_sketch": QuantileSketch(buckets=(1.0,)).to_state(),
        "size_sketch": QuantileSketch(buckets=(1.0,)).to_state(),
    }
    assert validate_record(ok) == []
    torn = dict(ok)
    torn["lof_sketch"] = {"bounds": [1.0], "counts": [0, 0]}  # no sum/count
    problems = validate_record(torn)
    assert any("half-stamped lof_sketch" in p for p in problems)
    not_dict = dict(ok)
    not_dict["size_sketch"] = [1, 2]
    assert any("size_sketch" in p for p in validate_record(not_dict))


def test_schema_lint_flags_inline_sketch(tmp_path):
    import schema_lint

    bad = tmp_path / "mod.py"
    bad.write_text(
        "def f(sink, sk):\n"
        "    sink.emit('quality_snapshot', lof_sketch={'bounds': []})\n"
    )
    hits = schema_lint.scan_inline_sketches(str(tmp_path))
    assert len(hits) == 1
    # the real package is clean (to_state() everywhere)
    assert schema_lint.violations() == []


# ---- /explain -------------------------------------------------------------


def test_explain_fields_and_http(tmp_path):
    sink = MetricsSink(tracer=Tracer())
    store, v = _sbm_store(tmp_path, sink=sink)
    eng = QueryEngine(store.load(), device=False)
    row = eng.explain(3)
    assert row["vertex"] == 3
    assert row["label"] == eng.membership(3)
    assert row["community_size"] == eng.community_size(3)
    assert 0 <= row["community_decile"] <= 9
    assert row["degree"] == len(eng.neighbors(3))
    assert 0 <= row["lof_rank_in_community"] < row["community_size"]
    assert row["community_top_lof"] >= row["lof"]
    assert 0.0 <= row["lof_percentile"] <= 1.0
    assert "neighbor_lof_mean" in row and "neighbor_lof_max" in row
    with pytest.raises(KeyError):
        eng.explain(v + 5)

    srv = SnapshotServer(store, sink=sink)
    _, port = srv.start()
    try:
        got = _get(port, "/explain?vertex=3")
        assert got["vertex"] == 3 and got["label"] == row["label"]
        for bad_path in ("/explain", "/explain?vertex=abc",
                         f"/explain?vertex={v + 5}"):
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(port, bad_path)
            assert ei.value.code == 400
    finally:
        srv.stop()
    assert validate_records(sink.records) == []


# ---- serve e2e: anomaly-rate shift fires an alert -------------------------


def test_delta_burst_shifts_anomaly_rate_fires_alert(tmp_path, monkeypatch):
    """The ISSUE 13 satellite e2e: a delta burst that shifts the anomaly
    rate produces a quality_drift record and a firing alert visible on
    /alertz and in obs_report."""
    monkeypatch.setenv("GRAPHMINE_ALERT_ANOMALY_RATE", "0.004")
    sink = MetricsSink(tracer=Tracer())
    store, v = _sbm_store(tmp_path, sink=sink, lof="zeros")
    srv = SnapshotServer(store, sink=sink)
    _, port = srv.start()
    try:
        base_rate = _get(port, "/alertz")["quality"]["state"]["anomaly_rate"]
        assert base_rate == 0.0
        # wire 8 vertices as cross-community hubs: their LOF scores jump
        rng = np.random.default_rng(5)
        hubs = rng.choice(v, 8, replace=False)
        ins = [
            [int(h), int(t)]
            for h in hubs for t in rng.integers(0, v, 30)
        ]
        out = _post(port, "/delta", {"insert": ins})
        assert out["version"] == 2
        az = _get(port, "/alertz")
        assert az["quality"]["drift"] is not None
        rate = az["quality"]["state"]["anomaly_rate"]
        assert rate > 0.004, f"burst did not shift the anomaly rate: {rate}"
        rules = {r["name"]: r for r in az["rules"]}
        assert rules["anomaly_rate_high"]["state"] == "firing"
        assert az["firing"] >= 1
        # the drift record is in the stream and schema-valid
        drifts = [
            r for r in sink.records if r.get("phase") == "quality_drift"
        ]
        assert drifts and drifts[-1]["anomaly_rate_delta"] > 0
    finally:
        srv.stop()
    assert validate_records(sink.records) == []
    # obs_report renders the quality section + the firing (warn) alert
    # without gating (anomaly_rate_high is warn, not page)
    import obs_report

    stream = tmp_path / "m.jsonl"
    with open(stream, "w") as f:
        for r in sink.records:
            f.write(json.dumps(r) + "\n")
    out_path = tmp_path / "report.txt"
    rc = obs_report.main([str(stream), "--out", str(out_path)])
    assert rc == 0
    text = out_path.read_text()
    assert "quality & alerts" in text
    assert "anomaly_rate_high" in text and "ALERT FIRING" in text


# ---- THE acceptance: scorer regression between two publishes --------------


def test_acceptance_scorer_regression_canary_alert_fleet_and_report(
    tmp_path, monkeypatch,
):
    sink = MetricsSink(tracer=Tracer())
    store, v = _sbm_store(tmp_path, sink=sink)
    srv = SnapshotServer(store, sink=sink)
    _, port = srv.start()
    try:
        # publish 1: healthy scorer
        out = _post(port, "/delta", {"insert": [[0, 1]]})
        assert out["version"] == 2
        az = _get(port, "/alertz")
        assert az["quality"]["canary"]["recall_at_k"] == 1.0
        rules = {r["name"]: r for r in az["rules"]}
        assert rules["canary_recall_low"]["state"] in (
            "inactive", "resolved"
        )

        # publish 2: an injected scorer regression (the canary_probe
        # fault seam corrupts the scores the production scorer returned)
        def corrupt(**ctx):
            st = ctx["state"]
            st["scores"] = np.zeros_like(np.asarray(st["scores"]))
            return None

        corrupt.wants_ctx = True
        inj = faults.FaultInjector().add("canary_probe", corrupt)
        with inj.installed():
            out = _post(port, "/delta", {"insert": [[1, 2]]})
        assert out["version"] == 3
        assert inj.fired("canary_probe") == 1
        az = _get(port, "/alertz")
        assert az["quality"]["canary"]["recall_at_k"] < 0.7
        rules = {r["name"]: r for r in az["rules"]}
        assert rules["canary_recall_low"]["state"] == "firing"

        # the firing stream: obs_report gates with exit 4 HERE
        firing_stream = tmp_path / "firing.jsonl"
        with open(firing_stream, "w") as f:
            for r in sink.records:
                f.write(json.dumps(r) + "\n")

        # publish 3: healthy again -> firing -> resolved on /alertz
        out = _post(port, "/delta", {"insert": [[2, 3]]})
        assert out["version"] == 4
        az = _get(port, "/alertz")
        rules = {r["name"]: r for r in az["rules"]}
        assert rules["canary_recall_low"]["state"] == "resolved"
        assert az["quality"]["canary"]["recall_at_k"] == 1.0

        # records: schema-valid, span-joined to the publishing trace
        by_phase: dict = {}
        for r in sink.records:
            by_phase.setdefault(r.get("phase"), []).append(r)
        assert validate_records(sink.records) == []
        for phase in ("quality_snapshot", "quality_drift", "canary_score"):
            recs = by_phase[phase]
            assert len(recs) >= 3
            for r in recs:
                for key in ("run_id", "trace_id", "span_id", "span_path"):
                    assert r.get(key), (phase, key, r)
                assert "delta_apply" in r["span_path"]
        states = [r["state"] for r in by_phase["alert"]
                  if r["name"] == "canary_recall_low"]
        assert states == ["firing", "resolved"]

        # fleet: router-merged sketch == counter-wise per-replica merge
        srv2 = SnapshotServer(store)
        addr2 = srv2.start()
        from graphmine_tpu.serve.fleet import FleetRouter

        router = FleetRouter([
            ("r0", "127.0.0.1", port),
            ("r1", addr2[0], addr2[1]),
        ])
        _, rport = router.start()
        try:
            router.probe_once()
            r_az = _get(rport, "/alertz")
            assert sorted(r_az["replicas"]) == ["r0", "r1"]
            merged = r_az["quality"]["merged"]
            for key in ("lof_sketch", "size_sketch"):
                by_hand = None
                for rid in ("r0", "r1"):
                    sk = QuantileSketch.from_state(
                        r_az["replicas"][rid]["quality"]["state"][key]
                    )
                    by_hand = sk if by_hand is None else by_hand.merge(sk)
                assert merged[key]["counts"] == by_hand.to_state()["counts"]
                assert merged[key]["count"] == by_hand.to_state()["count"]
            # the fleet /metrics scrape carries the merged sketch
            text = urllib.request.urlopen(
                f"http://127.0.0.1:{rport}/metrics"
            ).read().decode()
            assert "graphmine_fleet_lof_score_sketch_bucket" in text
        finally:
            router.stop()
            srv2.stop()
    finally:
        srv.stop()

    # obs_report from the JSONL alone: quality timeline renders; the
    # firing-canary stream exits 4 (the CI gate), the resolved stream 0
    import obs_report

    out_path = tmp_path / "firing_report.txt"
    rc = obs_report.main([str(firing_stream), "--out", str(out_path)])
    assert rc == 4
    text = out_path.read_text()
    assert "quality & alerts" in text
    assert "canary_recall_low" in text
    assert "canary@k" in text
    # --lenient downgrades the gate
    assert obs_report.main(
        [str(firing_stream), "--lenient", "--out", str(out_path)]
    ) == 0
    # the full (resolved) stream passes
    full_stream = tmp_path / "full.jsonl"
    with open(full_stream, "w") as f:
        for r in sink.records:
            f.write(json.dumps(r) + "\n")
    assert obs_report.main(
        [str(full_stream), "--out", str(out_path)]
    ) == 0


def test_quality_disabled_env(tmp_path, monkeypatch):
    monkeypatch.setenv("GRAPHMINE_QUALITY", "0")
    sink = MetricsSink(tracer=Tracer())
    store, v = _sbm_store(tmp_path, sink=sink)
    from graphmine_tpu.serve.delta import DeltaIngestor, EdgeDelta

    ing = DeltaIngestor(store, sink=sink)
    assert not ing.quality_enabled and ing._canary is None
    snap = ing.apply(EdgeDelta.from_pairs(insert=[(0, 1)]))
    assert "canary_features" not in snap.arrays
    phases = {r["phase"] for r in sink.records}
    assert "quality_snapshot" not in phases and "canary_score" not in phases
    assert validate_records(sink.records) == []
    # the kill switch also covers the READ-time engine pass: /healthz
    # and /alertz must not build the O(V) quality state
    srv = SnapshotServer(store, sink=sink)
    _, port = srv.start()
    try:
        assert not srv.quality_enabled
        h = _get(port, "/healthz")
        assert h["ok"] and h["alerts_firing"] == 0
        az = _get(port, "/alertz")
        assert az["quality"] == {"disabled": True}
        assert srv.engine._quality_state is None  # never built
    finally:
        srv.stop()
