"""GraphX ``LabelPropagation`` oracle cross-validation (VERDICT r1 item 3).

The north-star clause "matching GraphFrames community IDs on bundled data"
(BASELINE.json; call site ``Graphframes.py:81``) is validated here without
a JVM: ``graphmine_tpu.oracle`` reproduces GraphX's exact Pregel structure
(both-direction messages, multiplicity, fixed supersteps, first-max
``maxBy``) with the tie-break explicit, and the TPU engine is required to
match it label-for-label under the shared deterministic tie rule. GraphX's
own tie order is machine-dependent (Scala Map iteration order downstream
of Spark's combiner merge order — see the module docstring), so the
GraphX-like ``hash_order`` rule is compared at partition level with the
measured agreement pinned.
"""

import numpy as np
import pytest

from graphmine_tpu.oracle import (
    canonical_partition,
    graphx_label_propagation,
    scala_trie_order_key,
)


def _ari(a, b):
    from graphmine_tpu.ops.cluster_metrics import adjusted_rand_index

    return float(adjusted_rand_index(np.asarray(a), np.asarray(b)))


def test_triangle_and_isolate():
    # Synchronous LPA has no convergence guarantee (GraphX runs exactly
    # maxIter steps for the same reason — odd cycles can oscillate under
    # some tie choices); under the smallest-label rule the triangle does
    # settle, and the isolated vertex keeps its label under every rule.
    src = np.array([0, 1, 2], np.int64)
    dst = np.array([1, 2, 0], np.int64)
    labels = graphx_label_propagation(src, dst, 4, max_iter=4, tie="smallest")
    assert set(labels[:3]) == {0}
    for tie in ("smallest", "largest", "hash_order", "random"):
        labels = graphx_label_propagation(src, dst, 4, max_iter=4, tie=tie)
        assert labels[3] == 3  # no messages -> keeps initial label


def test_tie_rules_differ_on_even_split():
    # Vertex 2 hears {0: 1, 1: 1}: a pure tie between labels 0 and 1.
    src = np.array([0, 1], np.int64)
    dst = np.array([2, 2], np.int64)
    small = graphx_label_propagation(src, dst, 3, max_iter=1, tie="smallest")
    large = graphx_label_propagation(src, dst, 3, max_iter=1, tie="largest")
    hashy = graphx_label_propagation(src, dst, 3, max_iter=1, tie="hash_order")
    assert small[2] == 0 and large[2] == 1
    # hash_order picks whichever of {0, 1} iterates first in the Scala trie.
    keys = scala_trie_order_key(np.array([0, 1], np.int64))
    assert hashy[2] == int(np.argmin(keys))


def test_duplicate_edges_carry_multiplicity():
    # Two copies of 1->3 outvote one 2->3 (Graphframes.py:70-74 keeps dups).
    src = np.array([1, 1, 2], np.int64)
    dst = np.array([3, 3, 3], np.int64)
    labels = graphx_label_propagation(src, dst, 4, max_iter=1, tie="largest")
    assert labels[3] == 1  # multiplicity 2 beats tie-rule preference


def test_engine_matches_oracle_exactly_on_random_graphs():
    """Label-for-label parity engine==oracle under the shared smallest-label
    tie rule, across sizes and seeds: the engine implements GraphX's
    structure, differing only in the (explicit) tie-break."""
    from graphmine_tpu.graph.container import build_graph
    from graphmine_tpu.ops.lpa import label_propagation

    for v, e, seed in ((50, 120, 0), (300, 1500, 1), (1000, 8000, 2)):
        r = np.random.default_rng(seed)
        src = r.integers(0, v, e).astype(np.int32)
        dst = r.integers(0, v, e).astype(np.int32)
        g = build_graph(src, dst, num_vertices=v)
        engine = np.asarray(label_propagation(g, max_iter=5))
        oracle = graphx_label_propagation(src, dst, v, max_iter=5, tie="smallest")
        np.testing.assert_array_equal(engine, oracle.astype(np.int32))


def test_engine_matches_oracle_exactly_on_bundled_data(bundled_graph, bundled_edges):
    from graphmine_tpu.ops.lpa import label_propagation

    v = bundled_edges.num_vertices
    engine = np.asarray(label_propagation(bundled_graph, max_iter=5))
    oracle = graphx_label_propagation(
        bundled_edges.src, bundled_edges.dst, v, max_iter=5, tie="smallest"
    )
    np.testing.assert_array_equal(engine, oracle.astype(np.int32))


def test_bundled_partition_agreement_across_tie_rules(bundled_edges):
    """The north-star check, stated honestly: community *partitions* on the
    bundled data agree to ARI > 0.85 between this engine's tie rule and
    the GraphX-like hash-order rule (measured 0.896; community counts
    579 vs 612) and ARI > 0.8 even vs the adversarial largest-label rule
    (measured 0.835; 650 communities), with every rule inside the
    measured community-count band (BASELINE.md: ~650, band [550, 750]).
    Ties move individual vertices but not the community structure — and
    any single GraphX run is itself one sample from this tie-rule
    family."""
    v = bundled_edges.num_vertices
    parts = {}
    for tie in ("smallest", "hash_order", "largest"):
        labels = graphx_label_propagation(
            bundled_edges.src, bundled_edges.dst, v, max_iter=5, tie=tie
        )
        n_comm = len(np.unique(labels))
        assert 550 <= n_comm <= 750, (tie, n_comm)
        parts[tie] = canonical_partition(labels)
    assert _ari(parts["smallest"], parts["hash_order"]) > 0.85
    assert _ari(parts["smallest"], parts["largest"]) > 0.8


def test_canonical_partition_invariant_to_relabeling(rng):
    labels = rng.integers(0, 7, 100)
    perm = rng.permutation(100)  # arbitrary label-value permutation
    relabeled = perm[labels]
    np.testing.assert_array_equal(
        canonical_partition(labels), canonical_partition(relabeled)
    )
