"""Distributed == single-device parity on an 8-virtual-device CPU mesh.

The TPU analog of the reference's (nonexistent) cluster testing: the same
shard_map code paths that run over ICI on real chips run here on fake
devices (SURVEY §4, "multi-chip-without-a-cluster").
"""

import jax
import numpy as np
import pytest

from graphmine_tpu.graph.container import build_graph
from graphmine_tpu.ops.cc import connected_components
from graphmine_tpu.ops.lpa import label_propagation
from graphmine_tpu.parallel import make_mesh
from graphmine_tpu.parallel.sharded import (
    partition_graph,
    shard_graph_arrays,
    sharded_connected_components,
    sharded_label_propagation,
)


def _random_graph(rng, v, e):
    return rng.integers(0, v, e).astype(np.int32), rng.integers(0, v, e).astype(np.int32)


@pytest.fixture(scope="module")
def mesh8():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return make_mesh(8)


def test_sharded_lpa_matches_single_device(mesh8, rng):
    for v, e in [(50, 200), (97, 513), (8, 8)]:
        src, dst = _random_graph(rng, v, e)
        g = build_graph(src, dst, num_vertices=v)
        want = np.asarray(label_propagation(g, max_iter=4))
        sg = shard_graph_arrays(
            partition_graph(g, mesh=mesh8, build_bucket_plan=True), mesh8
        )
        got = np.asarray(sharded_label_propagation(sg, mesh8, max_iter=4))
        np.testing.assert_array_equal(got, want)


def test_sharded_cc_matches_single_device(mesh8, rng):
    for v, e in [(50, 60), (200, 150)]:
        src, dst = _random_graph(rng, v, e)
        g = build_graph(src, dst, num_vertices=v)
        want = np.asarray(connected_components(g))
        sg = shard_graph_arrays(partition_graph(g, mesh=mesh8), mesh8)
        got = np.asarray(sharded_connected_components(sg, mesh8))
        np.testing.assert_array_equal(got, want)


def test_sharded_bundled_parity(mesh8, bundled_graph):
    want = np.asarray(label_propagation(bundled_graph, max_iter=5))
    sg = shard_graph_arrays(partition_graph(bundled_graph, mesh=mesh8), mesh8)
    got = np.asarray(sharded_label_propagation(sg, mesh8, max_iter=5))
    np.testing.assert_array_equal(got, want)
    want_cc = np.asarray(connected_components(bundled_graph))
    got_cc = np.asarray(sharded_connected_components(sg, mesh8))
    np.testing.assert_array_equal(got_cc, want_cc)


def test_mesh_size_one(rng):
    mesh = make_mesh(1)
    src, dst = _random_graph(rng, 30, 100)
    g = build_graph(src, dst, num_vertices=30)
    sg = partition_graph(g, mesh=mesh)
    got = np.asarray(sharded_label_propagation(sg, mesh, max_iter=3))
    want = np.asarray(label_propagation(g, max_iter=3))
    np.testing.assert_array_equal(got, want)


def test_partition_shapes(rng):
    src, dst = _random_graph(rng, 100, 400)
    sg = partition_graph(src, dst, num_vertices=100, num_shards=8)
    assert sg.msg_recv_local.shape == sg.msg_send.shape
    assert sg.msg_recv_local.shape[0] == 8
    assert sg.padded_vertices >= 100
    # every real message is preserved exactly once
    total_real = int((np.asarray(sg.msg_recv_local) < sg.chunk_size).sum())
    assert total_real == 2 * 400


def test_sharded_pagerank_matches_single_device(mesh8):
    import numpy as np
    from graphmine_tpu.graph.container import build_graph
    from graphmine_tpu.ops.degrees import out_degrees
    from graphmine_tpu.ops.pagerank import pagerank
    from graphmine_tpu.parallel.sharded import (
        partition_graph,
        shard_graph_arrays,
        sharded_pagerank,
    )

    rng = np.random.default_rng(11)
    v, e = 200, 800
    src = rng.integers(0, v, e).astype(np.int32)
    dst = rng.integers(0, v, e).astype(np.int32)
    g = build_graph(src, dst, num_vertices=v, symmetric=False)
    sg = shard_graph_arrays(partition_graph(g, mesh=mesh8), mesh8)
    od = out_degrees(g)
    dist = np.asarray(sharded_pagerank(sg, mesh8, od, max_iter=80))
    single = np.asarray(pagerank(g, max_iter=80))
    np.testing.assert_allclose(dist, single, atol=1e-5)
    assert abs(dist.sum() - 1.0) < 1e-4


def test_multislice_mesh_lpa_cc_pagerank_parity():
    """2-D (dcn, ici) mesh: same results as single-device on all three ops."""
    import numpy as np
    from graphmine_tpu.graph.container import build_graph
    from graphmine_tpu.ops.cc import connected_components
    from graphmine_tpu.ops.degrees import out_degrees
    from graphmine_tpu.ops.lpa import label_propagation
    from graphmine_tpu.ops.pagerank import pagerank
    from graphmine_tpu.parallel.mesh import make_multislice_mesh
    from graphmine_tpu.parallel.sharded import (
        partition_graph,
        shard_graph_arrays,
        sharded_connected_components,
        sharded_label_propagation,
        sharded_pagerank,
    )

    mesh = make_multislice_mesh(2, 4)  # 2 "slices" x 4 "chips" of CPU devices
    rng = np.random.default_rng(5)
    v, e = 160, 640
    src = rng.integers(0, v, e).astype(np.int32)
    dst = rng.integers(0, v, e).astype(np.int32)

    g_sym = build_graph(src, dst, num_vertices=v)
    sg = shard_graph_arrays(partition_graph(g_sym, mesh=mesh), mesh)
    np.testing.assert_array_equal(
        np.asarray(sharded_label_propagation(sg, mesh, max_iter=5)),
        np.asarray(label_propagation(g_sym, max_iter=5)),
    )
    np.testing.assert_array_equal(
        np.asarray(sharded_connected_components(sg, mesh)),
        np.asarray(connected_components(g_sym)),
    )

    g_dir = build_graph(src, dst, num_vertices=v, symmetric=False)
    sgd = shard_graph_arrays(partition_graph(g_dir, mesh=mesh), mesh)
    np.testing.assert_allclose(
        np.asarray(sharded_pagerank(sgd, mesh, out_degrees(g_dir), max_iter=60)),
        np.asarray(pagerank(g_dir, max_iter=60)),
        atol=1e-5,
    )


def test_determinism_across_runs_and_shardings(mesh8):
    """SURVEY §5 race-detection story: same input => bit-identical labels
    across repeated runs and across sharding layouts."""
    import numpy as np
    from graphmine_tpu.graph.container import build_graph
    from graphmine_tpu.ops.lpa import label_propagation
    from graphmine_tpu.parallel.sharded import (
        partition_graph,
        shard_graph_arrays,
        sharded_label_propagation,
    )

    rng = np.random.default_rng(42)
    v, e = 120, 480
    src = rng.integers(0, v, e).astype(np.int32)
    dst = rng.integers(0, v, e).astype(np.int32)
    g = build_graph(src, dst, num_vertices=v)
    a = np.asarray(label_propagation(g, max_iter=4))
    b = np.asarray(label_propagation(g, max_iter=4))
    np.testing.assert_array_equal(a, b)
    sg = shard_graph_arrays(partition_graph(g, mesh=mesh8), mesh8)
    c = np.asarray(sharded_label_propagation(sg, mesh8, max_iter=4))
    np.testing.assert_array_equal(a, c)


def test_sort_fallback_body_matches_bucketed(mesh8):
    """The sort-based shard body (default partition) and the bucketed one
    (build_bucket_plan=True) must both agree with the single-device kernel."""
    import numpy as np
    from graphmine_tpu.graph.container import build_graph
    from graphmine_tpu.ops.lpa import label_propagation
    from graphmine_tpu.parallel.sharded import (
        partition_graph,
        shard_graph_arrays,
        sharded_label_propagation,
    )

    rng = np.random.default_rng(7)
    v, e = 96, 400
    src = rng.integers(0, v, e).astype(np.int32)
    dst = rng.integers(0, v, e).astype(np.int32)
    g = build_graph(src, dst, num_vertices=v)
    want = np.asarray(label_propagation(g, max_iter=4))

    fast = partition_graph(g, mesh=mesh8, build_bucket_plan=True)
    assert fast.bucket_send
    slow = partition_graph(g, mesh=mesh8)
    assert not slow.bucket_send  # opt-in: default partition has no plan
    got_fast = np.asarray(sharded_label_propagation(
        shard_graph_arrays(fast, mesh8), mesh8, max_iter=4))
    got_slow = np.asarray(sharded_label_propagation(
        shard_graph_arrays(slow, mesh8), mesh8, max_iter=4))
    np.testing.assert_array_equal(want, got_fast)
    np.testing.assert_array_equal(want, got_slow)

    # lpa_only placement: CSR arrays dropped (no idle HBM), LPA still exact
    import pytest

    lean = shard_graph_arrays(fast, mesh8, lpa_only=True)
    assert lean.msg_send is None and lean.degrees is None
    got_lean = np.asarray(sharded_label_propagation(lean, mesh8, max_iter=4))
    np.testing.assert_array_equal(want, got_lean)
    with pytest.raises(ValueError, match="lpa_only"):
        shard_graph_arrays(slow, mesh8, lpa_only=True)


def test_weighted_sharded_lpa_matches_single_device(mesh8):
    """Weighted LPA through the sort shard body == single-device weighted
    kernel; the bucketed plan and ring schedule refuse weighted graphs."""
    import numpy as np
    import pytest

    from graphmine_tpu.graph.container import build_graph
    from graphmine_tpu.ops.lpa import label_propagation
    from graphmine_tpu.parallel.ring import ring_label_propagation
    from graphmine_tpu.parallel.sharded import (
        partition_graph,
        shard_graph_arrays,
        sharded_label_propagation,
    )

    rng = np.random.default_rng(17)
    v, e = 90, 500
    src = rng.integers(0, v, e).astype(np.int32)
    dst = rng.integers(0, v, e).astype(np.int32)
    w = rng.uniform(0.2, 5.0, e).astype(np.float32)
    g = build_graph(src, dst, num_vertices=v, edge_weights=w)
    want = np.asarray(label_propagation(g, max_iter=4))
    # sanity: weights actually change the outcome on this graph
    g_u = build_graph(src, dst, num_vertices=v)
    assert not np.array_equal(want, np.asarray(label_propagation(g_u, max_iter=4, plan=None)))

    sg = shard_graph_arrays(partition_graph(g, mesh=mesh8), mesh8)
    got = np.asarray(sharded_label_propagation(sg, mesh8, max_iter=4))
    np.testing.assert_array_equal(want, got)

    # r2: the ring schedule handles weights (weights are shard-local;
    # only labels travel the ring)
    ring = np.asarray(ring_label_propagation(sg, mesh8, max_iter=4))
    np.testing.assert_array_equal(want, ring)

    # r2: the bucketed shard body handles weights too. Exact weights
    # (multiples of 1/4) so the bucketed kernel's different summation
    # order can't produce near-tie rounding differences vs the sort body.
    w_x = (rng.integers(1, 16, e) / 4.0).astype(np.float32)
    g_x = build_graph(src, dst, num_vertices=v, edge_weights=w_x)
    want_x = np.asarray(label_propagation(g_x, max_iter=4, plan=None))
    sg_x = shard_graph_arrays(
        partition_graph(g_x, mesh=mesh8, build_bucket_plan=True), mesh8
    )
    assert sg_x.bucket_weight
    got_x = np.asarray(sharded_label_propagation(sg_x, mesh8, max_iter=4))
    np.testing.assert_array_equal(want_x, got_x)


def test_bucket_plan_matches_class_rows_reference():
    """The vectorized shard bucket-plan builder (VERDICT r1 item 6) is
    pinned bit-for-bit against a direct _class_rows implementation — the
    shared single source of truth for bucket-row semantics."""
    from graphmine_tpu.ops.bucketed_mode import _class_rows, _extend_widths
    from graphmine_tpu.parallel.sharded import _build_shard_bucket_plan, partition_graph

    def reference_plan(deg, send_pad, counts, chunk_size, d):
        sentinel_send = chunk_size * d
        widths = _extend_widths(int(deg.max(initial=1)))
        classes = np.searchsorted(widths, np.maximum(deg, 1))
        ptr = np.zeros((d, chunk_size), dtype=np.int64)
        np.cumsum(deg[:, :-1], axis=1, out=ptr[:, 1:])
        bucket_send, bucket_target = [], []
        for c in np.unique(classes[deg > 0]):
            w = int(widths[c])
            per_shard = [
                _class_rows(ptr[s], deg[s], deg[s] > 0, classes[s], c, w,
                            send_pad[s], sentinel_send, int(counts[s]))
                for s in range(d)
            ]
            n_c = max(len(rows) for rows, _ in per_shard)
            send_c = np.full((d, n_c, w), sentinel_send, dtype=np.int32)
            tgt_c = chunk_size + np.tile(np.arange(n_c, dtype=np.int32), (d, 1))
            for s, (rows, mat) in enumerate(per_shard):
                send_c[s, : len(rows)] = mat
                tgt_c[s, : len(rows)] = rows
            bucket_send.append(send_c)
            bucket_target.append(tgt_c)
        return tuple(bucket_send), tuple(bucket_target)

    for v, e, d, seed in ((64, 300, 4, 0), (257, 4000, 8, 1), (1000, 30000, 6, 2)):
        rng = np.random.default_rng(seed)
        # power-law-ish skew so several width classes (incl. hubs) appear
        raw = rng.pareto(1.1, size=2 * e)
        ids = np.minimum((raw * v / 20).astype(np.int64), v - 1).astype(np.int32)
        src, dst = ids[:e], ids[e:]
        sg = partition_graph(src, dst, num_vertices=v, num_shards=d,
                             build_bucket_plan=True)
        deg = np.asarray(sg.degrees)
        send_pad = np.asarray(sg.msg_send)
        counts = (np.asarray(sg.msg_recv_local) < sg.chunk_size).sum(axis=1)
        ref_send, ref_tgt = reference_plan(deg, send_pad, counts, sg.chunk_size, d)
        assert len(ref_send) == len(sg.bucket_send)
        for a, b in zip(sg.bucket_send, ref_send):
            np.testing.assert_array_equal(np.asarray(a), b)
        for a, b in zip(sg.bucket_target, ref_tgt):
            np.testing.assert_array_equal(np.asarray(a), b)


# ---------------------------------------------------------------------------
# shard-aware checkpoint: reshard-on-restore parity (ISSUE 2)
# ---------------------------------------------------------------------------


def test_reshard_restore_parity_lpa_cc(mesh8, rng, tmp_path):
    """Acceptance: kill at superstep N on 4 devices -> sharded manifest
    checkpoint -> restore onto 2 devices -> final LPA/CC labels
    bit-identical to the uninterrupted 4-device run."""
    import jax.numpy as jnp

    from graphmine_tpu.parallel.sharded import sharded_connected_components
    from graphmine_tpu.pipeline import checkpoint as ckpt

    mesh4, mesh2 = make_mesh(4), make_mesh(2)
    v, e = 120, 600
    src, dst = _random_graph(rng, v, e)
    g = build_graph(src, dst, num_vertices=v)
    sg4 = shard_graph_arrays(partition_graph(g, mesh=mesh4), mesh4)
    sg2 = shard_graph_arrays(partition_graph(g, mesh=mesh2), mesh2)

    # --- LPA: 6 supersteps uninterrupted vs 3 + (checkpoint, reshard) + 3
    want = np.asarray(sharded_label_propagation(sg4, mesh4, max_iter=6))
    mid = np.asarray(sharded_label_propagation(sg4, mesh4, max_iter=3))
    d = str(tmp_path / "ck_lpa")
    ckpt.save_sharded(d, mid, 3, num_shards=4)
    restored, it = ckpt.load_sharded(d)
    assert it == 3
    got = np.asarray(sharded_label_propagation(
        sg2, mesh2, max_iter=3, init_labels=jnp.asarray(restored)
    ))
    np.testing.assert_array_equal(got, want)

    # --- CC: fixpoint uninterrupted vs 2 bounded supersteps + resume
    want_cc = np.asarray(sharded_connected_components(sg4, mesh4))
    mid_cc = np.asarray(sharded_connected_components(sg4, mesh4, max_iter=2))
    d2 = str(tmp_path / "ck_cc")
    ckpt.save_sharded(d2, mid_cc, 2, num_shards=4)
    restored_cc, _ = ckpt.load_sharded(d2)
    got_cc = np.asarray(sharded_connected_components(
        sg2, mesh2, init_labels=jnp.asarray(restored_cc)
    ))
    np.testing.assert_array_equal(got_cc, want_cc)


def test_reshard_restore_parity_pagerank(mesh8, tmp_path):
    """PageRank mid-run reshard-restore (4 -> 2 devices): the resumed
    power iteration matches the uninterrupted trajectory."""
    import jax.numpy as jnp

    from graphmine_tpu.ops.degrees import out_degrees
    from graphmine_tpu.parallel.sharded import sharded_pagerank
    from graphmine_tpu.pipeline import checkpoint as ckpt

    mesh4, mesh2 = make_mesh(4), make_mesh(2)
    rng = np.random.default_rng(23)
    v, e = 150, 700
    src = rng.integers(0, v, e).astype(np.int32)
    dst = rng.integers(0, v, e).astype(np.int32)
    g = build_graph(src, dst, num_vertices=v, symmetric=False)
    od = out_degrees(g)
    sg4 = shard_graph_arrays(partition_graph(g, mesh=mesh4), mesh4)
    sg2 = shard_graph_arrays(partition_graph(g, mesh=mesh2), mesh2)

    # tol=0 pins the iteration count so 30 == 10 + 20 exactly
    want = np.asarray(sharded_pagerank(sg4, mesh4, od, max_iter=30, tol=0.0))
    mid = np.asarray(sharded_pagerank(sg4, mesh4, od, max_iter=10, tol=0.0))
    d = str(tmp_path / "ck_pr")
    ckpt.save_sharded(d, mid, 10, num_shards=4)
    restored, it = ckpt.load_sharded(d)
    assert it == 10 and restored.dtype == np.float32
    got = np.asarray(sharded_pagerank(
        sg2, mesh2, od, max_iter=20, tol=0.0,
        init_ranks=jnp.asarray(restored),
    ))
    np.testing.assert_allclose(got, want, atol=1e-6)
    assert abs(got.sum() - 1.0) < 1e-4


# ---------------------------------------------------------------------------
# in-loop divergence tripwires (ISSUE 2) — direct sharded-op API
# ---------------------------------------------------------------------------


@pytest.mark.faults
def test_tripwires_are_silent_on_clean_runs(mesh8, rng):
    """Armed tripwires must not change the labels/ranks of a healthy run
    (the guard is observation-only until it fires)."""
    from graphmine_tpu.parallel.sharded import sharded_connected_components

    v, e = 80, 350
    src, dst = _random_graph(rng, v, e)
    g = build_graph(src, dst, num_vertices=v)
    sg = shard_graph_arrays(partition_graph(g, mesh=mesh8), mesh8)
    np.testing.assert_array_equal(
        np.asarray(sharded_label_propagation(sg, mesh8, max_iter=4)),
        np.asarray(sharded_label_propagation(
            sg, mesh8, max_iter=4, tripwire_every=2
        )),
    )
    np.testing.assert_array_equal(
        np.asarray(sharded_connected_components(sg, mesh8)),
        np.asarray(sharded_connected_components(sg, mesh8, tripwire_every=3)),
    )


@pytest.mark.faults
def test_lpa_tripwire_catches_label_out_of_range(mesh8, rng):
    import jax.numpy as jnp

    from graphmine_tpu.pipeline.resilience import DivergenceError

    mesh4 = make_mesh(4)
    v, e = 64, 300
    src, dst = _random_graph(rng, v, e)
    g = build_graph(src, dst, num_vertices=v)
    sg = shard_graph_arrays(partition_graph(g, mesh=mesh4), mesh4)
    bad = np.arange(v, dtype=np.int32)
    bad[40:48] = 10_000  # wrapped gather index / torn collective
    with pytest.raises(DivergenceError) as ei:
        sharded_label_propagation(
            sg, mesh4, max_iter=4, init_labels=jnp.asarray(bad),
            tripwire_every=1,
        )
    assert ei.value.kind == "label_out_of_range"
    assert 0 <= ei.value.shard < 4 and ei.value.iteration >= 1


@pytest.mark.faults
def test_lpa_tripwire_catches_oscillation(mesh8):
    """Synchronous LPA livelock (bipartite period-2 swap) is detected
    instead of burning max_iter and returning a silently-unstable state."""
    from graphmine_tpu.pipeline.resilience import DivergenceError

    mesh2 = make_mesh(2)
    # K2: the two labels swap every superstep, forever
    src = np.array([0, 1], np.int32)
    dst = np.array([1, 0], np.int32)
    g = build_graph(src, dst, num_vertices=2)
    sg = shard_graph_arrays(partition_graph(g, mesh=mesh2), mesh2)
    with pytest.raises(DivergenceError) as ei:
        sharded_label_propagation(sg, mesh2, max_iter=6, tripwire_every=1)
    assert ei.value.kind == "oscillation"
    # unarmed: the historical behavior (runs to max_iter) is untouched
    out = np.asarray(sharded_label_propagation(sg, mesh2, max_iter=6))
    assert out.shape == (2,)


@pytest.mark.faults
def test_pagerank_tripwire_catches_nan_with_shard_attribution(mesh8):
    """NaN injected into ONE shard's messages is caught and attributed to
    that shard — NaN ends the loop 'converged' (delta>tol is False), so
    the exit guard must catch what the cadence guard misses."""
    import dataclasses

    from graphmine_tpu.ops.degrees import out_weights
    from graphmine_tpu.parallel.sharded import sharded_pagerank
    from graphmine_tpu.pipeline.resilience import DivergenceError

    mesh4 = make_mesh(4)
    rng = np.random.default_rng(3)
    v, e = 64, 300
    src = rng.integers(0, v, e).astype(np.int32)
    dst = rng.integers(0, v, e).astype(np.int32)
    w = rng.uniform(0.5, 2.0, e).astype(np.float32)
    g = build_graph(src, dst, num_vertices=v, edge_weights=w, symmetric=False)
    sg_host = partition_graph(g, mesh=mesh4)
    mw = np.asarray(sg_host.msg_weight).copy()
    mw[2, :4] = np.nan  # poison shard 2
    sg = shard_graph_arrays(
        dataclasses.replace(sg_host, msg_weight=mw), mesh4
    )
    ow = out_weights(g)
    # clean weighted run passes with the wire armed
    clean = np.asarray(sharded_pagerank(
        shard_graph_arrays(sg_host, mesh4), mesh4, ow, max_iter=20,
        tripwire_every=2,
    ))
    assert np.isfinite(clean).all()
    with pytest.raises(DivergenceError) as ei:
        sharded_pagerank(sg, mesh4, ow, max_iter=20, tripwire_every=2)
    assert ei.value.kind == "nonfinite_ranks" and ei.value.shard == 2


@pytest.mark.faults
def test_cc_tripwire_catches_out_of_range_init(mesh8):
    import jax.numpy as jnp

    from graphmine_tpu.parallel.sharded import sharded_connected_components
    from graphmine_tpu.pipeline.resilience import DivergenceError

    mesh4 = make_mesh(4)
    src = np.arange(0, 30, dtype=np.int32)
    dst = (src + 1) % 31
    g = build_graph(src, dst, num_vertices=31)
    sg = shard_graph_arrays(partition_graph(g, mesh=mesh4), mesh4)
    bad = np.arange(31, dtype=np.int32)
    bad[5] = -9  # min-propagation keeps a negative forever
    with pytest.raises(DivergenceError) as ei:
        sharded_connected_components(
            sg, mesh4, init_labels=jnp.asarray(bad), tripwire_every=1
        )
    assert ei.value.kind == "label_out_of_range"
