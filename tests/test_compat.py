"""The pyspark/graphframes shim (graphmine_tpu.compat): the reference script
must run VERBATIM on the TPU-native engine — every call site from
``Graphframes.py:1-120`` (parquet read, DataFrame preprocessing, the RDD
vertex idiom, UDFs, GraphFrame + labelPropagation, census loops)."""

import os
import runpy
import sys

import numpy as np
import pytest

from graphmine_tpu import compat

REFERENCE_SCRIPT = "/root/reference/CommunityDetection/Graphframes.py"


def write_tiny_outlinks(tmp_path):
    """CommonCrawl-shaped parquet: _c0..(parent URL, parent domain, child
    domain, child URL), one null-domain row (the reference filters it)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    edges = [
        ("a.com", "b.com"), ("a.com", "b.com"), ("a.com", "c.com"),
        ("b.com", "c.com"), ("c.com", "a.com"),
        ("x.org", "y.org"), ("y.org", "x.org"),
        ("z.org", "z2.org"),
    ]
    pd_, cd_ = zip(*edges)
    table = pa.table(
        {
            "_c0": [f"http://{p}/page" for p in pd_] + ["http://nul/"],
            "_c1": list(pd_) + [None],
            "_c2": list(cd_) + ["q.com"],
            "_c3": [f"http://{c}/page" for c in cd_] + ["http://q.com/"],
        }
    )
    d = tmp_path / "data" / "outlinks_pq"
    d.mkdir(parents=True)
    pq.write_table(table, d / "part-00000.snappy.parquet", compression="snappy")
    return len(edges)


@pytest.fixture
def shim():
    mods = compat.install()
    yield mods
    for name in mods:
        sys.modules.pop(name, None)


@pytest.mark.skipif(
    not os.path.exists(REFERENCE_SCRIPT), reason="reference tree not mounted"
)
def test_reference_script_runs_verbatim(tmp_path, capsys, monkeypatch, shim):
    n_edges = write_tiny_outlinks(tmp_path)
    monkeypatch.chdir(tmp_path)
    globs = runpy.run_path(REFERENCE_SCRIPT, run_name="__main__")
    out = capsys.readouterr().out

    # the script's own printed census (Graphframes.py:18, :54, :85, :120)
    assert out.splitlines()[0].strip() == str(n_edges + 1)  # raw row count
    assert "Communities in the Dataset." in out
    assert "Vertices in" in out

    # its computed state, reachable because runpy returns the globals
    df = globs["df"]
    assert df.count() == n_edges  # null-domain row filtered
    assert globs["ParentChild_id"].count() == 7  # distinct domains
    communities = globs["Community_Graphs"]
    labels = [r["label"] for r in communities.collect()]
    names = [r["name"] for r in communities.collect()]
    by_name = dict(zip(names, labels))
    # Synchronous LPA oscillates on tiny bipartite pieces (GraphX-parity
    # behavior), so assert at the component level: the a/b/c cluster and
    # the x/y pair never share labels.
    abc = {by_name["a.com"], by_name["b.com"], by_name["c.com"]}
    xy = {by_name["x.org"], by_name["y.org"]}
    assert not (abc & xy)
    assert by_name["b.com"] == by_name["c.com"]


def test_row_tuple_and_field_access(shim):
    r = compat.Row._make(("v1", 7), ["id", "n"])
    assert r[0] == "v1" and r["n"] == 7 and r.n == 7
    assert tuple(r) == ("v1", 7)
    assert r.asDict() == {"id": "v1", "n": 7}
    with pytest.raises(AttributeError):
        r.missing
    # pyspark constructor conventions
    named = compat.Row(id="a", n=1)
    assert named["id"] == "a" and tuple(named) == ("a", 1)
    bare = compat.Row("a", 1)
    assert bare[1] == 1
    with pytest.raises(KeyError):
        bare["id"]


def test_rdd_vertex_idiom(shim):
    from graphmine_tpu.table import Table

    t = Table(a=np.array(["p", "q", "p"], dtype=object),
              b=np.array(["q", "r", "r"], dtype=object))
    rdd = compat.DataFrame(t).rdd.flatMap(lambda x: x).distinct()
    assert rdd.count() == 3
    df = rdd.map(lambda x: (x.upper(), x)).toDF(["id", "name"])
    assert df.columns == ["id", "name"]
    assert [r["id"] for r in df.collect()] == ["P", "Q", "R"]


def test_udf_and_monotonic_id(shim):
    from pyspark.sql.functions import monotonically_increasing_id, udf

    from graphmine_tpu.table import Table

    up = udf(lambda s: s.upper())
    df = compat.DataFrame(Table(x=np.array(["a", None, "c"], dtype=object)))
    out = df.withColumn("up", up("x"))
    assert list(out._t["up"]) == ["A", None, "C"]
    ids = df.withColumn("rid", monotonically_increasing_id())
    assert list(ids._t["rid"]) == [0, 1, 2]


def test_session_plumbing_and_create_dataframe(shim):
    import pyspark
    from pyspark.sql import SQLContext, SparkSession

    sc = pyspark.SparkContext("local[*]")
    session = SparkSession.builder.appName("t").getOrCreate()
    sql = SQLContext(sc)
    df = sql.createDataFrame([("a", 1), ("b", 2)], ["k", "v"])
    assert df.count() == 2 and df.columns == ["k", "v"]
    assert session.createDataFrame([("z", 9)], ["k", "v"]).collect()[0]["k"] == "z"
    assert sc.parallelize([1, 2, 3]).map(lambda x: x * 2).collect() == [2, 4, 6]


def test_graphframe_facade_algorithms(shim):
    from graphframes import GraphFrame

    from graphmine_tpu.table import Table

    v = compat.DataFrame(Table(id=np.array(["a", "b", "c", "d"], dtype=object)))
    e = compat.DataFrame(Table(
        src=np.array(["a", "b", "c"], dtype=object),
        dst=np.array(["b", "c", "d"], dtype=object),
    ))
    g = GraphFrame(v, e)
    cc = g.connectedComponents()
    assert cc.select("component").distinct().count() == 1
    # pageRank returns a GraphFrame: results ride .vertices / .edges
    pr = g.pageRank(resetProbability=0.15, maxIter=10)
    assert pr.vertices.count() == 4 and "pagerank" in pr.vertices.columns
    assert "weight" in pr.edges.columns
    assert pr.edges.collect()[0]["weight"] == 1.0  # outdeg(a) == 1
    deg = g.degrees  # property, as in GraphFrames
    assert {r["id"]: r["degree"] for r in deg.collect()}["b"] == 2
    assert {r["id"]: r["inDegree"] for r in g.inDegrees.collect()}["d"] == 1
    # distance FROM each vertex TO the landmark, following edge direction
    sp = g.shortestPaths(landmarks=["d"])
    dists = {r["id"]: r["distances"] for r in sp.collect()}
    assert dists["a"] == {"d": 3} and dists["d"] == {"d": 0}
    assert g.shortestPaths(landmarks=["a"]).collect()[3]["distances"] == {}


def test_collect_returns_fresh_list(shim):
    from graphmine_tpu.table import Table

    df = compat.DataFrame(Table(a=np.array([3, 1, 2])))
    rows = df.collect()
    rows.sort()
    rows.append("junk")
    assert [r["a"] for r in df.collect()] == [3, 1, 2]


def test_dropna_modes_head_first(shim):
    from graphmine_tpu.table import Table

    df = compat.DataFrame(Table(
        a=np.array(["x", None, None], dtype=object),
        b=np.array(["y", "z", None], dtype=object),
    ))
    assert df.dropna().count() == 1            # how='any'
    assert df.dropna(how="all").count() == 2   # only the all-null row drops
    assert df.dropna(thresh=1).count() == 2
    assert df.head() == ("x", "y")
    assert df.head(1) == [("x", "y")]          # head(n) is always a list
    empty = df.filter(np.zeros(3, dtype=bool))
    assert empty.first() is None and empty.head(2) == []


def graph_with_attrs(shim):
    from graphframes import GraphFrame

    from graphmine_tpu.table import Table

    v = compat.DataFrame(Table(
        id=np.array(["a", "b", "c", "d", "e"], dtype=object),
        age=np.array([30, 40, 50, 60, 70]),
    ))
    e = compat.DataFrame(Table(
        src=np.array(["a", "b", "c", "a"], dtype=object),
        dst=np.array(["b", "c", "d", "e"], dtype=object),
        rel=np.array(["f", "f", "g", "g"], dtype=object),
    ))
    return GraphFrame(v, e)


def test_bfs_sql_expressions_paths_dataframe(shim):
    g = graph_with_attrs(shim)
    paths = g.bfs("age = 30", "age = 60")
    assert paths.columns == ["from", "e0", "v1", "e1", "v2", "e2", "to"]
    row = paths.collect()[0]
    assert row["from"] == "a" and row["to"] == "d"
    assert row["e0"] == ("a", "b") and row["v1"] == "b"
    # unreachable target set -> empty frame
    assert g.bfs("age = 60", "age = 30").count() == 0
    # from == to -> zero-hop path
    z = g.bfs("id = 'c'", "age > 45")
    assert z.collect()[0]["from"] == "c" and z.collect()[0]["to"] == "c"


def test_bfs_edge_filter_restricts_traversal(shim):
    """GraphFrames ``bfs(edgeFilter=...)``: only edges satisfying the SQL
    predicate are traversable; the vertex set is unchanged (was a
    NotImplementedError through r1)."""
    g = graph_with_attrs(shim)
    # a->b->c->d exists, but c->d has rel='g': filtering to rel='f' cuts it
    assert g.bfs("id = 'a'", "id = 'd'").count() > 0
    assert g.bfs("id = 'a'", "id = 'd'", edgeFilter="rel = 'f'").count() == 0
    # a->b->c survives the filter
    paths = g.bfs("id = 'a'", "id = 'c'", edgeFilter="rel = 'f'")
    row = paths.collect()[0]
    assert row["from"] == "a" and row["to"] == "c" and row["v1"] == "b"
    # predicates see id-valued src/dst (GraphFrames semantics)
    assert g.bfs("id = 'a'", "id = 'e'", edgeFilter="dst != 'e'").count() == 0


def test_find_motifs_dataframe(shim):
    g = graph_with_attrs(shim)
    m = g.find("(x)-[e]->(y); (y)-[]->(z)")
    assert set(m.columns) == {"x", "e", "y", "z"}
    rows = {(r["x"], r["y"], r["z"]) for r in m.collect()}
    assert rows == {("a", "b", "c"), ("b", "c", "d")}
    first = m.collect()[0]
    assert first["e"] == (first["x"], first["y"])  # edge cells are id pairs


def test_filter_vertices_edges_sql(shim):
    g = graph_with_attrs(shim)
    sub = g.filterVertices("age < 55")
    assert sub.vertices.count() == 3
    assert sub.edges.count() == 2  # a->b, b->c survive
    # filtered frames speak vertex ids, never engine indices or bookkeeping
    assert "orig" not in sub.vertices.columns
    assert {(r["src"], r["dst"]) for r in sub.edges.collect()} == {
        ("a", "b"), ("b", "c")
    }
    sub2 = g.filterEdges("rel = 'g'")
    assert sub2.edges.count() == 2
    # src/dst in edge predicates are id-valued (GraphFrames semantics)
    assert g.filterEdges("src = 'a'").edges.count() == 2
    iso = sub2.dropIsolatedVertices()
    assert iso.vertices.count() == 4  # b drops (only 'f' edges touched it)
    lp = sub.labelPropagation(maxIter=2)
    assert "orig" not in lp.columns


def test_bfs_max_path_length_zero_means_no_traversal(shim):
    g = graph_with_attrs(shim)
    assert g.bfs("id = 'a'", "id = 'd'", maxPathLength=0).count() == 0
    z = g.bfs("age > 25", "age < 45", maxPathLength=0)
    assert {r["from"] for r in z.collect()} == {"a", "b"}  # zero-hop overlap


def test_column_expressions(shim):
    from pyspark.sql import functions as F

    from graphmine_tpu.table import Table

    df = compat.DataFrame(Table(
        name=np.array(["ann", "bob", None, "dan"], dtype=object),
        age=np.array([30.0, 40.0, 50.0, np.nan]),
        city=np.array(["x", "y", "x", "y"], dtype=object),
    ))
    # comparisons, boolean algebra, null semantics
    assert df.filter(F.col("age") > 35).count() == 2
    assert df.filter((F.col("age") > 35) & (F.col("city") == "y")).count() == 1
    assert df.filter(F.col("name").isNull()).count() == 1
    assert df.filter(F.col("age").isNotNull() & ~(F.col("city") == "x")).count() == 1
    assert df.filter(df.name.startswith("a")).count() == 1  # attribute access
    assert df.filter(F.col("name").isin("ann", "dan")).count() == 2
    # arithmetic + withColumn + alias/select
    out = df.withColumn("next_age", F.col("age") + 1)
    assert out.collect()[0]["next_age"] == 31.0
    sel = df.select(F.col("age").alias("years"), "city")
    assert sel.columns == ["years", "city"]
    # when/otherwise
    flagged = df.withColumn(
        "grp", F.when(F.col("age") < 35, "young").otherwise("old"))
    assert [r["grp"] for r in flagged.collect()] == ["young", "old", "old", "old"]
    # lit + cast
    casted = df.select(F.col("age").cast("string").alias("s"))
    assert casted.collect()[0]["s"] == "30.0"


def test_column_aggregates_and_sort_desc(shim):
    from pyspark.sql import functions as F

    from graphmine_tpu.table import Table

    df = compat.DataFrame(Table(
        g=np.array(["a", "a", "b"], dtype=object),
        v=np.array([1.0, 3.0, 5.0]),
    ))
    agg = df.groupBy("g").agg(F.sum("v").alias("total"), F.count("*"),
                              F.max("v"))
    row = {r["g"]: (r["total"], r["count(*)"], r["max(v)"]) for r in agg.collect()}
    assert row["a"] == (4.0, 2, 3.0) and row["b"] == (5.0, 1, 5.0)
    top = df.sort(F.desc("v")).collect()[0]
    assert top["v"] == 5.0
    mixed = df.sort(F.asc("g"), F.desc("v")).collect()
    assert [r["v"] for r in mixed] == [3.0, 1.0, 5.0]
    # global agg with Column markers (df.agg, no groupBy)
    tot = df.agg(F.sum("v").alias("total"), F.count("*"))
    assert tot.collect()[0]["total"] == 9.0 and tot.collect()[0]["count(*)"] == 3
    # ascending list form
    lst = df.sort("g", "v", ascending=[True, False]).collect()
    assert [r["v"] for r in lst] == [3.0, 1.0, 5.0]
    # desc-major with asc-minor stays stable per key
    t2 = compat.DataFrame(Table(
        a=np.array([1, 1, 2]), b=np.array([2.0, 1.0, 0.0])))
    out = t2.sort(F.desc("a"), F.asc("b")).collect()
    assert [(r["a"], r["b"]) for r in out] == [(2, 0.0), (1, 1.0), (1, 2.0)]


def test_column_null_propagation_and_casts(shim):
    from pyspark.sql import functions as F

    from graphmine_tpu.table import Table

    df = compat.DataFrame(Table(
        x=np.array([1, None, 3], dtype=object),       # post-join nullable int
        age=np.array([30.0, np.nan]).repeat([2, 1]),  # [30, 30, nan]
    ))
    y = df.withColumn("y", F.col("x") + 1).collect()
    assert y[0]["y"] == 2.0 and np.isnan(y[1]["y"]) and y[2]["y"] == 4.0
    s = df.select(F.col("age").cast("string").alias("s")).collect()
    assert s[2]["s"] is None  # null never becomes the string 'nan'
    i = df.select(F.col("age").cast("int").alias("i")).collect()
    assert i[0]["i"] == 30 and i[2]["i"] is None
    # isin with incomparable value types is SQL-false, not a crash
    assert df.filter(F.col("age").isin("a", "b")).count() == 0
    with pytest.raises(ValueError, match="duplicate"):
        df.select("x", F.col("x"))


def test_csv_reader_spark_string_default(shim, tmp_path):
    from graphmine_tpu.table import Table
    from pyspark.sql import SparkSession

    p = str(tmp_path / "d.csv")
    compat.DataFrame(Table(v=np.array([1, 2]))).write.csv(p, header=True)
    session = SparkSession.builder.getOrCreate()
    assert session.read.csv(p, header=True)._t.schema["v"] == np.dtype(object)
    assert session.read.csv(p, header=True, inferSchema=True)._t.schema[
        "v"] == np.dtype(np.int64)


def test_pagerank_on_filtered_frame_hides_bookkeeping(shim):
    g = graph_with_attrs(shim)
    pr = g.filterVertices("age < 55").pageRank(maxIter=5)
    assert "orig" not in pr.vertices.columns
    assert "pagerank" in pr.vertices.columns


def test_write_modes_and_reader_csv(shim, tmp_path):
    from graphmine_tpu.table import Table

    df = compat.DataFrame(Table(k=np.array(["a", "b"], dtype=object),
                                v=np.array([1, 2])))
    p = str(tmp_path / "out.parquet")
    df.write.parquet(p)
    with pytest.raises(FileExistsError):
        df.write.parquet(p)  # Spark default mode: error
    df.write.mode("overwrite").parquet(p)
    df.write.mode("ignore").parquet(p)  # silently keeps existing
    from pyspark.sql import SparkSession

    back = SparkSession.builder.getOrCreate().read.parquet(p)
    assert back.count() == 2 and back.columns == ["k", "v"]
    c = str(tmp_path / "out.csv")
    df.write.csv(c, header=True)
    csv_back = SparkSession.builder.getOrCreate().read.csv(c, header=True)
    assert [r["k"] for r in csv_back.collect()] == ["a", "b"]


def test_aggregate_messages_am_namespace(shim):
    """The canonical GraphFrames aggregateMessages example: sum of
    neighbors' ages per user, on the stock friends graph."""
    from graphframes.examples import Graphs
    from graphframes.lib import AggregateMessages as AM
    from pyspark.sql import functions as F

    g = Graphs.friends()
    out = g.aggregateMessages(
        F.sum(AM.msg).alias("summedAges"),
        sendToSrc=AM.dst["age"],
        sendToDst=AM.src["age"],
    )
    got = {r["id"]: r["summedAges"] for r in out.collect()}
    # hand-checked from the canonical graph (GraphFrames user guide)
    assert got["a"] == 36 + 29 + 32   # Bob + David + Esther
    assert got["c"] == 36 + 36 + 36   # Bob, Fanny (in-edges) + Bob (c->b)
    assert "g" not in got  # Gabby has no edges: dropped, as in GraphFrames

    # mean + count in one call, attribute-style access
    out2 = g.aggregateMessages(
        F.avg(AM.msg).alias("m"), F.count(AM.msg).alias("n"),
        sendToDst=AM.src.age,
    )
    got2 = {r["id"]: (r["m"], r["n"]) for r in out2.collect()}
    assert got2["b"] == (32.0, 2)  # Alice (34) and Charlie (30) -> Bob
    with pytest.raises(ValueError):
        g.aggregateMessages(F.sum(AM.msg))
    with pytest.raises(TypeError):
        g.aggregateMessages("not a marker", sendToDst=AM.src.age)
    with pytest.raises(TypeError, match="AM.msg"):
        g.aggregateMessages(F.sum(AM.src["age"]), sendToDst=AM.src.age)
    with pytest.raises(TypeError, match="must be Columns"):
        g.aggregateMessages(F.sum(AM.msg).alias("s"), sendToDst="src.age")
    # frames without explicit vertex columns still expose AM.dst['id']
    import numpy as _np

    bare = compat._wrap_engine(
        __import__("graphmine_tpu.frames", fromlist=["GraphFrame"]).GraphFrame(
            (_np.array([0, 1], _np.int32), _np.array([1, 0], _np.int32)))
    )
    s = bare.aggregateMessages(F.sum(AM.msg).alias("s"), sendToDst=AM.dst["id"])
    assert {r["id"]: r["s"] for r in s.collect()} == {0: 0, 1: 1}


def test_friends_graph_shape(shim):
    from graphframes.examples import Graphs

    g = Graphs.friends()
    assert g.vertices.count() == 7 and g.edges.count() == 8
    assert {r["relationship"] for r in g.edges.collect()} == {"friend", "follow"}
    # Gabby is isolated
    assert g.dropIsolatedVertices().vertices.count() == 6


def test_install_refuses_real_pyspark(shim, monkeypatch):
    import types

    fake_real = types.ModuleType("pyspark")
    fake_real.__doc__ = "Apache Spark Python API"
    monkeypatch.setitem(sys.modules, "pyspark", fake_real)
    with pytest.raises(RuntimeError, match="real pyspark"):
        compat.install()
    compat.install(force=True)  # explicit override allowed
