"""Compute-plane performance observability (ISSUE 12, marker `perf`):

- the analytical cost model exact against HAND-COMPUTED tiny plans for
  all three superstep families (fused + sharded, weighted) and both LOF
  impls — the derivation reads the plan objects, so these tests pin the
  byte/slot accounting to paper arithmetic;
- roofline anchor overrides (env / file) and provenance;
- superstep_timing achieved-vs-model attribution: ops seams, the driver
  e2e (every LPA/CC phase emits a schema-valid record joinable to its
  phase span — THE acceptance criterion), and the sharded driver path's
  exchange split;
- obs_report's roofline section + the waterfall threshold/model lines;
- tools/bench_diff.py: regression / no-regression / tolerance-edge gates
  on synthetic BENCH files, the committed BENCH_r01–r05 trajectory
  self-check, the silicon-capture manifest, the blocked-crossover
  suggestion, and `bench.py --list-missing`;
- schema: half-stamped cost sub-records fail validation; schema_lint
  flags inline cost=... literals outside the single builder.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from graphmine_tpu.graph.container import build_graph
from graphmine_tpu.obs import costmodel
from graphmine_tpu.obs.schema import COST_KEYS, validate_record, validate_records
from graphmine_tpu.obs.spans import Tracer
from graphmine_tpu.pipeline.metrics import MetricsSink

from conftest import cached_edgelist

pytestmark = pytest.mark.perf

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO, "tools")
if TOOLS not in sys.path:
    sys.path.insert(0, TOOLS)

import bench_diff  # noqa: E402

# Deterministic anchors for the hand-computed cases (the seeds are real
# measurements; tests want round numbers).
ANCHORS = {
    "gather_slots_per_sec": {"v": 100.0, "src": "test"},
    "binned_slots_per_sec": {"v": 50.0, "src": "test"},
    "exchange_bytes_per_sec": {"v": 400.0, "src": "test"},
    "lof_exact_pairs_per_sec": {"v": 1000.0, "src": "test"},
    "lof_ivf_points_per_sec": {"v": 50.0, "src": "test"},
}


def ring4():
    """Directed 4-ring; symmetric message CSR => M=8, every degree 2."""
    src = np.array([0, 1, 2, 3], np.int32)
    dst = np.array([1, 2, 3, 0], np.int32)
    return build_graph(src, dst, num_vertices=4)


def star21(weights=None):
    """Hub of degree 21 (falls in the ladder's 20->22 gap): bucketed rows
    are 21x1 (leaves) + 1x22 (hub) = 43 padded slots over M=42."""
    src = np.zeros(21, np.int32)
    dst = np.arange(1, 22, dtype=np.int32)
    return build_graph(src, dst, num_vertices=22, edge_weights=weights)


# ---------------------------------------------------------------------------
# cost model: hand-computed exactness
# ---------------------------------------------------------------------------


def test_sort_cost_exact():
    c = costmodel.superstep_cost(
        "lpa_superstep", "sort", 4, 8, 4, anchors=ANCHORS
    )
    assert (c.slots, c.padded_slots) == (8, 8)
    assert c.bytes_gathered == 4 * 8          # one int32 label per slot
    assert c.bytes_scattered == 4 * 4         # V results
    assert c.padding_overhead == 1.0
    assert c.exchange_bytes == 0
    assert c.predicted_seconds == pytest.approx(8 / 100.0)
    assert c.predicted_per_chip == pytest.approx(4 / (8 / 100.0))
    assert c.unit == "edges/s/chip"


def test_weighted_sort_cost_doubles_gathered_bytes():
    c = costmodel.superstep_cost(
        "lpa_superstep", "sort", 4, 8, 4, weighted=True, anchors=ANCHORS
    )
    assert c.bytes_gathered == 2 * 4 * 8      # label + float32 weight
    assert c.predicted_seconds == pytest.approx(16 / 100.0)


def test_bucketed_cost_exact_ring_and_star():
    from graphmine_tpu.ops.bucketed_mode import BucketedModePlan

    plan = BucketedModePlan.from_graph(ring4(), with_send=True)
    c = costmodel.superstep_cost(
        "lpa_superstep", "bucketed", 4, 8, 4, plan=plan, anchors=ANCHORS
    )
    # 4 vertices x width-2 rows = 8 slots, zero padding on the ring
    assert (c.family, c.padded_slots, c.padding_overhead) == ("bucketed", 8, 1.0)
    assert c.predicted_seconds == pytest.approx(8 / 100.0)

    plan2 = BucketedModePlan.from_graph(star21(), with_send=True)
    c2 = costmodel.superstep_cost(
        "lpa_superstep", "bucketed", 22, 42, 21, plan=plan2, anchors=ANCHORS
    )
    # hand-computed: 21 leaves x w=1 + hub x w=22 (deg 21 pads 1 slot)
    assert c2.padded_slots == 21 * 1 + 1 * 22 == 43
    assert c2.padding_overhead == pytest.approx(43 / 42)
    assert c2.bytes_gathered == 4 * 43
    assert c2.predicted_seconds == pytest.approx(43 / 100.0)


def test_blocked_cost_exact_and_weighted():
    from graphmine_tpu.ops.blocking import BlockedPlan

    plan = BlockedPlan.from_graph(ring4())
    c = costmodel.superstep_cost(
        "lpa_superstep", "blocked", 4, 8, 4, plan=plan, anchors=ANCHORS
    )
    # stream pass M=8 at the binned rate + 8 reduce-row slots at gather
    assert (c.family, c.slots, c.padded_slots) == ("blocked", 8, 16)
    assert c.bytes_gathered == 4 * (8 + 8)
    assert c.bytes_scattered == 4 * 8 + 4 * 4   # tile scatter + writeback
    assert c.predicted_seconds == pytest.approx(8 / 50.0 + 8 / 100.0)

    gw = star21(weights=np.ones(21, np.float32) * 2.0)
    planw = BlockedPlan.from_graph(gw)
    cw = costmodel.superstep_cost(
        "lpa_superstep", "blocked", 22, 42, 21, plan=planw, anchors=ANCHORS
    )
    # weight payload rides the reduce rows only (stream carries labels)
    assert cw.padded_slots == 42 + 43
    assert cw.bytes_gathered == 4 * (42 + 43 * 2)
    assert cw.predicted_seconds == pytest.approx(42 / 50.0 + 43 * 2 / 100.0)
    # explicit weighted=False models a weight-blind op on the same plan
    cc = costmodel.superstep_cost(
        "cc_superstep", "blocked", 22, 42, 21, plan=planw, weighted=False,
        anchors=ANCHORS,
    )
    assert cc.bytes_gathered == 4 * (42 + 43)


def test_sharded_cost_exact_all_families():
    from graphmine_tpu.parallel.sharded import partition_graph

    src = np.arange(16, dtype=np.int32)
    dst = (src + 1) % 16
    g = build_graph(src, dst, num_vertices=16, to_device=False)

    # sort shard body: padded [2, 16] message arrays, Vc=8
    sg = partition_graph(g, num_shards=2)
    c = costmodel.sharded_superstep_cost(
        "lpa_superstep", sg, 16, num_messages=32, anchors=ANCHORS
    )
    assert (c.family, c.devices) == ("sort", 2)
    assert c.padded_slots == 16                 # Mp per shard
    assert c.exchange_bytes == 4 * 8 * (2 - 1)  # Vc to each of D-1 peers
    assert c.compute_seconds == pytest.approx(16 / 100.0)
    assert c.exchange_seconds == pytest.approx(32 / 400.0)
    assert c.predicted_seconds == pytest.approx(0.16 + 0.08)
    assert c.predicted_per_chip == pytest.approx(16 / (0.24 * 2))

    # stacked bucket plan: [2, 8, 2] rows -> 16 padded slots per chip
    sgb = partition_graph(g, num_shards=2, build_bucket_plan=True)
    cb = costmodel.sharded_superstep_cost(
        "lpa_superstep", sgb, 16, num_messages=32, anchors=ANCHORS
    )
    assert (cb.family, cb.padded_slots) == ("bucketed", 16)
    assert cb.compute_seconds == pytest.approx(16 / 100.0)

    # blocked bin groups: stream Mp=16 + [2, 8, 2] reduce rows
    sgk = partition_graph(g, num_shards=2, build_blocked_plan=True)
    ck = costmodel.sharded_superstep_cost(
        "lpa_superstep", sgk, 16, num_messages=32, anchors=ANCHORS
    )
    assert (ck.family, ck.padded_slots) == ("blocked", 16 + 16)
    assert ck.compute_seconds == pytest.approx(16 / 50.0 + 16 / 100.0)


def test_lof_cost_exact():
    ce = costmodel.lof_cost("exact", 100, 5, features=8, anchors=ANCHORS)
    assert ce.slots == 100 * 100
    assert ce.bytes_gathered == 4 * 8 * 100 * 100
    assert ce.predicted_seconds == pytest.approx(10000 / 1000.0)
    assert ce.predicted_per_chip == pytest.approx(10.0)
    assert ce.unit == "points/s/chip"
    ci = costmodel.lof_cost("ivf", 100, 5, features=8, anchors=ANCHORS)
    assert ci.predicted_seconds == pytest.approx(100 / 50.0)
    assert ci.slots == 100 * 5
    # the ring-sharded exact scorer splits the pair work
    c2 = costmodel.lof_cost("exact", 100, 5, devices=2, anchors=ANCHORS)
    assert c2.slots == 100 * 100 // 2
    with pytest.raises(ValueError):
        costmodel.lof_cost("pallas", 100, 5)


# ---------------------------------------------------------------------------
# roofline anchors: seeds, env/file overrides, provenance
# ---------------------------------------------------------------------------


def test_roofline_seeds_carry_provenance():
    a = costmodel.rooflines()
    assert a["gather_slots_per_sec"]["v"] == pytest.approx(1.32e8)
    assert "BENCH_r04/r05" in a["gather_slots_per_sec"]["src"]
    # the unmeasured seeds SAY they are unmeasured
    assert "unmeasured" in a["exchange_bytes_per_sec"]["src"]
    assert "blocking" in a["binned_slots_per_sec"]["src"]


def test_roofline_env_and_file_overrides(monkeypatch, tmp_path):
    monkeypatch.setenv("GRAPHMINE_ROOFLINE_GATHER_SLOTS_PER_SEC", "5e8")
    a = costmodel.rooflines()
    assert a["gather_slots_per_sec"] == {"v": 5e8, "src": "env"}
    # file override: the re-seed path a fresh silicon capture uses
    p = tmp_path / "roof.json"
    p.write_text(json.dumps(
        {"binned_slots_per_sec": 2.5e8, "unknown_anchor": 1.0}
    ))
    monkeypatch.setenv("GRAPHMINE_ROOFLINE_FILE", str(p))
    a = costmodel.rooflines()
    assert a["binned_slots_per_sec"]["v"] == 2.5e8
    assert a["binned_slots_per_sec"]["src"].startswith("file:")
    # env still beats file for the anchor both set
    assert a["gather_slots_per_sec"]["src"] == "env"
    # malformed file raises instead of silently un-anchoring the model
    p.write_text("[1, 2]")
    with pytest.raises(ValueError):
        costmodel.rooflines()


# ---------------------------------------------------------------------------
# cost sub-record schema: all-or-nothing like trace identity
# ---------------------------------------------------------------------------


def test_cost_record_shape_matches_schema():
    c = costmodel.superstep_cost("lpa_superstep", "sort", 4, 8, 4)
    assert set(c.record().keys()) == set(COST_KEYS)


def test_half_stamped_cost_fails_validation():
    c = costmodel.superstep_cost("lpa_superstep", "sort", 4, 8, 4)
    rec = {"phase": "plan_build", "t": 1.0, "op": "x", "family": "sort",
           "seconds": 0.1, "padded_slots_per_edge": 2.0, "cost": c.record()}
    assert validate_record(rec) == []
    broken = dict(rec)
    broken["cost"] = {k: 1 for k in sorted(COST_KEYS)[:4]}
    problems = validate_record(broken)
    assert problems and "half-stamped cost" in problems[0]
    broken["cost"] = "not-a-dict"
    assert any("not dict" in p for p in validate_record(broken))


def test_schema_lint_flags_inline_cost_literals(tmp_path):
    import schema_lint

    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "bad.py").write_text(
        'sink.emit("plan_build", cost={"family": "sort"})\n'
        "# a comment mentioning cost={...} must NOT trip the lint\n"
        'sink.emit("plan_build", cost=dict(family="sort"))\n'
        'sink.emit("plan_build", cost=estimate.record())\n'
    )
    hits = schema_lint.scan_inline_costs(str(pkg))
    assert [line for _, line in hits] == [1, 3]
    # and the real package is clean (the builder lives in costmodel.py)
    assert schema_lint.scan_inline_costs() == []


def test_bench_diff_tiers_match_bench_py():
    import bench

    assert tuple(bench._TIER_ORDER) == bench_diff.ALL_TIERS


# ---------------------------------------------------------------------------
# superstep_timing: ops seams
# ---------------------------------------------------------------------------


def _sink():
    return MetricsSink(tracer=Tracer())


def _timings(m, op=None):
    return [r for r in m.records if r["phase"] == "superstep_timing"
            and (op is None or r["op"] == op)]


def test_ops_seams_emit_schema_valid_timing():
    from graphmine_tpu.ops.cc import connected_components
    from graphmine_tpu.ops.lpa import label_propagation
    from graphmine_tpu.ops.pagerank import pagerank

    g = ring4()
    m = _sink()
    labels = label_propagation(g, max_iter=3, sink=m)
    assert labels.shape == (4,)
    (t,) = _timings(m, "lpa_superstep")
    assert t["window"] == 3 and t["family"] == "sort"
    assert t["edges_per_sec_per_chip"] > 0
    assert t["achieved_fraction"] > 0
    assert isinstance(t["cold_compile"], bool)
    # an identical warm call must NOT carry the cold-compile marker
    m_warm = _sink()
    label_propagation(g, max_iter=3, sink=m_warm)
    (tw,) = _timings(m_warm, "lpa_superstep")
    assert tw["cold_compile"] is False

    cc = connected_components(g, sink=m)
    assert int(np.asarray(cc).max()) == 0
    (tc,) = _timings(m, "cc_superstep")
    assert tc["window"] >= 1 and tc["iteration"] == tc["window"]

    gd = build_graph(
        np.array([0, 1, 2], np.int32), np.array([1, 2, 0], np.int32),
        num_vertices=3, symmetric=False,
    )
    pr = pagerank(gd, max_iter=30, sink=m)
    assert float(np.asarray(pr).sum()) == pytest.approx(1.0, abs=1e-4)
    (tp,) = _timings(m, "pagerank_inflow")
    assert 1 <= tp["window"] <= 30
    assert validate_records(m.records) == []


def test_timing_not_emitted_without_sink_or_under_jit():
    import jax

    from graphmine_tpu.ops.lpa import label_propagation

    g = ring4()
    m = _sink()
    # under jit the auto seam skips plan AND timing (tracer context)
    jitted = jax.jit(lambda graph: label_propagation(graph, max_iter=2, sink=m))
    jitted(g)
    assert _timings(m) == []


def test_lof_impl_selected_carries_threshold_and_cost():
    from graphmine_tpu.ops.lof import lof_scores

    m = _sink()
    pts = np.random.default_rng(0).normal(size=(64, 4)).astype(np.float32)
    lof_scores(pts, k=5, sink=m)
    (sel,) = [r for r in m.records if r["phase"] == "impl_selected"]
    assert sel["thresholds"]["lof_ivf_min_points"] == 1 << 17
    assert sel["cost"]["unit"] == "points/s/chip"
    assert set(sel["cost"].keys()) == set(COST_KEYS)
    assert validate_records(m.records) == []


def test_superstep_auto_seam_impl_selected_carries_thresholds(monkeypatch):
    from graphmine_tpu.ops.lpa import label_propagation

    monkeypatch.setenv("GRAPHMINE_BLOCKED_MIN_MESSAGES", "123")
    m = _sink()
    label_propagation(ring4(), max_iter=1, sink=m)
    (sel,) = [r for r in m.records if r["phase"] == "impl_selected"]
    # the env-overridden constant is what the record ships — the value
    # that actually decided, not the compiled-in default
    assert sel["thresholds"]["blocked_min_messages"] == 123
    assert sel["cost"]["family"] == sel["impl"]


# ---------------------------------------------------------------------------
# driver e2e: the acceptance criterion
# ---------------------------------------------------------------------------

_E2E: dict = {}


def _edgelist_path() -> str:
    if "path" not in _E2E:
        rng = np.random.default_rng(7)
        v, e = 160, 800
        src = rng.integers(0, v, e)
        dst = (src + rng.integers(1, v // 2, e)) % v
        text = "".join(f"{s} {t}\n" for s, t in zip(src, dst))
        _E2E["path"] = cached_edgelist("graphmine_perf", text)
    return _E2E["path"]


def _run_driver(tmp_path, **kw):
    from graphmine_tpu.pipeline.config import PipelineConfig
    from graphmine_tpu.pipeline.driver import run_pipeline

    base = dict(
        data_path=_edgelist_path(), data_format="edgelist",
        outlier_method="none", num_devices=1, max_iter=5,
        metrics_out=str(tmp_path / "metrics.jsonl"),
    )
    base.update(kw)
    return run_pipeline(PipelineConfig(**base))


def test_driver_e2e_timing_joinable_and_report_renders(tmp_path):
    """Acceptance: a CPU driver run emits >=1 schema-valid
    superstep_timing per LPA/CC phase, joinable to its phase span, and
    obs_report renders the roofline section with an achieved-fraction
    column from the JSONL alone."""
    res = _run_driver(
        tmp_path, snapshot_out=str(tmp_path / "snap"),
        outlier_method="lof",
    )
    recs = res.metrics.records
    assert validate_records(recs) == []
    run_id = recs[0]["run_id"]
    lpa = [r for r in recs if r["phase"] == "superstep_timing"
           and r["op"] == "lpa_superstep"]
    cc = [r for r in recs if r["phase"] == "superstep_timing"
          and r["op"] == "cc_superstep"]
    assert lpa and cc
    for r in lpa:
        # joinable: same run, span under the LPA phase span
        assert r["run_id"] == run_id
        assert r["span_path"].startswith("run/lpa")
        assert r["predicted_edges_per_sec_per_chip"] > 0
        assert r["edges_per_sec_per_chip"] > 0
        assert set(r["cost"].keys()) == set(COST_KEYS)
    assert all(
        r["span_path"].startswith("run/snapshot_publish") for r in cc
    )
    # the final superstep always closes a window: the last LPA timing
    # record covers through max_iter. The operating point's
    # compile-bearing FIRST superstep is excluded (the watchdog's
    # `warmed` discipline), so 5 supersteps time 4 window slots.
    assert lpa[-1]["iteration"] == 5
    assert sum(r["window"] for r in lpa) == 4

    # obs_report: roofline section from the JSONL alone, exit 0
    out = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "obs_report.py"),
         str(tmp_path / "metrics.jsonl")],
        capture_output=True, text=True,
    )
    assert out.returncode == 0, out.stderr
    assert "-- roofline (achieved vs cost model) --" in out.stdout
    assert "frac" in out.stdout
    assert "model anchors:" in out.stdout
    # the waterfall small fix: thresholds + model under the auto lines
    assert "thresholds: " in out.stdout
    assert "model: " in out.stdout


def test_driver_sharded_timing_carries_exchange_split(tmp_path):
    res = _run_driver(tmp_path, num_devices=8, max_iter=3)
    lpa = [r for r in res.metrics.records
           if r["phase"] == "superstep_timing"]
    assert lpa, "sharded driver run emitted no superstep_timing"
    for r in lpa:
        assert r["devices"] == 8
        assert r["variant"] == "replicated"
        assert r["cost"]["exchange_bytes"] > 0
        assert r["cost"]["exchange_seconds"] >= 0
    assert validate_records(res.metrics.records) == []


def test_obs_report_flags_below_model_windows(tmp_path):
    sys.path.insert(0, TOOLS)
    import obs_report

    c = costmodel.superstep_cost("lpa_superstep", "sort", 4, 8, 4)
    base = dict(
        phase="superstep_timing", t=1.0, op="lpa_superstep",
        family="sort", variant="single", window=2, seconds=0.1,
        edges_per_sec_per_chip=100, devices=1, cost=c.record(),
    )
    records = [
        dict(base, iteration=2, achieved_fraction=0.95,
             predicted_edges_per_sec_per_chip=105),
        dict(base, iteration=4, achieved_fraction=0.2,
             predicted_edges_per_sec_per_chip=500),
        # a compile-bearing window below model must NOT raise the flag
        dict(base, iteration=6, achieved_fraction=0.05,
             predicted_edges_per_sec_per_chip=500, cold_compile=True),
    ]
    report = obs_report.build_report(records, roofline_min_frac=0.5)
    assert report.count("<< below 0.5x model") == 1
    assert "1 window(s) below 0.5x of model" in report
    assert "includes XLA compile" in report
    # configurable fraction: at 0.1 nothing is flagged
    assert "<< below" not in obs_report.build_report(
        records, roofline_min_frac=0.1
    )


# ---------------------------------------------------------------------------
# bench_diff: gate, trajectory, manifest, crossover suggestion
# ---------------------------------------------------------------------------


def _bench_file(tmp_path, name, n, tiers, tail_records=()):
    """Synthetic driver artifact: suite-summary tiers + optional full
    tail records (the shape bench.py's orchestrator really prints)."""
    suite_tiers = {}
    for tier, spec in tiers.items():
        if "err" in spec:
            suite_tiers[tier] = {"err": spec["err"]}
        else:
            suite_tiers[tier] = {
                "m": spec["metric"], "v": spec["value"],
                "u": spec["unit"], "vs": spec.get("vs", 1.0),
            }
    tail = "".join(json.dumps(r) + "\n" for r in tail_records)
    path = tmp_path / name
    path.write_text(json.dumps({
        "n": n, "cmd": "python bench.py", "rc": 0, "tail": tail,
        "parsed": {"metric": "x", "suite": {"tiers": suite_tiers}},
    }))
    return str(path)


def _chip(v):
    return {"chip": {
        "metric": "lpa_edges_per_sec_per_chip", "value": v,
        "unit": "edges/s/chip",
    }}


def test_bench_diff_gate_no_regression(tmp_path, capsys):
    a = _bench_file(tmp_path, "BENCH_r90.json", 90, _chip(100_000_000))
    b = _bench_file(tmp_path, "BENCH_r91.json", 91, _chip(95_000_000))
    assert bench_diff.main([a, b]) == 0
    out = capsys.readouterr().out
    assert "gate: clean" in out


def test_bench_diff_gate_regression_names_metric(tmp_path, capsys):
    a = _bench_file(tmp_path, "BENCH_r90.json", 90, _chip(100_000_000))
    b = _bench_file(tmp_path, "BENCH_r91.json", 91, _chip(85_000_000))
    assert bench_diff.main([a, b]) == 1
    err = capsys.readouterr().err
    assert "lpa_edges_per_sec_per_chip" in err
    assert "chip tolerance" in err


def test_bench_diff_tolerance_edge_and_direction(tmp_path):
    # exactly AT the 10% tolerance: not a regression (strict inequality)
    a = _bench_file(tmp_path, "BENCH_r90.json", 90, _chip(100_000_000))
    b = _bench_file(tmp_path, "BENCH_r91.json", 91, _chip(90_000_000))
    assert bench_diff.main([a, b]) == 0
    # one unit past it (vs the same 100M base): regression
    c = _bench_file(tmp_path, "BENCH_r92.json", 92, _chip(89_999_999))
    assert bench_diff.main([a, c]) == 1
    # seconds regress UPWARD (lower=better)
    ns = lambda v: {"northstar": {
        "metric": "lpa_100m_maxiter5_seconds", "value": v, "unit": "s",
    }}
    d = _bench_file(tmp_path, "BENCH_r93.json", 93, ns(8.0))
    e = _bench_file(tmp_path, "BENCH_r94.json", 94, ns(9.5))
    assert bench_diff.main([d, e]) == 1
    f = _bench_file(tmp_path, "BENCH_r95.json", 95, ns(7.0))
    assert bench_diff.main([d, f]) == 0
    # per-tier override via --tolerance
    assert bench_diff.main([d, e, "--tolerance", "northstar=0.5"]) == 0


def test_bench_diff_single_file_pins_the_gate(tmp_path, monkeypatch, capsys):
    """Single-file mode gates THE NAMED file even when its round number
    parses older than the newest committed capture (a re-run of an old
    round must not silently fall out of the comparison)."""
    c1 = _bench_file(tmp_path, "BENCH_r01.json", 1, _chip(100_000_000))
    c2 = _bench_file(tmp_path, "BENCH_r02.json", 2, _chip(101_000_000))
    monkeypatch.setattr(
        bench_diff, "committed_bench_files", lambda repo_dir=None: [c1, c2]
    )
    fresh_dir = tmp_path / "fresh"
    fresh_dir.mkdir()
    recap = _bench_file(fresh_dir, "BENCH_r01.json", 1, _chip(80_000_000))
    assert bench_diff.main([recap]) == 1
    err = capsys.readouterr().err
    assert "lpa_edges_per_sec_per_chip" in err


def test_bench_diff_capture_change_gates_only_under_strict(tmp_path):
    a = _bench_file(tmp_path, "BENCH_r90.json", 90, _chip(100_000_000))
    fb = {"chip": {
        "metric": "lpa_edges_per_sec_per_chip_cpu_fallback",
        "value": 1_000_000, "unit": "edges/s/chip",
    }}
    b = _bench_file(tmp_path, "BENCH_r91.json", 91, fb)
    # a fresh CPU-fallback capture vs committed silicon must NOT fail the
    # default gate (this container can never produce silicon numbers)
    assert bench_diff.main([a, b]) == 0
    assert bench_diff.main([a, b, "--strict-capture"]) == 1


def test_bench_diff_committed_trajectory_selfcheck(capsys):
    """The CI self-check satellite: the full committed BENCH_r01–r05
    trajectory renders without error, and the r04->r05 gate is clean."""
    committed = bench_diff.committed_bench_files(REPO)
    assert len(committed) >= 5
    assert bench_diff.main(committed + ["--no-gate"]) == 0
    out = capsys.readouterr().out
    assert "bench trajectory" in out
    assert "r05" in out
    r04 = os.path.join(REPO, "BENCH_r04.json")
    r05 = os.path.join(REPO, "BENCH_r05.json")
    assert bench_diff.main([r04, r05]) == 0


def test_bench_diff_manifest_tracks_fallback_only_tiers(tmp_path, capsys):
    real = _bench_file(tmp_path, "BENCH_r90.json", 90, _chip(100_000_000))
    fb_rec = {
        "metric": "blocking_binned_slots_per_sec_cpu_fallback",
        "value": 1000.0, "unit": "slots/s", "vs_baseline": 0.1,
        "detail": {"binned_vs_random_gather": 0.5,
                   "capture": {"cpu_fallback": "tpu unreachable"}},
    }
    fb = _bench_file(
        tmp_path, "BENCH_r91.json", 91,
        {"blocking": {
            "metric": "blocking_binned_slots_per_sec_cpu_fallback",
            "value": 1000.0, "unit": "slots/s"}},
        tail_records=[fb_rec],
    )
    assert bench_diff.main([real, fb, "--manifest", "--no-gate"]) == 0
    out = capsys.readouterr().out
    manifest = json.loads(out.split("== silicon-capture manifest ==")[1])
    assert manifest["tiers"]["chip"] == "silicon"
    assert manifest["tiers"]["blocking"] == "cpu_fallback"
    assert manifest["sub_records"][
        "blocking.binned_vs_random_gather"] == "cpu_fallback"
    assert "blocking" in manifest["pending"]
    assert "chip" not in manifest["pending"]
    # --strict turns a non-empty backlog into exit 1
    assert bench_diff.main(
        [real, fb, "--manifest", "--strict", "--no-gate"]
    ) == 1


def test_bench_diff_crossover_suggestion_on_silicon_blocking(tmp_path, capsys):
    rec = {
        "metric": "blocking_binned_slots_per_sec", "value": 2.6e8,
        "unit": "slots/s", "vs_baseline": 2.0,
        "detail": {"binned_vs_random_gather": 1.9,
                   "capture": {"cpu_fallback": None}},
    }
    f = _bench_file(
        tmp_path, "BENCH_r90.json", 90,
        {"blocking": {"metric": "blocking_binned_slots_per_sec",
                      "value": 2.6e8, "unit": "slots/s"}},
        tail_records=[rec],
    )
    assert bench_diff.main([f, "--no-gate"]) == 0
    out = capsys.readouterr().out
    assert "blocked-crossover suggestion" in out
    assert "1.90x" in out
    assert "BLOCKED_MIN_VERTICES" in out
    # the constants are parsed from ops/blocking.py source (stdlib-only)
    consts = bench_diff._current_blocked_constants()
    assert consts["BLOCKED_MIN_MESSAGES"] == 1 << 22
    assert consts["BLOCKED_MIN_VERTICES"] == 1 << 21
    # a CPU-fallback ratio must NOT produce a suggestion
    capsys.readouterr()


def test_bench_list_missing_cli():
    env = dict(os.environ)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--list-missing"],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=120,
    )
    assert out.returncode == 0, out.stderr
    manifest = json.loads(out.stdout)
    # the repo's real backlog: blocking + serve have never been captured
    # on silicon (they postdate the r05 window — ROADMAP backlog)
    assert "blocking" in manifest["pending"]
    assert "serve" in manifest["pending"]
    assert manifest["tiers"]["chip"] == "silicon"
    strict = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--list-missing",
         "--strict"],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=120,
    )
    assert strict.returncode == 1
