"""Relational ops beyond the reference's call sites: join / group_by /
agg / drop / dropna / fillna — the rest of the Spark DataFrame surface a
migrating user leans on. Oracle-checked against pure-Python equivalents,
with SQL null semantics (null keys never match; GROUP BY groups nulls)."""

import numpy as np
import pytest

from graphmine_tpu.table import Table


def left():
    return Table(
        {
            "k": np.array(["a", "b", "b", None, "d"], dtype=object),
            "lv": np.array([1, 2, 3, 4, 5]),
        }
    )


def right():
    return Table(
        {
            "k": np.array(["b", "b", "c", None], dtype=object),
            "rv": np.array([10.0, 20.0, 30.0, 40.0]),
        }
    )


def rows(t, *cols):
    return [tuple(t[c][i] for c in cols) for i in range(len(t))]


# -- join --------------------------------------------------------------------


def test_inner_join_null_keys_never_match():
    j = left().join(right(), on="k", how="inner")
    assert j.columns == ["k", "lv", "rv"]
    # b matches twice per left b-row; nulls on either side never match
    assert rows(j, "k", "lv", "rv") == [
        ("b", 2, 10.0),
        ("b", 2, 20.0),
        ("b", 3, 10.0),
        ("b", 3, 20.0),
    ]


def test_left_join_pads_nulls_preserving_left_order():
    j = left().join(right(), on="k", how="left")
    assert rows(j, "k", "lv") == [
        ("a", 1), ("b", 2), ("b", 2), ("b", 3), ("b", 3), (None, 4), ("d", 5),
    ]
    rv = j["rv"]
    assert np.isnan(rv[0]) and np.isnan(rv[5]) and np.isnan(rv[6])
    assert list(rv[1:5]) == [10.0, 20.0, 10.0, 20.0]


def test_right_and_full_join_append_unmatched_right():
    j = left().join(right(), on="k", how="right")
    # matched pairs first (left order), then unmatched right rows (c, null)
    assert rows(j, "k", "rv")[-2:] == [("c", 30.0), (None, 40.0)]
    assert j["lv"][len(j) - 1] is None  # int column promoted to hold null
    f = left().join(right(), on="k", how="full")
    # full = left-join rows + unmatched right rows
    assert len(f) == 7 + 2
    assert rows(f, "k")[:1] == [("a",)]
    assert rows(f, "k")[-2:] == [("c",), (None,)]


def test_semi_anti_join():
    s = left().join(right(), on="k", how="left_semi")
    assert rows(s, "k", "lv") == [("b", 2), ("b", 3)]
    a = left().join(right(), on="k", how="left_anti")
    assert rows(a, "k", "lv") == [("a", 1), (None, 4), ("d", 5)]


def test_join_suffixes_collisions_and_multi_key():
    l = Table(k=np.array([1, 2]), v=np.array([1.0, 2.0]))
    r = Table(k=np.array([2, 3]), v=np.array([20.0, 30.0]))
    j = l.join(r, on="k", how="inner")
    assert j.columns == ["k", "v", "v_r"]
    assert rows(j, "k", "v", "v_r") == [(2, 2.0, 20.0)]
    # multi-column key
    l2 = Table(a=np.array([1, 1, 2]), b=np.array([1, 2, 1]), x=np.array([7, 8, 9]))
    r2 = Table(a=np.array([1, 2]), b=np.array([2, 1]), y=np.array([70, 80]))
    j2 = l2.join(r2, on=["a", "b"])
    assert rows(j2, "a", "b", "x", "y") == [(1, 2, 8, 70), (2, 1, 9, 80)]


def test_cross_join():
    l = Table(x=np.array([1, 2]))
    r = Table(y=np.array([10, 20, 30]))
    j = l.join(r, on=[], how="cross")
    assert len(j) == 6
    assert rows(j, "x", "y")[:3] == [(1, 10), (1, 20), (1, 30)]


def test_join_random_oracle():
    rng = np.random.default_rng(0)
    l = Table(k=rng.integers(0, 8, 40), v=rng.normal(size=40))
    r = Table(k=rng.integers(0, 8, 30), w=rng.normal(size=30))
    j = l.join(r, on="k", how="inner")
    expect = sorted(
        (int(lk), float(lv), float(rw))
        for lk, lv in zip(l["k"], l["v"])
        for rk, rw in zip(r["k"], r["w"])
        if lk == rk
    )
    got = sorted((int(a), float(b), float(c)) for a, b, c in rows(j, "k", "v", "w"))
    assert got == expect


def test_join_errors():
    with pytest.raises(KeyError):
        left().join(right(), on="missing")
    with pytest.raises(ValueError):
        left().join(right(), on="k", how="sideways")


# -- group_by / agg ----------------------------------------------------------


def grouped_src():
    return Table(
        {
            "g": np.array(["x", "y", "x", None, "y", "x"], dtype=object),
            "v": np.array([3.0, 1.0, np.nan, 5.0, 2.0, 1.0]),
            "s": np.array(["p", "q", "r", None, "q", None], dtype=object),
        }
    )


def test_group_count_first_appearance_order_nulls_grouped():
    c = grouped_src().group_by("g").count()
    assert rows(c, "g", "count") == [("x", 3), ("y", 2), (None, 1)]


def test_agg_sum_mean_min_max_null_handling():
    t = grouped_src().group_by("g").agg(
        {"v": "sum"}, total_mean=("v", "mean"), lo=("v", "min"), hi=("v", "max")
    )
    assert rows(t, "g") == [("x",), ("y",), (None,)]
    assert list(t["sum(v)"]) == [4.0, 3.0, 5.0]  # NaN v ignored
    assert list(t["total_mean"]) == [2.0, 1.5, 5.0]
    assert list(t["lo"]) == [1.0, 1.0, 5.0]
    assert list(t["hi"]) == [3.0, 2.0, 5.0]


def test_agg_count_and_count_distinct_ignore_nulls():
    t = grouped_src().group_by("g").agg(
        n=("s", "count"), d=("s", "count_distinct"), star=("*", "count")
    )
    assert list(t["n"]) == [2, 2, 0]
    assert list(t["d"]) == [2, 1, 0]
    assert list(t["star"]) == [3, 2, 1]


def test_agg_min_max_strings_and_first_and_collect():
    t = grouped_src().group_by("g").agg(
        lo=("s", "min"), hi=("s", "max"), f=("s", "first"),
        lst=("s", "collect_list"), st=("s", "collect_set"),
    )
    assert list(t["lo"]) == ["p", "q", None]  # all-null group -> null
    assert list(t["hi"]) == ["r", "q", None]
    assert list(t["f"]) == ["p", "q", None]
    assert list(t["lst"]) == [["p", "r"], ["q", "q"], []]
    assert list(t["st"]) == [["p", "r"], ["q"], []]


def test_agg_integer_sum_stays_integer():
    t = Table(g=np.array([0, 0, 1]), v=np.array([1, 2, 3]))
    out = t.group_by("g").agg({"v": "sum"})
    assert out["sum(v)"].dtype == np.int64
    assert list(out["sum(v)"]) == [3, 3]
    mn = t.group_by("g").agg({"v": "min"})
    assert list(mn["min(v)"]) == [1, 3]


def test_grouped_shortcuts_default_to_numeric_columns():
    t = Table(
        g=np.array(["a", "a", "b"], dtype=object),
        v=np.array([1.0, 2.0, 3.0]),
        s=np.array(["x", "y", "z"], dtype=object),
    )
    out = t.group_by("g").sum()
    assert out.columns == ["g", "sum(v)"]
    assert list(out["sum(v)"]) == [3.0, 3.0]
    assert list(t.group_by("g").mean("v")["mean(v)"]) == [1.5, 3.0]


def test_global_agg_and_empty_table():
    t = Table(v=np.array([1.0, 2.0, 3.0]))
    out = t.agg({"v": "sum"}, n=("*", "count"))
    assert len(out) == 1
    assert out["sum(v)"][0] == 6.0 and out["n"][0] == 3
    empty = Table(v=np.array([], dtype=np.float64))
    e = empty.agg(n=("*", "count"), s=("v", "sum"), m=("v", "min"))
    assert e["n"][0] == 0
    assert np.isnan(e["s"][0]) and np.isnan(e["m"][0])


def test_group_agg_random_oracle():
    rng = np.random.default_rng(1)
    g = rng.integers(0, 5, 200)
    v = rng.normal(size=200)
    t = Table(g=g, v=v)
    out = t.group_by("g").agg({"v": "sum"}, m=("v", "mean"),
                              lo=("v", "min"), hi=("v", "max"))
    for i in range(len(out)):
        key = out["g"][i]
        vals = v[g == key]
        assert out["sum(v)"][i] == pytest.approx(vals.sum())
        assert out["m"][i] == pytest.approx(vals.mean())
        assert out["lo"][i] == pytest.approx(vals.min())
        assert out["hi"][i] == pytest.approx(vals.max())


def test_agg_errors():
    t = Table(g=np.array([1]), s=np.array(["x"], dtype=object))
    with pytest.raises(TypeError):
        t.group_by("g").agg({"s": "sum"})
    with pytest.raises(ValueError):
        t.group_by("g").agg({"s": "median"})
    with pytest.raises(ValueError):
        t.group_by("g").agg(g=("s", "first"))  # collides with key column


# -- drop / dropna / fillna --------------------------------------------------


def test_drop_dropna_fillna():
    t = grouped_src()
    assert t.drop("v", "missing").columns == ["g", "s"]
    d = t.dropna()
    assert len(d) == 3  # rows 0, 1, 4
    assert list(d["v"]) == [3.0, 1.0, 2.0]
    assert len(t.dropna(subset=["g"])) == 5
    f = t.fillna("??", subset=["s"])
    assert list(f["s"]) == ["p", "q", "r", "??", "q", "??"]
    assert np.isnan(f["v"][2])  # numeric column untouched by string fill
    f2 = t.fillna(0.0)
    assert f2["v"][2] == 0.0
    assert f2["s"][3] is None  # string column untouched by numeric fill


def test_int64_sum_min_max_exact_above_2_53():
    big = 2**62 + 1
    t = Table(k=np.array(["a", "a"], dtype=object), v=np.array([big, 1], dtype=np.int64))
    out = t.group_by("k").agg({"v": "sum"}, hi=("v", "max"), lo=("v", "min"))
    assert out["sum(v)"][0] == big + 1
    assert out["hi"][0] == big and out["hi"].dtype == np.int64
    assert out["lo"][0] == 1


def test_join_coerces_mixed_int_float_keys():
    l = Table(k=np.array([1, 2, 3], dtype=np.int64), v=np.array([1, 2, 3]))
    r = Table(k=np.array([1.0, 2.0]), w=np.array([10, 20]))
    j = l.join(r, on="k", how="inner")
    assert sorted(zip(j["v"], j["w"])) == [(1, 10), (2, 20)]


def test_spark_join_alias_names():
    assert len(left().join(right(), on="k", how="fullouter")) == 9
    assert len(left().join(right(), on="k", how="leftsemi")) == 2
    assert len(left().join(right(), on="k", how="anti")) == 3


def test_grouped_count_key_collision_fails_loudly():
    t = Table({"count": np.array(["x", "x", "y"], dtype=object)})
    with pytest.raises(ValueError):
        t.group_by("count").count()


def test_drop_all_columns_keeps_row_count():
    t = Table(a=np.array([1, 2, 3]))
    assert t.drop("a").count() == 3


def test_nullable_int_columns_after_join_behave_numerically():
    # Joins promote int columns with nulls to object; aggregation, min/max,
    # and fillna must still treat them as numbers, not strings.
    t = Table(
        g=np.array(["a", "a", "b"], dtype=object),
        v=np.array([2, 10, None], dtype=object),
    )
    out = t.group_by("g").agg(lo=("v", "min"), hi=("v", "max"),
                              s=("v", "sum"), m=("v", "mean"))
    assert out["lo"][0] == 2 and out["hi"][0] == 10  # numeric, not "10" < "2"
    assert out["s"][0] == 12.0 and out["m"][0] == 6.0
    assert np.isnan(out["s"][1])
    f = t.fillna(0)
    assert f["v"][2] == 0  # numeric fill reaches the promoted column
    assert list(t.fillna("x")["g"]) == ["a", "a", "b"]  # string fill skips it


def test_empty_tables_through_join_group_distinct():
    l = Table(k=np.array(["a", "b"], dtype=object), v=np.array([1, 2]))
    empty = Table(k=np.array([], dtype=object), w=np.array([], dtype=np.float64))
    assert len(l.join(empty, on="k", how="left_anti")) == 2
    assert len(l.join(empty, on="k", how="inner")) == 0
    j = l.join(empty, on="k", how="left")
    assert len(j) == 2 and np.isnan(j["w"]).all()  # float nulls stay float NaN
    assert len(empty.join(l, on="k", how="right")) == 2
    assert len(empty.group_by("k").count()) == 0
    assert len(empty.distinct()) == 0


def test_empty_key_list_rejected_and_cross_rejects_keys():
    l = Table(x=np.array([1, 2]))
    r = Table(y=np.array([10, 20]))
    with pytest.raises(ValueError, match="cross"):
        l.join(r, on=[], how="inner")
    r2 = Table(x=np.array([1]))
    with pytest.raises(ValueError, match="no key"):
        l.join(r2, on="x", how="cross")


def test_parquet_and_csv_roundtrip(tmp_path):
    t = Table(
        s=np.array(["a", None, "c"], dtype=object),
        x=np.array([1.5, np.nan, 3.0]),
        n=np.array([1, 2, 3]),
    )
    pq_path = str(tmp_path / "t.parquet")
    t.write_parquet(pq_path)
    back = Table.read_parquet(pq_path)
    assert back["s"][1] is None and np.isnan(back["x"][1])
    assert list(back["n"]) == [1, 2, 3]

    csv_path = str(tmp_path / "t.csv")
    t.select("n").write_csv(csv_path)
    again = Table.read_csv(csv_path)
    assert list(again["n"]) == [1, 2, 3]
    headless = str(tmp_path / "h.csv")
    t.select("n", "x").write_csv(headless, header=False)
    cols = Table.read_csv(headless, header=False)
    assert cols.columns == ["_c0", "_c1"]  # Spark's autogenerated names


def test_spark_camelcase_aliases():
    t = Table(g=np.array([1, 1, 2]), v=np.array([1.0, 2.0, 3.0]))
    assert list(t.groupBy("g").count()["count"]) == [2, 1]
