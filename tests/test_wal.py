"""Durable write path suite (marker ``wal``):
tools/run_tier1.sh --wal-only.

The acceptance pins (ISSUE 10):

- write-ahead log: checksummed framed records fsync'd before the
  acknowledgement, torn-tail tolerant recovery (wal_torn_tail), segment
  rotation + compaction keyed to the published snapshot version;
- writer-epoch fencing at the snapshot store: a stale-epoch publish
  refuses loudly with ``PublishFencedError`` + a ``publish_fenced``
  record — split-brain impossibility at the store, not by convention;
- WAL-durable 202 acknowledgements + kill/restart: every 202-acked
  delta reaches the final snapshot via startup replay; a clean stop
  resolves WAL-durable queued batches as accepted (202), never a
  shutdown 503;
- duplicate-submit parity: a retried ``X-Delta-Id`` (serve_cli reuses
  one key across retries) never double-applies;
- the log-shipped standby: verbatim WAL copy within a bounded,
  observable replication lag (``ship_lag`` injector + records,
  /healthz gauges), fenced promotion replaying the tail;
- THE chaos test: a hammered 2-writer/3-replica fleet, writer
  SIGKILL'd mid-burst → standby promoted within the bound, ZERO
  acknowledged-delta loss, ZERO mixed-version reads, and the deposed
  writer's comeback publish fenced with a loud record.
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from graphmine_tpu.graph.container import build_graph
from graphmine_tpu.obs.schema import validate_records
from graphmine_tpu.obs.spans import Tracer
from graphmine_tpu.pipeline.checkpoint import graph_fingerprint
from graphmine_tpu.pipeline.metrics import MetricsSink
from graphmine_tpu.serve import (
    PublishFencedError,
    SnapshotStore,
    WriteAheadLog,
)
from graphmine_tpu.serve.delta import DeltaIngestor, EdgeDelta, cold_recompute
from graphmine_tpu.serve.fleet import FleetConfig, FleetRouter, ReplicaSpec
from graphmine_tpu.serve.server import SnapshotServer
from graphmine_tpu.testing import faults

pytestmark = pytest.mark.wal


# ---- fixtures -------------------------------------------------------------


def _clique(lo, hi):
    ids = np.arange(lo, hi)
    s, d = np.meshgrid(ids, ids)
    m = s.ravel() < d.ravel()
    return s.ravel()[m], d.ravel()[m]


def _community_graph():
    parts = [_clique(0, 12), _clique(12, 26), _clique(26, 40)]
    src = np.concatenate([p[0] for p in parts]).astype(np.int32)
    dst = np.concatenate([p[1] for p in parts]).astype(np.int32)
    return src, dst, 40


def _sink():
    return MetricsSink(tracer=Tracer())


def _publish_base(tmp_path, sink=None):
    src, dst, v = _community_graph()
    g = build_graph(src, dst, num_vertices=v)
    labels, cc, _ = cold_recompute(g)
    store = SnapshotStore(str(tmp_path / "snap"))
    store.publish(
        {
            "src": src, "dst": dst, "labels": labels, "cc_labels": cc,
            "lof": np.zeros(v, np.float32),
        },
        fingerprint=graph_fingerprint(src, dst),
        sink=sink,
    )
    return store, src, dst, v


def _edges(engine):
    return set(
        zip(np.asarray(engine.snapshot["src"]).tolist(),
            np.asarray(engine.snapshot["dst"]).tolist())
    )


def _post(host, port, path, payload, timeout=60, headers=None):
    req = urllib.request.Request(
        f"http://{host}:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def _get(host, port, path, timeout=30):
    with urllib.request.urlopen(
        f"http://{host}:{port}{path}", timeout=timeout
    ) as r:
        return json.loads(r.read())


# ---- WAL unit: framing / recovery / rotation / compaction -----------------


def test_wal_append_entries_pending_roundtrip(tmp_path):
    sink = _sink()
    w = WriteAheadLog(str(tmp_path / "wal"), sink=sink)
    for i in range(6):
        seq, dup = w.append(
            {"insert": [[i, i + 1]]}, delta_id=f"d{i}", deadline_s=5.0,
        )
        assert seq == i + 1 and not dup
    assert w.last_seq == 6 and w.applied_seq == 0
    got = w.entries(1)
    assert [e["seq"] for e in got] == [1, 2, 3, 4, 5, 6]
    assert got[2]["payload"] == {"insert": [[2, 3]]}
    assert got[2]["id"] == "d2" and got[2]["deadline_s"] == 5.0
    # a duplicate id maps onto the original accept, writing nothing
    assert w.append({"insert": [[9, 9]]}, delta_id="d3") == (4, True)
    assert w.last_seq == 6
    # watermark: entries at/below it leave pending
    w.commit(4, snapshot_version=5)
    assert w.applied_seq == 4 and w.applied_version == 5
    assert [e["seq"] for e in w.pending()] == [5, 6]
    # tombstone: a durable-but-shed entry is excluded from replay
    w.skip(5)
    assert [e["seq"] for e in w.pending()] == [6]
    w.close()
    # a fresh open rebuilds the same state from disk alone
    w2 = WriteAheadLog(str(tmp_path / "wal"))
    assert w2.applied_seq == 4
    assert [e["seq"] for e in w2.pending()] == [6]
    assert w2.lookup("d5") == 6 and w2.lookup("nope") is None
    w2.close()
    appends = [r for r in sink.records if r["phase"] == "wal_append"]
    assert len(appends) == 6
    assert all(r["bytes"] > 0 and r["seconds"] >= 0 for r in appends)
    assert validate_records(sink.records) == []


def test_wal_torn_tail_keeps_prefix_and_appends_past(tmp_path):
    root = str(tmp_path / "wal")
    w = WriteAheadLog(root)
    for i in range(5):
        w.append({"insert": [[i, i + 1]]}, delta_id=f"d{i}")
    w.close()
    torn = faults.wal_torn_tail(root)
    assert torn.endswith(".seg")
    w2 = WriteAheadLog(root)
    # every record before the tear is intact; the torn one is gone
    assert w2.last_seq == 4
    assert [e["seq"] for e in w2.pending()] == [1, 2, 3, 4]
    # the log keeps accepting: the tear was truncated, not fatal
    seq, dup = w2.append({"insert": [[7, 8]]}, delta_id="after")
    assert seq == 5 and not dup
    assert [e["seq"] for e in w2.pending()] == [1, 2, 3, 4, 5]
    w2.close()
    # and the repaired log reopens cleanly
    w3 = WriteAheadLog(root)
    assert w3.last_seq == 5
    w3.close()


def test_wal_rotation_and_compaction_keyed_to_version(tmp_path):
    root = str(tmp_path / "wal")
    w = WriteAheadLog(root, segment_max_bytes=256, retain_segments=1)
    for i in range(12):
        w.append({"insert": [[i, i + 1]]}, delta_id=f"d{i}")
    n_before = w.snapshot()["segments"]
    assert n_before >= 3  # the size bound rotated
    # compaction follows the published-version watermark
    w.commit(10, snapshot_version=11)
    snap = w.snapshot()
    assert snap["segments"] < n_before
    # pending survives compaction; the retention tail keeps dedupe for
    # recently applied ids
    assert [e["seq"] for e in w.pending()] == [11, 12]
    retained_ids = [
        e["id"] for e in w.entries(0) if e.get("op") == "delta"
    ]
    assert "d11" in retained_ids
    # in-memory dedupe still covers everything this process saw
    assert w.append({"x": 1}, delta_id="d0")[1] is True
    w.close()


def test_wal_watermark_history_floor_and_rewind(tmp_path):
    root = str(tmp_path / "wal")
    w = WriteAheadLog(root)
    w.note_baseline(1)          # fresh log next to a v1 store
    assert w.commit_history() == [(0, 1)]
    w.note_baseline(9)          # only the FIRST baseline sticks
    assert w.commit_history() == [(0, 1)]
    for i in range(3):
        w.append({"insert": [[i, i + 1]]}, delta_id=f"d{i}")
        w.commit(i + 1, snapshot_version=i + 2)
    assert w.commit_history() == [(0, 1), (1, 2), (2, 3), (3, 4)]
    # the floor answers only for versions a retained pair vouches for
    assert w.replay_floor(1) == 0 and w.replay_floor(3) == 2
    assert w.replay_floor(7) is None
    # rewind moves the durable cursor back and drops foreign-lineage
    # pairs above it; forward "rewinds" are refused
    w.rewind(2, 3)
    assert w.applied_seq == 2 and w.applied_version == 3
    assert [e["seq"] for e in w.pending()] == [3]
    w.rewind(5, 9)
    assert w.applied_seq == 2
    w.close()
    # everything above survives a fresh open from disk alone
    w2 = WriteAheadLog(root)
    assert w2.applied_seq == 2 and w2.applied_version == 3
    assert w2.commit_history() == [(0, 1), (1, 2), (2, 3)]
    assert [e["seq"] for e in w2.pending()] == [3]
    # merge_history: new seqs fill in, an existing seq keeps the local
    # pair, the watermark advances to the merged max
    w2.merge_history([(2, 99), (3, 4), (4, 5)])
    assert w2.commit_history() == [(0, 1), (1, 2), (2, 3), (3, 4), (4, 5)]
    assert w2.applied_seq == 4 and w2.applied_version == 5
    w2.close()


# ---- writer-epoch fencing at the store ------------------------------------


def test_publish_epoch_fencing(tmp_path):
    sink = _sink()
    store, src, dst, v = _publish_base(tmp_path, sink=sink)
    arrays = {
        "src": src, "dst": dst,
        "labels": np.zeros(v, np.int32), "cc_labels": np.zeros(v, np.int32),
        "lof": np.zeros(v, np.float32),
    }
    assert store.current_epoch() == 0
    # epoch-less publishes inherit (the single-writer compatibility rule)
    s2 = store.publish(arrays, sink=sink)
    assert s2.writer_epoch == 0
    # the promotion's first act: durably raise the fence
    store.fence_epoch(2, sink=sink, reason="test promotion")
    assert store.current_epoch() == 2
    # the deposed writer's comeback publish refuses LOUDLY
    with pytest.raises(PublishFencedError, match="behind the store's epoch"):
        store.publish(arrays, epoch=1, sink=sink)
    fenced = [r for r in sink.records if r["phase"] == "publish_fenced"]
    assert len(fenced) == 1
    assert fenced[0]["attempted_epoch"] == 1 and fenced[0]["store_epoch"] == 2
    # the promoted writer publishes at the fence
    s3 = store.publish(arrays, epoch=2, sink=sink)
    assert s3.writer_epoch == 2 and s3.version == 3
    # the manifest chain carries the epoch; loads see it
    assert store.load().writer_epoch == 2
    # epochs never lower
    with pytest.raises(ValueError, match="monotonic"):
        store.fence_epoch(1)
    assert validate_records(sink.records) == []


def test_advance_epoch_concurrent_promotions_mint_distinct_epochs(tmp_path):
    """The equal-epoch promotion race pin: ``fence_epoch(current_epoch()
    + 1)`` composed by racing promoters (the prober's auto-promote vs an
    operator's /promote on another server) reads the same current epoch
    and fences the SAME value on both sides — fence_epoch accepts an
    equal epoch as an idempotent re-assert, so both writers would pass
    the fence and the split-brain the epoch exists to forbid is back.
    ``advance_epoch`` mints read+increment under the inter-process fence
    lock: every concurrent promotion gets a DISTINCT epoch, so exactly
    one owns the highest and every other is immediately fenced."""
    sink = _sink()
    store, *_ = _publish_base(tmp_path, sink=sink)
    # separate store handles = separate promoting servers on one root
    handles = [store] + [SnapshotStore(store.root) for _ in range(3)]
    minted, barrier = [], threading.Barrier(len(handles))
    lock = threading.Lock()

    def promote(s):
        barrier.wait()
        e = s.advance_epoch(sink=sink, reason="racing promotion")
        with lock:
            minted.append(e)

    threads = [threading.Thread(target=promote, args=(s,)) for s in handles]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # no two promotions own the same epoch; the store ends at the max
    assert sorted(minted) == [1, 2, 3, 4]
    assert store.current_epoch() == 4
    # every mint announced itself (one writer_promote per promotion)
    promotes = [r for r in sink.records if r["phase"] == "writer_promote"]
    assert len(promotes) == 4
    # equal-epoch re-assert via fence_epoch stays an idempotent no-raise
    # (the standby's startup path re-asserts its own fence), while every
    # loser of the race is fenced at the store
    assert store.fence_epoch(4) == 4
    assert validate_records(sink.records) == []


def test_fenced_ingestor_publish(tmp_path):
    """The deposed-writer shape end-to-end: an ingestor created at the
    old epoch keeps working until the store is fenced past it, then its
    next publish refuses — acknowledged state is safe from the zombie."""
    sink = _sink()
    store, *_ = _publish_base(tmp_path, sink=sink)
    deposed = DeltaIngestor(
        store, sink=sink, lof_k=4, check_samples=8, epoch=0,
    )
    deposed.apply(EdgeDelta.from_pairs(insert=[(0, 13)]))  # fine at epoch 0
    store.fence_epoch(1, reason="standby promoted")
    with pytest.raises(PublishFencedError):
        deposed.apply(EdgeDelta.from_pairs(insert=[(0, 14)]))
    assert any(r["phase"] == "publish_fenced" for r in sink.records)
    # the promoted side continues the version chain unharmed
    promoted = DeltaIngestor(
        store, sink=sink, lof_k=4, check_samples=8, epoch=1,
    )
    snap = promoted.apply(EdgeDelta.from_pairs(insert=[(0, 15)]))
    assert snap.version == 3 and snap.writer_epoch == 1
    assert validate_records(sink.records) == []


# ---- WAL-durable acknowledgements: 202, kill/restart, shutdown ------------


def test_wal_202_ack_and_kill_restart_replays_everything(tmp_path):
    """THE durability pin (satellite 1): every 202-acknowledged delta
    reaches the final served snapshot across a writer kill — the WAL
    replays the accepted-but-unapplied tail through admission on
    restart."""
    sink = _sink()
    store, src, dst, v = _publish_base(tmp_path, sink=sink)
    wal_dir = str(tmp_path / "wal")
    server = SnapshotServer(store, sink=sink, wal=wal_dir)
    acked = []
    out = server.apply_delta(
        {"insert": [[0, 13]]}, delta_id="live-0", ack="wal",
    )
    assert out["verdict"] == "accepted" and out["durable"]
    acked.append((0, 13))
    server.wait_applied(60)
    # kill the listener; the 'process' stops cleanly but MORE durable
    # acknowledgements exist only in the WAL (appended after the last
    # apply — the crash window)
    faults.writer_kill_mid_apply(server)
    w = WriteAheadLog(wal_dir)
    for i, pair in enumerate([(1, 14), (2, 15), (3, 16)]):
        seq, dup = w.append(
            {"insert": [list(pair)]}, delta_id=f"crash-{i}",
        )
        assert not dup
        acked.append(pair)
    w.close()
    # 'restart the writer': a fresh server on the same store + WAL
    sink2 = _sink()
    server2 = SnapshotServer(store, sink=sink2, wal=wal_dir)
    assert server2.wait_applied(120)
    edges = _edges(server2.engine)
    for pair in acked:
        assert pair in edges, f"202-acked delta {pair} lost across restart"
    replays = [r for r in sink2.records if r["phase"] == "wal_replay"]
    assert replays and replays[0]["entries"] == 3
    assert replays[0]["source"] == "startup"
    # replayed applies settled the watermark: a second restart is a no-op
    assert server2.wal.applied_seq == server2.wal.last_seq
    server2.stop()
    assert validate_records(sink2.records) == []


def test_clean_stop_resolves_durable_batches_as_accepted_not_shed(tmp_path):
    """Satellite 1's shutdown half: a clean stop() must NOT drain
    WAL-durable accepted batches as 503 sheds — they resolve as 202
    accepted and replay on restart. (Pre-r11, stop() shed them with
    'server shutting down' — un-accepting acknowledged work.)"""
    sink = _sink()
    store, *_ = _publish_base(tmp_path, sink=sink)
    wal_dir = str(tmp_path / "wal")
    server = SnapshotServer(store, sink=sink, wal=wal_dir)
    inj = faults.FaultInjector()
    inj.add("delta_repair", faults.slow_repair(1.0), at=1, repeat=1)
    results = []

    def fire(payload, delta_id):
        results.append(
            server.apply_delta(payload, delta_id=delta_id)
        )

    with inj.installed():
        t0 = threading.Thread(
            target=fire, args=({"insert": [[0, 13]]}, "held"),
        )
        t0.start()
        time.sleep(0.3)  # batch A mid-apply, holding the worker
        t1 = threading.Thread(
            target=fire, args=({"insert": [[0, 14]]}, "parked"),
        )
        t1.start()
        time.sleep(0.2)  # batch B parked on the queue, WAL-durable
        stopper = threading.Thread(target=server.stop)
        stopper.start()
        t0.join(timeout=60)
        t1.join(timeout=60)
        stopper.join(timeout=60)
    by_id = {r.get("delta_id", ""): r for r in results if "verdict" in r}
    parked = by_id.get("parked") or next(
        r for r in results if r.get("verdict") == "accepted"
    )
    assert parked["verdict"] == "accepted", results
    assert parked["durable"] and "replays on restart" in parked["note"]
    # NO shutdown shed was recorded for the durable batch
    sheds = [
        r for r in sink.records
        if r["phase"] == "delta_shed" and r["stage"] == "shutdown"
    ]
    assert sheds == []
    # restart: the accepted batch reaches the snapshot
    server2 = SnapshotServer(store, sink=sink, wal=wal_dir)
    assert server2.wait_applied(120)
    assert (0, 14) in _edges(server2.engine)
    server2.stop()
    assert validate_records(sink.records) == []


def test_duplicate_delta_id_never_double_applies(tmp_path):
    """Duplicate-submit parity (satellite 2): the same X-Delta-Id
    resubmitted — racing while pending AND retried after the apply —
    produces exactly one application of the batch."""
    sink = _sink()
    store, *_ = _publish_base(tmp_path, sink=sink)
    server = SnapshotServer(store, sink=sink, wal=str(tmp_path / "wal"))
    host, port = server.start()
    try:
        code, out, _ = _post(
            host, port, "/delta", {"insert": [[0, 13]]},
            headers={"X-Delta-Id": "once", "X-Delta-Ack": "wal"},
        )
        assert code == 202 and out["verdict"] == "accepted"
        # a racing duplicate while (possibly) still pending
        code2, out2, _ = _post(
            host, port, "/delta", {"insert": [[0, 13]]},
            headers={"X-Delta-Id": "once", "X-Delta-Ack": "wal"},
        )
        assert out2["verdict"] == "duplicate" and out2["seq"] == out["seq"]
        server.wait_applied(60)
        # a retry after the lost 202: deduped, applied, NOT re-spliced
        code3, out3, _ = _post(
            host, port, "/delta", {"insert": [[0, 13]]},
            headers={"X-Delta-Id": "once"},
        )
        assert code3 == 200
        assert out3["verdict"] == "duplicate" and out3["applied"]
        src = np.asarray(server.engine.snapshot["src"])
        dst = np.asarray(server.engine.snapshot["dst"])
        n = int(((src == 0) & (dst == 13)).sum())
        assert n == 1, f"duplicate submit applied {n} times"
        # a malformed id is refused before it can pollute records
        code4, out4, _ = _post(
            host, port, "/delta", {"insert": [[0, 14]]},
            headers={"X-Delta-Id": "bad id! definitely not in the alphabet"},
        )
        assert code4 == 400
    finally:
        server.stop()
    assert validate_records(sink.records) == []


# ---- serve_cli: idempotency key rides every retry (satellite 2) -----------


class _ShedThenOkHandler(BaseHTTPRequestHandler):
    sheds_left = 2
    seen_ids: list = []

    def log_message(self, fmt, *args):  # noqa: A003
        pass

    def do_POST(self):  # noqa: N802
        self.rfile.read(int(self.headers.get("Content-Length", 0)))
        type(self).seen_ids.append(self.headers.get("X-Delta-Id"))
        if type(self).sheds_left > 0:
            type(self).sheds_left -= 1
            body = json.dumps({"verdict": "shed", "reason": "test"}).encode()
            self.send_response(503)
            self.send_header("Retry-After", "1")
        else:
            body = json.dumps({"version": 2}).encode()
            self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


def test_serve_cli_delta_sends_one_idempotency_key_across_retries(capsys):
    import sys
    sys.path.insert(
        0, os.path.join(os.path.dirname(__file__), "..", "tools")
    )
    import serve_cli

    class H(_ShedThenOkHandler):
        sheds_left = 2
        seen_ids = []

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    host, port = httpd.server_address[:2]
    try:
        rc = serve_cli.main([
            "delta", "--url", f"http://{host}:{port}",
            "--insert", "1,2", "--max-retries", "4",
        ])
    finally:
        httpd.shutdown()
        httpd.server_close()
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["status"] == 200 and out["attempts"] == 3
    # ONE generated key, identical on every attempt — the server-side
    # dedupe contract for retries after a lost acknowledgement
    assert len(H.seen_ids) == 3
    assert len(set(H.seen_ids)) == 1 and H.seen_ids[0]
    assert H.seen_ids[0] == out["delta_id"]


# ---- log shipping: standby copy + observable lag --------------------------


def test_standby_ships_wal_and_lag_is_observable(tmp_path):
    sink = _sink()
    store, *_ = _publish_base(tmp_path, sink=sink)
    primary = SnapshotServer(
        store, sink=sink, wal=str(tmp_path / "wal-p"),
    )
    host, port = primary.start()
    standby = SnapshotServer(
        store, sink=sink, wal=str(tmp_path / "wal-s"),
        standby_of=f"http://{host}:{port}",
        primary_wal=str(tmp_path / "wal-p"),
    )
    try:
        # a standby refuses client writes (503 through the shed path)
        refused = standby.apply_delta({"insert": [[0, 13]]})
        assert refused["verdict"] == "shed"
        assert "standby" in refused["reason"]
        for i in range(3):
            primary.apply_delta(
                {"insert": [[0, 13 + i]]}, delta_id=f"p{i}", ack="wal",
            )
        primary.wait_applied(60)
        # deterministic catch-up: one poll ships the verbatim copy
        standby._shipper.poll_once()
        assert standby.wal.last_seq == primary.wal.last_seq
        assert standby.wal.applied_seq == primary.wal.applied_seq
        ship = standby._shipper.snapshot()
        assert ship["lag_entries"] == 0
        h = standby.healthz()
        assert h["standby"] and h["replication_lag_entries"] == 0
        assert "wal" in h and h["wal"]["last_seq"] == primary.wal.last_seq
        # congest the link: lag becomes visible, then heals
        faults.ship_lag(standby, 30.0)
        primary.apply_delta(
            {"insert": [[1, 20]]}, delta_id="behind", ack="wal",
        )
        primary.wait_applied(60)
        # the standby has NOT polled (chaos delay): manufacture the lag
        # verdict deterministically by asking the primary where it is
        faults.ship_lag(standby, 0.0)
        standby._shipper.poll_once()
        assert standby.wal.lookup("behind") is not None
        # ship_lag records appear only while genuinely behind; the
        # snapshot surface always answers
        assert standby._shipper.snapshot()["polls"] >= 2
    finally:
        standby.stop()
        primary.stop()
    assert validate_records(sink.records) == []


def test_ship_lag_injector_delays_polls_and_emits_records(tmp_path):
    sink = _sink()
    store, *_ = _publish_base(tmp_path, sink=sink)
    primary = SnapshotServer(store, sink=sink, wal=str(tmp_path / "wal-p"))
    host, port = primary.start()
    standby = SnapshotServer(
        store, sink=sink, wal=str(tmp_path / "wal-s"),
        standby_of=f"http://{host}:{port}", ship_interval_s=0.05,
    )
    standby.start()
    try:
        faults.ship_lag(standby, 0.4)
        for i in range(2):
            primary.apply_delta(
                {"insert": [[0, 13 + i]]}, delta_id=f"lag{i}", ack="wal",
            )
        # while the link crawls, the primary is ahead; the loop's
        # first delayed poll lands within ~0.5s and reports the gap it
        # closed — wait for catch-up and assert lag was observed
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            if standby.wal.lookup("lag1") is not None:
                break
            time.sleep(0.05)
        assert standby.wal.lookup("lag1") is not None
    finally:
        standby.stop()
        primary.stop()
    assert validate_records(sink.records) == []


# ---- promotion: fence, replay, resume -------------------------------------


def test_promote_replays_tail_and_fences_deposed_writer(tmp_path):
    sink = _sink()
    store, *_ = _publish_base(tmp_path, sink=sink)
    wal_p = str(tmp_path / "wal-p")
    primary = SnapshotServer(store, sink=sink, wal=wal_p)
    host, port = primary.start()
    standby = SnapshotServer(
        store, sink=sink, wal=str(tmp_path / "wal-s"),
        standby_of=f"http://{host}:{port}", primary_wal=wal_p,
    )
    try:
        primary.apply_delta(
            {"insert": [[0, 13]]}, delta_id="shipped", ack="wal",
        )
        primary.wait_applied(60)
        standby._shipper.poll_once()
        # the writer dies with an acked-but-unshipped, unapplied tail
        faults.writer_kill_mid_apply(primary)
        w = WriteAheadLog(wal_p)
        w.append({"insert": [[1, 14]]}, delta_id="tail")
        w.close()
        out = standby.promote()
        assert out["promoted"] and out["epoch"] == 1
        assert out["copied_tail"] >= 1 and out["replayed"] >= 1
        assert standby.wait_applied(120)
        edges = _edges(standby.engine)
        assert (0, 13) in edges and (1, 14) in edges
        # the promoted writer accepts writes at the new epoch
        res = standby.apply_delta({"insert": [[2, 15]]}, delta_id="new")
        assert res["version"] == standby.engine.version
        assert standby.healthz()["writer_epoch"] == 1
        assert "standby" not in standby.healthz()
        # the deposed writer's zombie apply publishes → fenced AT the
        # store, loudly — split-brain is impossible, not refused by
        # convention
        with pytest.raises(PublishFencedError):
            primary.apply_delta({"insert": [[3, 16]]}, delta_id="zombie")
        fenced = [r for r in sink.records if r["phase"] == "publish_fenced"]
        assert fenced and fenced[-1]["store_epoch"] == 1
        promotes = [r for r in sink.records if r["phase"] == "writer_promote"]
        assert promotes and promotes[-1]["epoch"] == 1
    finally:
        standby.stop()
        try:
            primary.stop()
        except Exception:  # noqa: BLE001 — listener already killed
            pass
    assert validate_records(sink.records) == []

    # the offline report renders the failover timeline from the JSONL
    import sys
    sys.path.insert(
        0, os.path.join(os.path.dirname(__file__), "..", "tools")
    )
    import obs_report

    report = obs_report.build_report(sink.records)
    assert "-- writer failover (WAL / promotion / fencing) --" in report
    assert "WRITER PROMOTE" in report
    assert "PUBLISH FENCED" in report
    assert "wal appends:" in report


def test_promote_separate_store_standby_rewinds_and_loses_nothing(tmp_path):
    """A standby running its OWN bootstrap copy of the store (no shared
    filesystem): the shipper mirrors the primary's watermark, which
    describes a store this replica does not have — promotion must place
    the replay cursor from the shipped watermark HISTORY at the adopted
    snapshot's version, so shipped-but-locally-unapplied acked deltas
    replay instead of being masked as applied (the documented loss
    bound is the shipped lag — here zero — not the bootstrap age)."""
    import shutil

    sink = _sink()
    store, *_ = _publish_base(tmp_path, sink=sink)
    primary = SnapshotServer(store, sink=sink, wal=str(tmp_path / "wal-p"))
    host, port = primary.start()
    # bootstrap the standby's store as a copy at v1, BEFORE any deltas
    shutil.copytree(str(tmp_path / "snap"), str(tmp_path / "snap-b"))
    store_b = SnapshotStore(str(tmp_path / "snap-b"))
    standby = SnapshotServer(
        store_b, sink=sink, wal=str(tmp_path / "wal-s"),
        standby_of=f"http://{host}:{port}",
    )
    try:
        for i in range(2):
            primary.apply_delta(
                {"insert": [[i, 13 + i]]}, delta_id=f"acked{i}", ack="wal",
            )
        assert primary.wait_applied(60)
        standby._shipper.poll_once()
        # fully shipped: lag 0, watermark mirrored past the local store
        assert standby.wal.last_seq == primary.wal.last_seq
        assert standby.wal.applied_version > store_b.peek_version()
        primary.stop()
        with pytest.warns(UserWarning, match="rewinding the replay"):
            out = standby.promote()
        assert out["promoted"] and out["replayed"] == 2
        assert standby.wait_applied(120)
        edges = _edges(standby.engine)
        assert (0, 13) in edges and (1, 14) in edges  # zero acked loss
        warns = [r for r in sink.records if r["phase"] == "warning"]
        assert any("rewinding the replay cursor" in r["message"]
                   for r in warns)
    finally:
        standby.stop()
        try:
            primary.stop()
        except Exception:  # noqa: BLE001 — already stopped
            pass
    assert validate_records(sink.records) == []


# ---- THE acceptance chaos test --------------------------------------------


def _fast_config(**overrides):
    kv = dict(
        probe_interval_s=0.08,
        probe_timeout_s=4.0,
        read_timeout_s=0.4,
        down_after_probes=2,
        reload_cadence_s=0.1,
        rejoin_timeout_s=15.0,
        breaker_backoff_base_s=0.3,
        breaker_backoff_max_s=1.0,
        retry_after_s=1.0,
        default_deadline_ms=5000,
        promote_timeout_s=120.0,
    )
    kv.update(overrides)
    return FleetConfig(**kv)


def test_writer_failover_chaos_acceptance(tmp_path):
    """ISSUE 10 acceptance: a 2-writer/3-replica fleet under a live
    read + write hammer. SIGKILL the primary mid-burst → the standby is
    promoted within the bound, EVERY 202-acknowledged delta is present
    in the final served snapshot (zero acknowledged loss), readers see
    ZERO mixed-version responses throughout, and the deposed writer's
    comeback publish is fenced with a loud ``publish_fenced`` record."""
    sink = _sink()
    store, src, dst, v = _publish_base(tmp_path)
    wal_p = str(tmp_path / "wal-r0")
    w0 = SnapshotServer(store, sink=sink, wal=wal_p)
    h0, p0 = w0.start()
    w1 = SnapshotServer(
        store, sink=sink, wal=str(tmp_path / "wal-r1"),
        standby_of=f"http://{h0}:{p0}", primary_wal=wal_p,
        ship_interval_s=0.05,
    )
    h1, p1 = w1.start()
    w2 = SnapshotServer(store)
    h2, p2 = w2.start()
    router = FleetRouter(
        [ReplicaSpec("r0", h0, p0), ReplicaSpec("r1", h1, p1),
         ReplicaSpec("r2", h2, p2)],
        writer="r0", standby="r1", sink=sink, config=_fast_config(),
    )
    rh, rp = router.start()

    hammer_errors: list = []
    acked: dict = {}           # delta_id -> (src, dst)
    acked_lock = threading.Lock()
    stop_writes = threading.Event()
    stop_reads = threading.Event()
    rng = np.random.default_rng(29)
    write_pairs = [
        (int(rng.integers(0, v)), int(rng.integers(0, v)))
        for _ in range(200)
    ]

    ok_reads = [0]

    def read_hammer(tid):
        seen = []
        while not stop_reads.is_set():
            try:
                code, body, headers = _post(
                    rh, rp, "/query", {"vertices": [0, 13, 27]},
                    timeout=30,
                )
                if code == 503:
                    # unavailable-CONSISTENT, by design: under the write
                    # burst the committed version churns faster than the
                    # prober converges, and the router refuses rather
                    # than mixing versions. A real client obeys
                    # Retry-After; a WRONG answer is what fails the test.
                    time.sleep(0.05)
                    continue
                if code != 200:
                    raise AssertionError(f"read failed: HTTP {code} {body}")
                if body["version"] != int(headers["X-Pinned-Version"]):
                    raise AssertionError(
                        f"MIXED VERSION: body v{body['version']} != pin "
                        f"{headers['X-Pinned-Version']}"
                    )
                seen.append(body["version"])
            except Exception as e:  # noqa: BLE001 — collect, assert later
                hammer_errors.append(e)
                return
            time.sleep(0.01)
        if seen != sorted(seen):
            hammer_errors.append(
                AssertionError(f"reader {tid} saw versions go backwards")
            )
        ok_reads[0] += len(seen)

    def write_hammer(tid):
        i = 0
        while not stop_writes.is_set():
            delta_id = f"wh{tid}-{i}"
            pair = write_pairs[(tid * 97 + i) % len(write_pairs)]
            i += 1
            try:
                code, body, _ = _post(
                    rh, rp, "/delta", {"insert": [list(pair)]},
                    headers={
                        "X-Delta-Id": delta_id, "X-Delta-Ack": "wal",
                    },
                    timeout=30,
                )
            except Exception:  # noqa: BLE001 — router mid-failover
                continue
            if code in (200, 202):
                # acknowledged: MUST survive everything below
                with acked_lock:
                    acked[delta_id] = pair
            time.sleep(0.005)

    readers = [
        threading.Thread(target=read_hammer, args=(i,)) for i in range(2)
    ]
    writers = [
        threading.Thread(target=write_hammer, args=(i,)) for i in range(2)
    ]
    try:
        deadline = time.monotonic() + 10
        while (
            router.replica_set.committed_version() is None
            and time.monotonic() < deadline
        ):
            time.sleep(0.02)
        for t in readers + writers:
            t.start()
        time.sleep(0.8)  # a real burst is in flight, some applied

        # SIGKILL the primary MID-BURST
        t_kill = time.monotonic()
        faults.writer_kill_mid_apply(w0)

        # the fleet promotes the standby within the bound
        bound_s = 20.0
        while time.monotonic() - t_kill < bound_s:
            rs = router.replica_set
            if rs.writer_id == "r1" and not rs.read_only:
                break
            time.sleep(0.05)
        time_to_writable = time.monotonic() - t_kill
        assert router.replica_set.writer_id == "r1", (
            f"standby not promoted within {bound_s}s: "
            f"{router.replica_set.snapshot()}"
        )
        assert time_to_writable < bound_s

        # keep hammering the promoted writer, then settle
        time.sleep(0.8)
        stop_writes.set()
        for t in writers:
            t.join(timeout=30)
        assert w1.wait_applied(300)
        stop_reads.set()
        for t in readers:
            t.join(timeout=30)

        # ZERO read failures / mixed versions (503s were retried — the
        # consistency choice, not a failure; served answers must exist)
        assert hammer_errors == [], hammer_errors[:3]
        assert ok_reads[0] > 20

        # ZERO acknowledged-delta loss: every 202'd batch is in the
        # final snapshot (count multiplicity so duplicates would show)
        eng = w1.engine
        counts: dict = {}
        for s, d in zip(
            np.asarray(eng.snapshot["src"]).tolist(),
            np.asarray(eng.snapshot["dst"]).tolist(),
        ):
            counts[(s, d)] = counts.get((s, d), 0) + 1
        with acked_lock:
            assert acked, "the burst never acknowledged anything"
            lost = [
                (did, pair) for did, pair in acked.items()
                if counts.get(pair, 0) < 1
            ]
        assert lost == [], f"{len(lost)} acknowledged deltas lost: {lost[:5]}"

        # the deposed writer's comeback publish is fenced, loudly —
        # either this very apply hits the store fence (first fenced
        # attempt raises), or a prior background apply already did and
        # the writer latched deposed, refusing at the front door (503)
        # before it can acknowledge into a black hole
        try:
            out = w0.apply_delta(
                {"insert": [[0, 13]]}, delta_id="deposed-comeback",
            )
        except PublishFencedError:
            pass
        else:
            assert out["verdict"] == "shed" and "fenced" in out["reason"], out
        fenced = [r for r in sink.records if r["phase"] == "publish_fenced"]
        assert fenced, "no publish_fenced record from the deposed writer"

        # the promotion trail is complete and loud
        promotes = [
            r for r in sink.records if r["phase"] == "writer_promote"
        ]
        assert any(r.get("replica") == "r1" for r in promotes)
        flips = [r for r in sink.records if r["phase"] == "fleet_degraded"]
        assert any(r["read_only"] for r in flips)          # loss was loud
        assert flips[-1]["read_only"] is False             # and bounded
        # post-promotion, writes flow through the router to r1
        code, body, headers = _post(rh, rp, "/delta", {"insert": [[0, 20]]})
        assert code == 200 and headers["X-Fleet-Replica"] == "r1"
    finally:
        stop_writes.set()
        stop_reads.set()
        router.stop()
        for s in (w0, w1, w2):
            try:
                s.stop()
            except Exception:  # noqa: BLE001 — killed replicas
                pass
    assert validate_records(sink.records) == []


# ---- review hardening: contiguous floor / compaction guard / fence lock ---


def test_wal_contiguous_floor_never_jumps_an_unresolved_gap(tmp_path):
    """The commit watermark is a CONTIGUOUS floor: publishing seq 2
    while acked seq 1 is still unapplied (the append-vs-enqueue race
    window) must not advance the floor past 1 — a crash in that window
    would make restart replay skip the acknowledged entry (silent
    loss). The published-over-a-gap seq persists in ``applied_above``
    so the crash can't double-apply it either."""
    root = str(tmp_path / "wal")
    w = WriteAheadLog(root)
    for i in range(3):
        w.append({"insert": [[i, i + 1]]}, delta_id=f"d{i}")
    w.commit_applied([2], snapshot_version=5)
    assert w.applied_seq == 0                      # floor held below the gap
    assert w.seq_applied(2) and not w.seq_applied(1)
    assert [e["seq"] for e in w.pending()] == [1, 3]
    w.close()
    # the parked seq survives a crash: replay still excludes it
    w2 = WriteAheadLog(root)
    assert w2.applied_seq == 0 and w2.seq_applied(2)
    assert [e["seq"] for e in w2.pending()] == [1, 3]
    # resolving the gap lets the floor sweep through the parked seq
    w2.commit_applied([1], snapshot_version=6)
    assert w2.applied_seq == 2 and w2.applied_version == 6
    assert [e["seq"] for e in w2.pending()] == [3]
    assert w2.commit_history()[-1] == (2, 6)
    w2.commit_applied([3], snapshot_version=7)
    assert w2.applied_seq == 3
    # tombstones are non-work: the floor passes the shed target AND the
    # tombstone record itself
    w2.append({"insert": [[7, 8]]}, delta_id="shed-me")       # seq 4
    w2.append({"insert": [[8, 9]]}, delta_id="applies")       # seq 5
    w2.skip(4)                                                # seq 6
    w2.commit_applied([5], snapshot_version=8)
    assert w2.applied_seq == 6 and w2.pending() == []
    w2.close()


def test_publish_over_inflight_gap_replays_exactly_once(tmp_path):
    """Server-level pin for the race: an acked WAL entry that never
    reached the apply queue (writer died post-fsync, pre-enqueue) must
    replay on restart even though a LATER seq already published — and
    the published one must not replay (the manifest's
    ``wal_applied_above`` voucher)."""
    sink = _sink()
    store, src, dst, v = _publish_base(tmp_path, sink=sink)
    wal_dir = str(tmp_path / "wal")
    server = SnapshotServer(store, sink=sink, wal=wal_dir)
    base_edges = len(np.asarray(server.engine.snapshot["src"]))
    # seq 1: acked (fsync'd) but never enqueued — the crash window
    seq, dup = server.wal.append({"insert": [[0, 13]]}, delta_id="inflight")
    assert seq == 1 and not dup
    # seq 2: a normal delta that applies and publishes over the gap
    out = server.apply_delta({"insert": [[0, 14]]}, delta_id="applies")
    assert out["version"] > 0
    assert server.wal.applied_seq == 0          # floor held below seq 1
    assert server.wal.seq_applied(2)
    faults.writer_kill_mid_apply(server)
    # restart: replay applies ONLY seq 1 — seq 2 is vouched applied
    sink2 = _sink()
    server2 = SnapshotServer(store, sink=sink2, wal=wal_dir)
    assert server2.wait_applied(120)
    edges = _edges(server2.engine)
    assert (0, 13) in edges and (0, 14) in edges
    assert len(np.asarray(server2.engine.snapshot["src"])) == base_edges + 2
    replays = [r for r in sink2.records if r["phase"] == "wal_replay"]
    assert replays and replays[0]["entries"] == 1
    assert server2.wal.applied_seq == server2.wal.last_seq
    server2.stop()
    assert validate_records(sink2.records) == []


def test_standby_compaction_protects_its_own_store_version(tmp_path):
    """A standby's WAL mirrors the PRIMARY's watermark — compacting
    against it would prune entries this replica's own (possibly old)
    bootstrap store has not absorbed, which a separate-store promotion
    must replay. ``protect_version`` pins the prune floor to the seq
    vouched for the LOCAL store version; no vouching pair = protect
    everything."""
    root = str(tmp_path / "wal")
    w = WriteAheadLog(root, segment_max_bytes=64, retain_segments=1)
    w.note_baseline(7)                      # local bootstrap store is v7
    n = 12
    for i in range(n):
        w.append({"insert": [[i, i + 1]]}, delta_id=f"d{i}")
    assert len(w.entries(1)) == n
    # mirrored primary watermark says all shipped+applied...
    w.protect_version = 7                   # ...but OUR store is still v7
    w.merge_history([(n, 40)])
    assert w.applied_seq == n
    assert len(w.entries(1)) == n, "standby pruned entries its store lacks"
    # an unvouched local version also protects everything
    w.protect_version = 99
    w.append({"insert": [[n, n + 1]]}, delta_id="more")
    w.commit(n + 1, snapshot_version=41)
    assert w.entries(1)[0]["seq"] == 1
    # promotion clears the guard: normal retention applies again
    w.protect_version = None
    w.append({"insert": [[n + 1, n + 2]]}, delta_id="post")
    w.commit(n + 2, snapshot_version=42)
    assert w.entries(1)[0]["seq"] > 1, "cleared guard should allow pruning"
    w.close()


def test_fence_epoch_mid_publish_cannot_evict_promoted_generation(tmp_path):
    """The fence re-check and the generation rotation hold the fence
    lock together: a promotion landing while a deposed writer's publish
    is between its array writes and its commit rename still fences it,
    and the promoted writer's generation is never rotated away."""
    store, src, dst, v = _publish_base(tmp_path)
    arrays = {
        "src": src, "dst": dst,
        "labels": np.zeros(v, np.int32), "cc_labels": np.zeros(v, np.int32),
        "lof": np.zeros(v, np.float32),
    }
    fenced_during_publish = threading.Event()

    def promote_mid_publish():
        store.fence_epoch(5, reason="test promotion")
        store.publish(arrays, epoch=5)
        fenced_during_publish.set()
        return None                       # side-effect hook, no raise

    inj = faults.FaultInjector()
    inj.add("snapshot_publish_commit", promote_mid_publish, at=1, repeat=1)
    with inj.installed():
        with pytest.raises(PublishFencedError):
            store.publish(arrays, epoch=0)
    assert fenced_during_publish.is_set()
    # the promoted writer's generation survived the deposed commit
    snap = store.load()
    assert snap.writer_epoch == 5
    assert store.current_epoch() == 5


def test_unknown_delta_ack_mode_is_refused(tmp_path):
    """An unknown ``X-Delta-Ack`` must 400, not silently downgrade to
    the blocking path (the client believes it asked for the fast
    durable 202 and would block to its full deadline instead)."""
    store, *_ = _publish_base(tmp_path)
    server = SnapshotServer(store, wal=str(tmp_path / "wal"))
    host, port = server.start()
    try:
        code, body, _ = _post(
            host, port, "/delta", {"insert": [[0, 13]]},
            headers={"X-Delta-Ack": "fsync"},
        )
        assert code == 400
        assert "X-Delta-Ack" in body["error"]
        # the canonical mode still answers 202 at the durability point
        code, body, _ = _post(
            host, port, "/delta", {"insert": [[0, 13]]},
            headers={"X-Delta-Ack": "wal", "X-Delta-Id": "ok-1"},
        )
        assert code == 202 and body["verdict"] == "accepted"
    finally:
        server.stop()


def test_wal_pending_gauge_counts_only_above_floor(tmp_path):
    """The pending-entries gauge must count acked-but-unpublished work
    exactly: once the contiguous floor advances past a tombstoned pair,
    those seqs may not keep subtracting (the all-time skipped set would
    make the gauge read 0 while a durable acknowledged delta still
    awaits apply — the exact backlog signal /healthz promises)."""
    w = WriteAheadLog(str(tmp_path / "wal"))
    s1, _ = w.append({"insert": [[0, 1]]}, delta_id="a")
    w.commit_applied([s1], 2)
    s2, _ = w.append({"insert": [[0, 2]]}, delta_id="b")
    w.skip(s2)  # shed off the queue: tombstone record takes seq 3
    s4, _ = w.append({"insert": [[0, 3]]}, delta_id="c")
    w.commit_applied([s4], 3)  # floor walks over the tombstoned pair
    assert w.applied_seq == s4
    assert w.snapshot()["pending_entries"] == 0
    s5, _ = w.append({"insert": [[0, 4]]}, delta_id="d")
    snap = w.snapshot()
    assert snap["pending_entries"] == 1, snap
    assert [e["seq"] for e in w.pending()] == [s5]
    # a tombstoned-but-not-yet-passed seq DOES subtract: shed seq 6 via
    # tombstone seq 7 while s5 still blocks the floor below them
    s6, _ = w.append({"insert": [[0, 5]]}, delta_id="e")
    w.skip(s6)
    assert w.snapshot()["pending_entries"] == 1
    w.close()


def test_fenced_writer_refuses_new_writes(tmp_path):
    """A deposed-but-alive writer whose publish came back fenced must
    stop answering 202 for NEW deltas: its publishes refuse forever and
    the promoted writer never tails a zombie's WAL, so each further
    acceptance would acknowledge work into a black hole. Reads keep
    serving; /healthz says why."""
    sink = _sink()
    store, *_ = _publish_base(tmp_path, sink=sink)
    server = SnapshotServer(store, sink=sink, wal=str(tmp_path / "wal"))
    try:
        # a rival promotion fences the store's epoch past this writer
        SnapshotStore(store.root).advance_epoch(reason="rival promotion")
        out = server.apply_delta(
            {"insert": [[0, 13]]}, delta_id="doomed", ack="wal",
        )
        # accepted before the fence is discovered (the WAL entry stays
        # durable; a later re-promotion of this process replays it)
        assert out["verdict"] == "accepted"
        server.wait_applied(60)  # the background publish hits the fence
        deadline = time.monotonic() + 30
        while server._fenced is None and time.monotonic() < deadline:
            time.sleep(0.02)
        assert server._fenced is not None
        refused = server.apply_delta(
            {"insert": [[0, 14]]}, delta_id="late", ack="wal",
        )
        assert refused["verdict"] == "shed"
        assert "fenced" in refused["reason"]
        hz = server.healthz()
        assert hz["ok"] and "fenced" in hz
        assert any(r["phase"] == "publish_fenced" for r in sink.records)
        # reads still serve from the last good snapshot
        assert server.engine.version >= 1
        # /promote re-fences in OUR favor and reopens the write path
        res = server.promote()
        assert res["promoted"] and server._fenced is None
        ok = server.apply_delta({"insert": [[0, 15]]}, delta_id="after")
        assert ok.get("verdict") != "shed" and "version" in ok, ok
        assert "fenced" not in server.healthz()
    finally:
        server.stop()
    assert validate_records(sink.records) == []
