"""Cross-process observability plane (ISSUE 11, docs/OBSERVABILITY.md
"Fleet tracing"): trace-context propagation, per-delta time-to-visible,
the federated metrics plane, and the stitching/gating tools.

Marker ``trace`` (``tools/run_tier1.sh --trace-only``). The acceptance
pin is :func:`test_fleet_chaos_trace_stitch_acceptance`: a 3-replica
chaos run (kill + roll + writer failover) whose per-process JSONL shards
alone reconstruct at least one COMPLETE per-delta timeline (admission →
WAL fsync → apply → publish → replica visible) and the failover
epoch-fence sequence, with zero half-stamped trace records.
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from graphmine_tpu.graph.container import build_graph
from graphmine_tpu.obs.histogram import Histogram
from graphmine_tpu.obs.schema import validate_records
from graphmine_tpu.obs.spans import TRACE_HEADER, TraceContext, Tracer
from graphmine_tpu.pipeline.checkpoint import graph_fingerprint
from graphmine_tpu.pipeline.metrics import MetricsSink, shard_sink
from graphmine_tpu.serve.delta import cold_recompute
from graphmine_tpu.serve.fleet import FleetConfig, FleetRouter, ReplicaSpec
from graphmine_tpu.serve.server import SnapshotServer
from graphmine_tpu.serve.snapshot import SnapshotStore
from graphmine_tpu.testing import faults

pytestmark = pytest.mark.trace

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if os.path.join(_REPO, "tools") not in sys.path:
    sys.path.insert(0, os.path.join(_REPO, "tools"))


# ---- helpers (the test_fleet.py idioms) -----------------------------------


def _clique(lo, hi):
    ids = np.arange(lo, hi)
    s, d = np.meshgrid(ids, ids)
    m = s.ravel() < d.ravel()
    return s.ravel()[m], d.ravel()[m]


def _publish_base(tmp_path):
    parts = [_clique(0, 12), _clique(12, 26), _clique(26, 40)]
    src = np.concatenate([p[0] for p in parts]).astype(np.int32)
    dst = np.concatenate([p[1] for p in parts]).astype(np.int32)
    v = 40
    g = build_graph(src, dst, num_vertices=v)
    labels, cc, _ = cold_recompute(g)
    store = SnapshotStore(str(tmp_path / "snap"))
    store.publish(
        {
            "src": src, "dst": dst, "labels": labels, "cc_labels": cc,
            "lof": np.zeros(v, np.float32),
        },
        fingerprint=graph_fingerprint(src, dst),
    )
    return store, v


def _post(host, port, path, payload, timeout=60, headers=None):
    req = urllib.request.Request(
        f"http://{host}:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def _get(host, port, path, timeout=30):
    with urllib.request.urlopen(
        f"http://{host}:{port}{path}", timeout=timeout
    ) as r:
        body = r.read()
        ct = r.headers.get("Content-Type", "")
    return json.loads(body) if "json" in ct else body.decode()


def _fast_config(**overrides):
    kv = dict(
        probe_interval_s=0.08,
        probe_timeout_s=4.0,
        read_timeout_s=1.0,
        down_after_probes=2,
        reload_cadence_s=0.1,
        rejoin_timeout_s=15.0,
        breaker_backoff_base_s=0.3,
        breaker_backoff_max_s=1.0,
        retry_after_s=1.0,
        default_deadline_ms=8000,
        promote_timeout_s=120.0,
    )
    kv.update(overrides)
    return FleetConfig(**kv)


# ---- TraceContext wire format ---------------------------------------------


def test_trace_context_header_roundtrip():
    ctx = TraceContext("ab" * 8, "cd" * 4)
    header = ctx.to_header()
    assert header == f"00-{'ab' * 8}-{'cd' * 4}-01"
    assert TraceContext.from_header(header) == ctx
    off = TraceContext("ab" * 8, "cd" * 4, sampled=False)
    assert TraceContext.from_header(off.to_header()) == off


@pytest.mark.parametrize("bad", [
    "", "garbage", "00-xyz-abc-01", "00-abcd1234-ef-01",
    "zz-" + "ab" * 8 + "-" + "cd" * 4 + "-01",
    "00-" + "ab" * 8 + "-" + "cd" * 4,          # 3 parts
    "00-" + "ab" * 40 + "-" + "cd" * 4 + "-01",  # trace_id too long
    "00-" + "AB" * 8 + "-" + "cd" * 4 + "-0\n",  # hostile flags
    None, 7,
])
def test_trace_context_malformed_headers_parse_to_none(bad):
    assert TraceContext.from_header(bad) is None


def test_trace_context_header_is_case_normalized():
    header = "00-" + "AB" * 8 + "-" + "CD" * 4 + "-01"
    ctx = TraceContext.from_header(header)
    assert ctx is not None and ctx.trace_id == "ab" * 8


# ---- span adoption / per-record trace identity ----------------------------


def test_span_adoption_new_trace_and_inheritance():
    sink = MetricsSink(tracer=Tracer())
    run_trace = sink.tracer.trace_id
    # default: records ride the run trace
    assert sink.emit("warning", message="x")["trace_id"] == run_trace
    # new_trace: the subtree is its own trace, nested spans inherit
    with sink.span("req", emit=False, new_trace=True) as sp:
        assert sp.trace_id != run_trace
        assert sink.emit("warning", message="x")["trace_id"] == sp.trace_id
        with sink.tracer.span("child") as child:
            assert child.trace_id == sp.trace_id
            assert child.path == "req/child"
    # remote: adopts the sender's identity, parents under its span
    ctx = TraceContext("12" * 8, "34" * 4)
    with sink.span("adopt", emit=False, remote=ctx) as sp:
        assert sp.trace_id == ctx.trace_id
        assert sp.parent_id == ctx.span_id
        rec = sink.emit("warning", message="y")
        assert rec["trace_id"] == ctx.trace_id
        assert validate_records([rec]) == []
    # back out of the span: the run trace again
    assert sink.emit("warning", message="z")["trace_id"] == run_trace
    with pytest.raises(ValueError):
        with sink.tracer.span("both", remote=ctx, new_trace=True):
            pass


def test_span_context_roundtrips_through_header():
    tracer = Tracer()
    with tracer.span("a") as sp:
        ctx = TraceContext.from_header(sp.context().to_header())
        assert ctx == TraceContext(sp.trace_id, sp.span_id)


# ---- Histogram.merge property tests (ISSUE 11 satellite) ------------------


def _hist(vals, buckets=(0.001, 0.01, 0.1, 1.0)):
    h = Histogram("h", buckets=buckets)
    for v in vals:
        h.observe(v)
    return h


def test_histogram_merge_commutative_and_associative_random():
    rng = np.random.default_rng(7)
    for _ in range(10):
        a, b, c = (
            rng.gamma(1.0, 0.05, size=rng.integers(0, 40)).tolist()
            for _ in range(3)
        )
        ab_c = _hist(a).merge(_hist(b)).merge(_hist(c)).snapshot()
        a_bc = _hist(a).merge(_hist(b).merge(_hist(c))).snapshot()
        ba = _hist(b).merge(_hist(a)).snapshot()
        ab = _hist(a).merge(_hist(b)).snapshot()
        assert ab_c.counts == a_bc.counts          # associative
        assert ab_c.count == len(a) + len(b) + len(c)
        assert ab.counts == ba.counts              # commutative
        assert ab.sum == pytest.approx(ba.sum)
        # merge == observing the union directly
        union = _hist(a + b + c).snapshot()
        assert ab_c.counts == union.counts
        assert ab_c.sum == pytest.approx(union.sum)


def test_histogram_merge_mismatched_ladder_raises():
    a = _hist([0.5], buckets=(0.1, 1.0))
    b = _hist([0.5], buckets=(0.2, 1.0))
    with pytest.raises(ValueError, match="bucket ladders"):
        a.merge(b)
    c = _hist([0.5], buckets=(0.1, 1.0, 10.0))
    with pytest.raises(ValueError, match="bucket ladders"):
        a.merge(c)


def test_histogram_merge_of_labeled_children():
    from graphmine_tpu.obs.histogram import HistogramFamily

    fam = HistogramFamily("ttv", buckets=(0.01, 0.1, 1.0))
    fam.labels(replica="r0").observe(0.05)
    fam.labels(replica="r0").observe(0.5)
    fam.labels(replica="r1").observe(0.005)
    fam.labels(replica="r2")  # zero observations merges as identity
    merged = Histogram("m", buckets=fam.bounds)
    for child in fam.children():
        merged.merge(child)
    snap = merged.snapshot()
    assert snap.count == 3
    # counter-wise equality against the children's summed buckets
    summed = [0] * (len(fam.bounds) + 1)
    for child in fam.children():
        for i, cnt in enumerate(child.snapshot().counts):
            summed[i] += cnt
    assert list(snap.counts) == summed


# ---- schema lint (ISSUE 11 satellite) -------------------------------------


def test_schema_lint_package_is_clean():
    import schema_lint

    assert schema_lint.violations() == []
    found = schema_lint.scan()
    # sanity: the scan actually sees the well-known emit sites
    phases = {p for p, _, _ in found}
    assert {"wal_append", "delta_stages", "admission", "lpa_iter"} <= phases


def test_schema_lint_catches_unregistered_phase(tmp_path):
    import schema_lint

    bad = tmp_path / "mod.py"
    bad.write_text(
        'def f(sink):\n'
        '    sink.emit(\n'
        '        "definitely_not_registered_phase", x=1)\n'
        '    sink.emit("wal_append", seq=1)\n'
    )
    out = schema_lint.violations(str(tmp_path))
    assert len(out) == 1
    assert "definitely_not_registered_phase" in out[0]
    assert "mod.py:2" in out[0]


# ---- obs_report strict gate (ISSUE 11 satellite) --------------------------


def test_obs_report_fails_on_half_stamped_records(tmp_path, capsys):
    from tools.obs_report import main as report_main

    mo = str(tmp_path / "m.jsonl")
    sink = MetricsSink(stream_path=mo, tracer=Tracer())
    sink.emit("run_start", pid=1)
    sink.emit("warning", message="fine")
    # a half-stamped record: run_id without the rest of the identity
    with open(mo, "a") as f:
        f.write(json.dumps({
            "phase": "warning", "t": time.time(), "message": "rotted",
            "run_id": sink.tracer.run_id,
        }) + "\n")
    assert report_main([mo]) == 3
    err = capsys.readouterr().err
    assert "partial trace identity" in err
    assert report_main([mo, "--lenient"]) == 0
    # unknown phases fail the same gate
    mo2 = str(tmp_path / "m2.jsonl")
    sink2 = MetricsSink(stream_path=mo2, tracer=Tracer())
    sink2.emit("run_start", pid=1)
    with open(mo2, "a") as f:
        f.write(json.dumps(
            {"phase": "not_a_phase", "t": time.time()}
        ) + "\n")
    capsys.readouterr()
    assert report_main([mo2]) == 3
    # and a clean stream still exits 0
    mo3 = str(tmp_path / "m3.jsonl")
    sink3 = MetricsSink(stream_path=mo3, tracer=Tracer())
    sink3.emit("run_start", pid=1)
    sink3.emit("run_end", ok=True)
    capsys.readouterr()
    assert report_main([mo3]) == 0


# ---- trace_stitch units ---------------------------------------------------


def test_trace_stitch_joins_shards_and_gates_stamping(tmp_path, capsys):
    import trace_stitch

    obs = tmp_path / "obs"
    writer = shard_sink(str(obs), "writer")
    router = shard_sink(str(obs), "router")
    ctx = TraceContext("fe" * 8, "dc" * 4)
    with writer.span("http:delta", emit=False, remote=ctx):
        writer.emit("admission", verdict="accept", reason="", rows=2,
                    queue_depth=0, repair_debt={})
        writer.emit("wal_append", seq=1, rows=2, bytes=100, seconds=0.001)
        writer.emit("delta_stages", version=2, seq=1, stages={
            "wal_fsync_s": 0.001, "queued_s": 0.0, "apply_s": 0.1,
            "total_s": 0.101,
        })
        writer.emit("snapshot_publish", version=2, snapshot_id="x",
                    path="p", bytes=10, arrays=["labels"], seconds=0.01)
    with router.span("fleet:delta", emit=False, remote=ctx):
        router.emit("delta_visible", replica="r1", version=2,
                    seconds=0.2)
    records, bad, problems = trace_stitch.load_shards([str(obs)])
    assert bad == 0 and problems == []
    traces = trace_stitch.stitch(records)
    deltas = trace_stitch.delta_traces(traces)
    assert ctx.trace_id in deltas
    _, stages = deltas[ctx.trace_id]
    assert all(stages.values()), stages
    assert trace_stitch.main([str(obs)]) == 0
    out = capsys.readouterr().out
    assert "verdict: COMPLETE" in out
    assert "2 process(es)" in out
    # a half-stamped record fails the gate (exit 3), --lenient downgrades
    with open(obs / "rotten.jsonl", "w") as f:
        f.write(json.dumps({
            "phase": "warning", "t": time.time(), "message": "x",
            "trace_id": "aa" * 8,
        }) + "\n")
    assert trace_stitch.main([str(obs)]) == 3
    capsys.readouterr()
    assert trace_stitch.main([str(obs), "--lenient"]) == 0
    capsys.readouterr()
    assert trace_stitch.main([str(tmp_path / "empty")]) == 2


def test_obs_report_directory_mode_renders_fleet_traces(tmp_path, capsys):
    """obs_report accepts a fleet --obs-dir: shards merge into one view
    and the fleet-traces section renders the trace_stitch join inline,
    each line attributed to the emitting process."""
    from tools.obs_report import main as report_main

    obs = tmp_path / "obs"
    writer = shard_sink(str(obs), "writer")
    router = shard_sink(str(obs), "router")
    ctx = TraceContext("ab" * 8, "cd" * 4)
    with writer.span("http:delta", emit=False, remote=ctx):
        writer.emit("admission", verdict="accept", reason="", rows=2,
                    queue_depth=0, repair_debt={})
        writer.emit("wal_append", seq=1, rows=2, bytes=100, seconds=0.001)
        writer.emit("delta_stages", version=2, seq=1, stages={
            "wal_fsync_s": 0.001, "queued_s": 0.0, "apply_s": 0.1,
            "total_s": 0.101,
        })
        writer.emit("snapshot_publish", version=2, snapshot_id="x",
                    path="p", bytes=10, arrays=["labels"], seconds=0.01)
    with router.span("fleet:delta", emit=False, remote=ctx):
        router.emit("delta_visible", replica="r1", version=2,
                    seconds=0.2)
    assert report_main([str(obs)]) == 0
    out = capsys.readouterr().out
    assert "-- fleet traces (cross-process timelines) --" in out
    assert "verdict: COMPLETE" in out
    assert "complete per-delta timelines: 1/1" in out
    # shard attribution: the line for wal_append names the writer shard,
    # delta_visible the router shard
    assert any("writer-" in ln and "wal_append" in ln
               for ln in out.splitlines())
    assert any("router-" in ln and "delta_visible" in ln
               for ln in out.splitlines())


# ---- stdlib-only surface (acceptance) -------------------------------------


def test_obs_and_tools_import_without_jax():
    """obs/ and the triage tools must load on a machine with no jax at
    all — a meta-path blocker in a child process proves it (the lazy
    PEP 562 package __init__ is what makes this possible)."""
    code = (
        "import sys\n"
        "class Block:\n"
        "    def find_module(self, name, path=None):\n"
        "        if name == 'jax' or name.startswith('jax.'):\n"
        "            return self\n"
        "    def load_module(self, name):\n"
        "        raise ImportError('jax blocked: ' + name)\n"
        "sys.meta_path.insert(0, Block())\n"
        f"sys.path.insert(0, {_REPO!r})\n"
        f"sys.path.insert(0, {os.path.join(_REPO, 'tools')!r})\n"
        "import graphmine_tpu\n"
        "import graphmine_tpu.obs.schema\n"
        "from graphmine_tpu.obs import Histogram, TraceContext, Tracer\n"
        "import obs_report, trace_stitch, schema_lint\n"
        "print('ok')\n"
    )
    p = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=120,
    )
    assert p.returncode == 0, p.stderr[-2000:]
    assert "ok" in p.stdout


# ---- POST /profilez -------------------------------------------------------


def test_profilez_disabled_answers_403(tmp_path):
    store, _ = _publish_base(tmp_path)
    srv = SnapshotServer(store)
    host, port = srv.start()
    try:
        code, body, _ = _post(host, port, "/profilez", {"duration_ms": 10})
        assert code == 403
        assert "disabled" in body["error"]
    finally:
        srv.stop()


def test_profilez_degrades_501_when_profiler_unavailable(
    tmp_path, monkeypatch,
):
    import jax

    store, _ = _publish_base(tmp_path)
    srv = SnapshotServer(store, profilez_dir=str(tmp_path / "prof"))
    host, port = srv.start()

    def boom(*a, **kw):
        raise RuntimeError("no profiler on this build")

    monkeypatch.setattr(jax.profiler, "start_trace", boom)
    try:
        code, body, _ = _post(host, port, "/profilez", {"duration_ms": 10})
        assert code == 501
        assert "unavailable" in body["error"]
    finally:
        srv.stop()


def test_profilez_captures_and_tags_with_trace_id(tmp_path):
    sink = MetricsSink(tracer=Tracer())
    store, _ = _publish_base(tmp_path)
    srv = SnapshotServer(
        store, sink=sink, profilez_dir=str(tmp_path / "prof"),
    )
    host, port = srv.start()
    ctx = TraceContext("ba" * 8, "dc" * 4)
    try:
        code, body, _ = _post(
            host, port, "/profilez", {"duration_ms": 30},
            headers={TRACE_HEADER: ctx.to_header()},
        )
        assert code == 200, body
        assert body["trace_id"] == ctx.trace_id
        assert ctx.trace_id in body["dir"]
        assert os.path.isdir(body["dir"])
        caps = [r for r in sink.records if r["phase"] == "profile_capture"]
        assert caps and caps[-1]["ok"] is True
        assert caps[-1]["trace_id"] == ctx.trace_id
    finally:
        srv.stop()


# ---- writer-side delta stages + trace adoption ----------------------------


def test_delta_stages_record_in_the_clients_trace(tmp_path):
    sink = MetricsSink(tracer=Tracer())
    store, _ = _publish_base(tmp_path)
    srv = SnapshotServer(store, sink=sink, wal=str(tmp_path / "wal"))
    host, port = srv.start()
    ctx = TraceContext("aa" * 8, "bb" * 4)
    try:
        code, body, _ = _post(
            host, port, "/delta", {"insert": [[1, 39]]},
            headers={TRACE_HEADER: ctx.to_header()},
        )
        assert code == 200 and body["version"] == 2
        by_phase = {}
        for r in sink.records:
            by_phase.setdefault(r["phase"], []).append(r)
        # the whole writer-side chain landed in the CLIENT's trace:
        # middleware adoption (access_log, admission, wal_append) plus
        # worker-side leader-span adoption (delta_apply,
        # snapshot_publish) plus the per-batch stage record
        for phase in ("access_log", "admission", "wal_append",
                      "delta_apply", "snapshot_publish", "delta_stages"):
            recs = [
                r for r in by_phase.get(phase, ())
                if r.get("trace_id") == ctx.trace_id
            ]
            assert recs, f"{phase} not in the client's trace"
        stages = [
            r for r in by_phase["delta_stages"]
            if r["trace_id"] == ctx.trace_id
        ][-1]["stages"]
        assert set(stages) == {
            "wal_fsync_s", "queued_s", "apply_s", "total_s"
        }
        assert stages["total_s"] >= stages["apply_s"] >= 0
        # the WAL entry carries the header durably
        entry = srv.wal.entries(1)[0]
        assert TraceContext.from_header(
            entry["trace"]
        ).trace_id == ctx.trace_id
        # /statusz serves the per-stage breakdown
        statusz = _get(host, port, "/statusz")
        assert "total" in statusz["delta_stages"]
        assert statusz["delta_stages"]["wal_fsync"]["count"] >= 1
        assert validate_records(sink.records) == []
    finally:
        srv.stop()


# ---- router: time-to-visible merged histogram + statusz -------------------


def test_router_time_to_visible_merged_equals_counterwise_sum(tmp_path):
    """Acceptance: the router /metrics merged time_to_visible histogram's
    bucket counters equal the counter-wise sum of the per-replica
    snapshots, asserted via Histogram.merge."""
    sink = MetricsSink(tracer=Tracer())
    store, _ = _publish_base(tmp_path)
    servers = [SnapshotServer(store, sink=sink, wal=str(tmp_path / "wal"))]
    servers += [SnapshotServer(store) for _ in range(2)]
    addrs = [s.start() for s in servers]
    specs = [
        ReplicaSpec(f"r{i}", h, p) for i, (h, p) in enumerate(addrs)
    ]
    router = FleetRouter(
        specs, writer="r0", sink=sink, config=_fast_config(),
    )
    rh, rp = router.start()
    try:
        deadline = time.monotonic() + 10
        while (
            router.replica_set.committed_version() is None
            and time.monotonic() < deadline
        ):
            time.sleep(0.02)
        for pair in ([1, 39], [2, 38]):
            code, body, _ = _post(rh, rp, "/delta", {"insert": [pair]})
            assert code == 200, body
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            with router._vis_lock:
                drained = not router._visibility
            if drained:
                break
            time.sleep(0.05)
        fam = router.registry.histogram_family(
            "graphmine_fleet_time_to_visible_seconds"
        )
        assert fam is not None
        children = fam.children()
        assert {c.labels["replica"] for c in children} == {"r0", "r1", "r2"}
        # every (delta, replica) leg observed: 2 deltas x 3 replicas
        assert sum(c.snapshot().count for c in children) == 6
        merged = router.time_to_visible_merged()
        reference = Histogram("ref", buckets=fam.bounds)
        for child in children:
            reference.merge(child)
        assert merged.snapshot().counts == reference.snapshot().counts
        assert merged.snapshot().count == 6
        # the merged series rides the /metrics exposition
        text = _get(rh, rp, "/metrics")
        assert "graphmine_fleet_time_to_visible_merged_seconds_count" in text
        assert "graphmine_fleet_time_to_visible_seconds" in text
        # /statusz: per-replica + merged quantiles, breaker last reasons,
        # writer epoch, WAL state — the gap-fill satellite
        statusz = _get(rh, rp, "/statusz")
        assert set(statusz["time_to_visible"]) == {
            "r0", "r1", "r2", "merged"
        }
        assert statusz["time_to_visible"]["merged"]["count"] == 6
        assert statusz["writer_epoch"] is not None
        assert statusz["wal"] is not None      # the writer runs a WAL
        for rep in statusz["replicas"]:
            assert "state_reason" in rep
            assert "last_transition_reason" in rep["breaker"]
        # delta_visible records emitted, schema-clean
        vis = [r for r in sink.records if r["phase"] == "delta_visible"]
        assert len(vis) == 6
        assert validate_records(sink.records) == []
    finally:
        router.stop()
        for s in servers:
            s.stop()


# ---- THE acceptance: chaos run -> shards -> stitched timelines ------------


def test_fleet_chaos_trace_stitch_acceptance(tmp_path):
    """ISSUE 11 acceptance: 3-replica fleet chaos (kill + roll + writer
    failover) with per-process shards under one --obs-dir; the shards
    ALONE reconstruct at least one complete per-delta timeline and the
    failover epoch-fence sequence, with no half-stamped records."""
    import trace_stitch

    obs = str(tmp_path / "obs")
    store, _ = _publish_base(tmp_path)
    s_writer = shard_sink(obs, "writer")
    s_standby = shard_sink(obs, "standby")
    s_replica = shard_sink(obs, "replica-2")
    s_router = shard_sink(obs, "router")
    wal_p = str(tmp_path / "wal-r0")
    w0 = SnapshotServer(store, sink=s_writer, wal=wal_p)
    h0, p0 = w0.start()
    w1 = SnapshotServer(
        store, sink=s_standby, wal=str(tmp_path / "wal-r1"),
        standby_of=f"http://{h0}:{p0}", primary_wal=wal_p,
        ship_interval_s=0.05,
    )
    h1, p1 = w1.start()
    w2 = SnapshotServer(store, sink=s_replica)
    h2, p2 = w2.start()
    router = FleetRouter(
        [ReplicaSpec("r0", h0, p0), ReplicaSpec("r1", h1, p1),
         ReplicaSpec("r2", h2, p2)],
        writer="r0", standby="r1", sink=s_router, config=_fast_config(),
    )
    rh, rp = router.start()
    sinks = (s_writer, s_standby, s_replica, s_router)
    try:
        deadline = time.monotonic() + 10
        while (
            router.replica_set.committed_version() is None
            and time.monotonic() < deadline
        ):
            time.sleep(0.02)

        # two synchronous deltas through the router: the per-delta
        # timelines under test
        for i, pair in enumerate(([1, 39], [2, 38])):
            code, body, _ = _post(
                rh, rp, "/delta", {"insert": [pair]},
                headers={"X-Delta-Id": f"acc-{i}"},
            )
            assert code == 200, body
        # let the prober close every replica's visibility leg
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            with router._vis_lock:
                if not router._visibility:
                    break
            time.sleep(0.05)

        # a read for trace variety
        _get(rh, rp, "/vertex?v=1")

        # CHAOS leg 1 — kill + restart a read replica (health churn)
        faults.replica_kill(w2)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if router.replica_set.replica("r2").state == "down":
                break
            router.probe_once()
            time.sleep(0.05)
        assert router.replica_set.replica("r2").state == "down"
        w2b = SnapshotServer(store, sink=s_replica, host=h2, port=p2)
        bind_deadline = time.monotonic() + 10
        while True:
            try:
                w2b.start()
                break
            except OSError:
                if time.monotonic() >= bind_deadline:
                    raise
                time.sleep(0.2)

        # CHAOS leg 2 — rolling reload (the roll walk in the stitch)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if router.replica_set.replica("r2").state == "healthy":
                break
            time.sleep(0.05)
        roll = router.rolling_reload()
        assert roll["ok"], roll

        # CHAOS leg 3 — writer kill, fenced failover onto the standby
        t_kill = time.monotonic()
        faults.writer_kill_mid_apply(w0)
        while time.monotonic() - t_kill < 20.0:
            rs = router.replica_set
            if rs.writer_id == "r1" and not rs.read_only:
                break
            time.sleep(0.05)
        assert router.replica_set.writer_id == "r1"

        # the deposed writer's comeback publish is fenced (loud record)
        try:
            out = w0.apply_delta({"insert": [[0, 13]]},
                                 delta_id="deposed-comeback")
        except Exception:  # noqa: BLE001 — PublishFencedError path
            pass
        else:
            assert out["verdict"] == "shed", out

        # one more delta through the promoted writer
        code, body, _ = _post(
            rh, rp, "/delta", {"insert": [[3, 37]]},
            headers={"X-Delta-Id": "acc-post-failover"},
        )
        assert code == 200, body
    finally:
        router.stop()
        for s in (w0, w1, w2):
            try:
                s.stop()
            except Exception:  # noqa: BLE001 — killed replicas
                pass
        try:
            w2b.stop()
        except Exception:  # noqa: BLE001 — may not exist on early failure
            pass
        for s in sinks:
            s.finalize(s.stream_path)

    # ---- the stitch, from the shards alone ----------------------------
    records, bad, problems = trace_stitch.load_shards([obs])
    assert problems == [], problems[:10]       # zero half-stamped records
    traces = trace_stitch.stitch(records)
    deltas = trace_stitch.delta_traces(traces)
    complete = [
        tid for tid, (_, stages) in deltas.items() if all(stages.values())
    ]
    assert complete, {
        tid: stages for tid, (_, stages) in deltas.items()
    }
    # the complete timeline genuinely crosses processes
    recs, _ = deltas[complete[0]]
    assert len({r["_src"] for r in recs}) >= 2
    # the failover epoch-fence sequence is reconstructable
    phases = {r["phase"] for r in records}
    assert {"writer_promote", "publish_fenced", "fleet_degraded"} <= phases
    report = trace_stitch.build_report(records, bad, problems)
    assert "verdict: COMPLETE" in report
    assert "writer_promote" in report
    assert "publish_fenced" in report
    assert "== failover sequence" in report
    assert "== rolling reload walk" in report
    # and the CLI gate passes end-to-end
    assert trace_stitch.main([obs, "--out", str(tmp_path / "r.txt")]) == 0
