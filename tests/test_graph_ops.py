"""PageRank / BFS / shortest paths / triangles / k-core vs oracles
(networkx where available, hand-computed otherwise) — SURVEY §4's
algorithm-semantics test strategy applied to the extended engine surface."""

import numpy as np
import pytest

from graphmine_tpu.graph.container import build_graph
from graphmine_tpu.ops.degrees import degrees, in_degrees, out_degrees
from graphmine_tpu.ops.kcore import core_numbers
from graphmine_tpu.ops.pagerank import pagerank
from graphmine_tpu.ops.paths import UNREACHABLE, bfs_distances, shortest_paths
from graphmine_tpu.ops.triangles import clustering_coefficient, triangle_count

nx = pytest.importorskip("networkx")


def _random_digraph(rng, v=40, e=160):
    src = rng.integers(0, v, e).astype(np.int32)
    dst = rng.integers(0, v, e).astype(np.int32)
    return src, dst


def test_degrees(rng):
    src, dst = _random_digraph(rng)
    g = build_graph(src, dst, num_vertices=40)
    np.testing.assert_array_equal(np.asarray(out_degrees(g)), np.bincount(src, minlength=40))
    np.testing.assert_array_equal(np.asarray(in_degrees(g)), np.bincount(dst, minlength=40))
    np.testing.assert_array_equal(
        np.asarray(degrees(g)),
        np.bincount(src, minlength=40) + np.bincount(dst, minlength=40),
    )


def test_pagerank_matches_networkx(rng):
    src, dst = _random_digraph(rng)
    g = build_graph(src, dst, num_vertices=40)
    got = np.asarray(pagerank(g, alpha=0.85, max_iter=200, tol=1e-10))
    gnx = nx.MultiDiGraph()
    gnx.add_nodes_from(range(40))
    gnx.add_edges_from(zip(src.tolist(), dst.tolist()))
    want = nx.pagerank(gnx, alpha=0.85, max_iter=200, tol=1e-12)
    want = np.array([want[i] for i in range(40)])
    np.testing.assert_allclose(got, want, atol=1e-5)
    assert abs(got.sum() - 1.0) < 1e-4


def test_pagerank_personalized(rng):
    src, dst = _random_digraph(rng)
    g = build_graph(src, dst, num_vertices=40)
    reset = np.zeros(40, np.float32)
    reset[3] = 1.0
    got = np.asarray(pagerank(g, reset=reset, max_iter=200, tol=1e-10))
    gnx = nx.MultiDiGraph()
    gnx.add_nodes_from(range(40))
    gnx.add_edges_from(zip(src.tolist(), dst.tolist()))
    want = nx.pagerank(gnx, alpha=0.85, personalization={i: float(reset[i]) for i in range(40)},
                       max_iter=200, tol=1e-12)
    want = np.array([want[i] for i in range(40)])
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_bfs_directed_chain():
    g = build_graph([0, 1, 2], [1, 2, 3], num_vertices=5)
    d = np.asarray(bfs_distances(g, np.array([0]), direction="out"))
    np.testing.assert_array_equal(d, [0, 1, 2, 3, UNREACHABLE])
    d_both = np.asarray(bfs_distances(g, np.array([3]), direction="both"))
    np.testing.assert_array_equal(d_both, [3, 2, 1, 0, UNREACHABLE])


def test_bfs_matches_networkx(rng):
    src, dst = _random_digraph(rng, v=60, e=150)
    g = build_graph(src, dst, num_vertices=60)
    d = np.asarray(bfs_distances(g, np.array([7]), direction="out"))
    gnx = nx.DiGraph()
    gnx.add_nodes_from(range(60))
    gnx.add_edges_from(zip(src.tolist(), dst.tolist()))
    want = nx.single_source_shortest_path_length(gnx, 7)
    for v in range(60):
        if v in want:
            assert d[v] == want[v], v
        else:
            assert d[v] == UNREACHABLE, v


def test_shortest_paths_landmarks(rng):
    src, dst = _random_digraph(rng, v=50, e=120)
    g = build_graph(src, dst, num_vertices=50)
    landmarks = [2, 11, 29]
    got = np.asarray(shortest_paths(g, landmarks, direction="out"))
    assert got.shape == (50, 3)
    gnx = nx.DiGraph()
    gnx.add_nodes_from(range(50))
    gnx.add_edges_from(zip(src.tolist(), dst.tolist()))
    for j, lm in enumerate(landmarks):
        # GraphFrames semantics: distance from each vertex TO the landmark
        want = nx.single_source_shortest_path_length(gnx.reverse(), lm)
        for v in range(50):
            if v in want:
                assert got[v, j] == want[v]
            else:
                assert got[v, j] == UNREACHABLE


def test_triangles_matches_networkx(rng):
    src, dst = _random_digraph(rng, v=50, e=300)
    g = build_graph(src, dst, num_vertices=50)
    tri, total = triangle_count(g)
    tri = np.asarray(tri)
    gnx = nx.Graph()
    gnx.add_nodes_from(range(50))
    gnx.add_edges_from(zip(src.tolist(), dst.tolist()))
    gnx.remove_edges_from(nx.selfloop_edges(gnx))
    want = nx.triangles(gnx)
    np.testing.assert_array_equal(tri, [want[i] for i in range(50)])
    assert int(total) == sum(want.values()) // 3

    cc = np.asarray(clustering_coefficient(g))
    want_cc = nx.clustering(gnx)
    np.testing.assert_allclose(cc, [want_cc[i] for i in range(50)], atol=1e-6)


def test_triangle_free():
    g = build_graph([0, 1, 2], [1, 2, 3], num_vertices=4)  # path: no triangles
    tri, total = triangle_count(g)
    assert int(total) == 0
    np.testing.assert_array_equal(np.asarray(tri), 0)


def test_sampled_clustering_tracks_exact(rng):
    """The wedge-sampled estimator stays inside its binomial error bound
    against the exact pipeline (VERDICT r3 item 5): per-vertex stderr is
    sqrt(c(1-c)/S) <= 1/(2*sqrt(S)); we pin a 4.5-sigma worst-case
    envelope plus a much tighter mean-error band, and exactness on
    degenerate vertices (deg < 2 -> 0, cliques -> 1)."""
    from graphmine_tpu.ops.triangles import sampled_clustering_coefficient

    src = rng.integers(0, 200, 2000)
    dst = rng.integers(0, 200, 2000)
    g = build_graph(src, dst, num_vertices=200)
    exact = np.asarray(clustering_coefficient(g))
    s = 256
    approx = sampled_clustering_coefficient(g, samples=s, seed=3)
    err = np.abs(approx - exact)
    assert err.max() <= 4.5 * 0.5 / np.sqrt(s) + 1e-6, err.max()
    assert err.mean() <= 1.5 * 0.5 / np.sqrt(s), err.mean()
    # determinism: same seed, same result — and because draws are a
    # stateless hash of (seed, vertex, sample), the chunk_vertices memory
    # knob CANNOT change the estimates
    again = sampled_clustering_coefficient(g, samples=s, seed=3)
    np.testing.assert_array_equal(approx, again)
    chunked = sampled_clustering_coefficient(
        g, samples=s, seed=3, chunk_vertices=17
    )
    np.testing.assert_array_equal(chunked, approx)
    # a different seed draws different wedges
    other = sampled_clustering_coefficient(g, samples=s, seed=4)
    assert not np.array_equal(other, approx)

    # exactly 0/1 where the estimator has no variance
    tri_g = build_graph([0, 1, 2], [1, 2, 0], num_vertices=5)  # K3 + isolates
    got = sampled_clustering_coefficient(tri_g, samples=8, seed=0)
    np.testing.assert_array_equal(got, [1.0, 1.0, 1.0, 0.0, 0.0])


def test_oriented_wedge_count_matches_expansion(rng):
    """The feasibility probe (r5: the exact wedge expansion OOM-killed a
    mega-hub 25M-edge run at 130 GB host RSS) counts EXACTLY the wedges
    ``_oriented_csr`` would materialize — pinned against the real
    expansion on random digraphs and on a hub star."""
    from graphmine_tpu.ops.triangles import _oriented_csr, oriented_wedge_count

    for v, e in ((60, 400), (200, 2000)):
        src = rng.integers(0, v, e)
        dst = rng.integers(0, v, e)
        g = build_graph(src, dst, num_vertices=v)
        want = len(_oriented_csr(g)[2])  # wedge_u length = expansion size
        assert oriented_wedge_count(g) == want

    # star: all edges orient away from the high-degree hub, so the hub's
    # quadratic wedge set never materializes — the count must reflect the
    # ORIENTED expansion (leaves' rows), not sum d(d-1)/2
    n = 50
    star = build_graph(np.zeros(n - 1, np.int32),
                       np.arange(1, n, dtype=np.int32), num_vertices=n)
    want = len(_oriented_csr(star)[2])
    assert oriented_wedge_count(star) == want

    # the shared-dedup plumbing (code-review r5): a precomputed
    # simple_undirected_edges pair gives identical results everywhere
    from graphmine_tpu.graph.container import simple_undirected_edges
    from graphmine_tpu.ops.triangles import sampled_clustering_coefficient

    g = build_graph(rng.integers(0, 80, 600), rng.integers(0, 80, 600),
                    num_vertices=80)
    se = simple_undirected_edges(g)
    assert oriented_wedge_count(g, simple_edges=se) == oriented_wedge_count(g)
    np.testing.assert_array_equal(
        np.asarray(clustering_coefficient(g, simple_edges=se)),
        np.asarray(clustering_coefficient(g)),
    )
    np.testing.assert_array_equal(
        sampled_clustering_coefficient(g, seed=2, simple_edges=se),
        sampled_clustering_coefficient(g, seed=2),
    )


def test_vertex_features_sampled_clustering_mode(rng):
    """r5: ``vertex_features(include_clustering="sampled")`` — the
    wedge-budget fallback the driver uses — matches the exact-feature
    matrix on every column except clustering, and the clustering column
    is the sampled estimator (bounded error vs exact)."""
    from graphmine_tpu.ops.features import vertex_features
    from graphmine_tpu.ops.lpa import label_propagation

    src = rng.integers(0, 300, 3000)
    dst = rng.integers(0, 300, 3000)
    g = build_graph(src, dst, num_vertices=300)
    labels = label_propagation(g, max_iter=3)
    exact = np.asarray(vertex_features(g, labels))
    sampled = np.asarray(vertex_features(g, labels, include_clustering="sampled"))
    np.testing.assert_array_equal(exact[:, :7], sampled[:, :7])
    assert np.abs(exact[:, 7] - sampled[:, 7]).max() <= 4.5 * 0.5 / np.sqrt(64) + 1e-6
    zeroed = np.asarray(vertex_features(g, labels, include_clustering=False))
    np.testing.assert_array_equal(zeroed[:, 7], 0.0)
    with np.testing.assert_raises(ValueError):
        vertex_features(g, labels, include_clustering="sample")


def test_kcore_matches_networkx(rng):
    src, dst = _random_digraph(rng, v=60, e=400)
    g = build_graph(src, dst, num_vertices=60)
    got = np.asarray(core_numbers(g))
    gnx = nx.Graph()
    gnx.add_nodes_from(range(60))
    gnx.add_edges_from(zip(src.tolist(), dst.tolist()))
    gnx.remove_edges_from(nx.selfloop_edges(gnx))
    want = nx.core_number(gnx)
    np.testing.assert_array_equal(got, [want[i] for i in range(60)])


def test_kcore_clique_plus_tail():
    # K4 (core 3) with a tail vertex (core 1) and an isolated vertex (core 0)
    edges = [(0, 1), (0, 2), (0, 3), (1, 2), (1, 3), (2, 3), (3, 4)]
    src, dst = np.array(edges, np.int32).T
    g = build_graph(src, dst, num_vertices=6)
    np.testing.assert_array_equal(np.asarray(core_numbers(g)), [3, 3, 3, 3, 1, 0])


def test_build_graph_rejects_out_of_range_endpoints():
    import pytest

    from graphmine_tpu.graph.container import build_graph

    for use_native in (True, False):
        with pytest.raises(ValueError, match="range"):
            build_graph(np.array([5], np.int32), np.array([0], np.int32),
                        num_vertices=3, symmetric=False, use_native=use_native)


def test_build_graph_and_plan_shares_csr():
    import jax
    import jax.numpy as jnp

    from graphmine_tpu.ops.bucketed_mode import (
        build_graph_and_plan,
        lpa_superstep_bucketed,
    )
    from graphmine_tpu.ops.lpa import lpa_superstep

    rng = np.random.default_rng(2)
    src = rng.integers(0, 64, 300).astype(np.int32)
    dst = rng.integers(0, 64, 300).astype(np.int32)
    g, plan = build_graph_and_plan(src, dst, num_vertices=64)
    assert plan.send_idx is not None
    labels = jnp.asarray(rng.integers(0, 64, 64).astype(np.int32))
    np.testing.assert_array_equal(
        np.asarray(jax.jit(lpa_superstep)(labels, g)),
        np.asarray(jax.jit(lpa_superstep_bucketed)(labels, g, plan)),
    )


def test_device_plan_matches_host_plan():
    """from_ptr(send_device=...) must be bit-identical to the host path —
    including hub-histogram spans — since the fused superstep consumes
    either interchangeably."""
    import jax.numpy as jnp

    import importlib

    # the ops package re-exports a *function* named bucketed_mode, which
    # shadows the submodule under plain `import ... as`
    bm = importlib.import_module("graphmine_tpu.ops.bucketed_mode")
    from graphmine_tpu.graph.container import _message_csr, _prepare_edges

    rng = np.random.default_rng(5)
    v, e = 512, 20_000  # hub degrees exceed a lowered histogram threshold
    src = np.minimum(rng.geometric(0.02, e) - 1, v - 1).astype(np.int32)
    dst = rng.integers(0, v, e).astype(np.int32)
    src, dst, v = _prepare_edges(src, dst, v)
    ptr, recv, send, _ = _message_csr(src, dst, v, True, True)

    old = bm._HIST_MIN_DEG
    bm._HIST_MIN_DEG = 64
    try:
        host = bm.BucketedModePlan.from_ptr(ptr, v, send)
        dev = bm.BucketedModePlan.from_ptr(ptr, v, send,
                                           send_device=jnp.asarray(send))
    finally:
        bm._HIST_MIN_DEG = old

    assert len(host.send_idx) == len(dev.send_idx)
    for a, b in zip(host.send_idx, dev.send_idx):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(host.vertex_ids, dev.vertex_ids):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert (host.hist_send is None) == (dev.hist_send is None)
    if host.hist_send is not None:
        np.testing.assert_array_equal(np.asarray(host.hist_send),
                                      np.asarray(dev.hist_send))
        np.testing.assert_array_equal(np.asarray(host.hist_row_offset),
                                      np.asarray(dev.hist_row_offset))
        np.testing.assert_array_equal(np.asarray(host.hist_vertex_ids),
                                      np.asarray(dev.hist_vertex_ids))
