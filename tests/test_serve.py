"""Serving-layer suite (marker ``serve``): versioned snapshots, delta
ingest with warm-start repair, the batched query engine and the HTTP
front end — tools/run_tier1.sh --serve-only.

The acceptance pins (ISSUE 5):
- snapshot round-trip is byte-identical; a mismatched graph fingerprint
  refuses; a kill mid-publish leaves the previous snapshot loadable and
  a corrupt generation rolls back to ``.prev``;
- warm-start repair labels are IDENTICAL to a cold full recompute for
  insert-only, delete-only and mixed delta batches, and the tripwire
  fallback path is exercised by fault injection;
- a live query server swaps to a newly published snapshot without
  dropping in-flight queries;
- ``query_batch`` / ``delta_apply`` / ``snapshot_publish`` records are
  schema-registered, span-joined and rendered by tools/obs_report.py.
"""

import json
import os
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from graphmine_tpu.graph.container import build_graph
from graphmine_tpu.obs.schema import validate_records
from graphmine_tpu.obs.spans import Tracer
from graphmine_tpu.pipeline.checkpoint import (
    CheckpointCorruptionError,
    FingerprintMismatch,
    graph_fingerprint,
)
from graphmine_tpu.pipeline.metrics import MetricsSink
from graphmine_tpu.serve import (
    DeltaIngestor,
    EdgeDelta,
    QueryEngine,
    SnapshotStore,
)
from graphmine_tpu.serve.delta import (
    cold_recompute,
    frontier_budget,
    repair_labels,
    splice_edges,
    validate_delta,
)
from graphmine_tpu.testing import faults

pytestmark = pytest.mark.serve


# ---- fixtures -------------------------------------------------------------


def _clique(lo, hi):
    ids = np.arange(lo, hi)
    s, d = np.meshgrid(ids, ids)
    m = s.ravel() < d.ravel()
    return s.ravel()[m], d.ravel()[m]


def _community_graph(extra=()):
    """Three well-separated cliques (LPA converges to one fixpoint from
    any init — what makes warm-vs-cold equality decidable) plus optional
    extra edges."""
    parts = [_clique(0, 12), _clique(12, 26), _clique(26, 40)]
    src = np.concatenate([p[0] for p in parts] + [np.asarray([e[0] for e in extra], np.int64)])
    dst = np.concatenate([p[1] for p in parts] + [np.asarray([e[1] for e in extra], np.int64)])
    return src.astype(np.int32), dst.astype(np.int32), 40


def _sink():
    return MetricsSink(tracer=Tracer())


def _publish_base(tmp_path, src, dst, v, sink=None):
    g = build_graph(src, dst, num_vertices=v)
    labels, cc, _ = cold_recompute(g)
    store = SnapshotStore(str(tmp_path / "snap"))
    store.publish(
        {
            "src": src, "dst": dst, "labels": labels, "cc_labels": cc,
            "lof": np.linspace(0.5, 2.5, v).astype(np.float32),
        },
        fingerprint=graph_fingerprint(src, dst),
        sink=sink,
    )
    return store, g, labels, cc


# ---- snapshot store -------------------------------------------------------


def test_snapshot_roundtrip_byte_identical(tmp_path):
    src, dst, v = _community_graph()
    sink = _sink()
    store, g, labels, cc = _publish_base(tmp_path, src, dst, v, sink=sink)
    snap = store.load(fingerprint=graph_fingerprint(src, dst), sink=sink)
    assert snap.version == 1 and snap.parent == ""
    for name, want in (("src", src), ("dst", dst), ("labels", labels),
                       ("cc_labels", cc)):
        got = snap[name]
        assert got.dtype == want.dtype
        assert got.tobytes() == np.asarray(want).tobytes()
    # second publish continues the version/parent chain
    snap2 = store.publish(
        dict(snap.arrays), fingerprint=snap.fingerprint, sink=sink
    )
    assert snap2.version == 2
    assert snap2.parent == snap.snapshot_id
    assert validate_records(sink.records) == []


def test_snapshot_fingerprint_refusal(tmp_path):
    src, dst, v = _community_graph()
    store, *_ = _publish_base(tmp_path, src, dst, v)
    other = graph_fingerprint(dst, src)  # permuted graph: different identity
    with pytest.raises(FingerprintMismatch, match="different graph"):
        store.load(fingerprint=other)
    # no rollback happened: the real fingerprint still loads generation 1
    assert store.load(fingerprint=graph_fingerprint(src, dst)).version == 1


def test_torn_publish_leaves_previous_loadable(tmp_path):
    """A kill between writing the tmp generation and the publish rename
    (the snapshot_publish_commit fault seam) must leave the previous
    snapshot the loadable one — and the next publish must succeed."""
    src, dst, v = _community_graph()
    store, g, labels, cc = _publish_base(tmp_path, src, dst, v)
    arrays = dict(store.load().arrays)
    inj = faults.FaultInjector()
    inj.add("snapshot_publish_commit", faults.preemption)
    with inj.installed():
        with pytest.raises(faults.SimulatedPreemption):
            store.publish(arrays, fingerprint=graph_fingerprint(src, dst))
    assert inj.fired("snapshot_publish_commit") == 1
    snap = store.load()
    assert snap.version == 1  # the survivor is the previous generation
    # the orphaned tmp generation is swept by the next publish, which lands
    snap2 = store.publish(arrays, fingerprint=graph_fingerprint(src, dst))
    assert snap2.version == 2
    assert not [
        p for p in os.listdir(store.root) if ".tmp." in p
    ], "stale tmp generations must be swept"


def test_publish_version_chain_survives_missing_current_generation(tmp_path):
    """A kill in the window between the two publish renames leaves only
    ``.prev`` intact; the next publish must continue the version/parent
    chain from it — never reset to version 1 (version regressions would
    break the server's swap comparison and the provenance chain)."""
    import shutil

    src, dst, v = _community_graph()
    store, *_ = _publish_base(tmp_path, src, dst, v)
    snap1 = store.load()
    arrays = dict(snap1.arrays)
    store.publish(arrays, fingerprint=snap1.fingerprint)  # v2, rotates v1
    shutil.rmtree(store._gen())  # crash window: only .prev (v1) remains
    snap = store.publish(arrays, fingerprint=snap1.fingerprint)
    assert snap.version == 2
    assert snap.parent == snap1.snapshot_id


@pytest.mark.parametrize(
    "damage", ["not_json", "bad_checksum", "missing_array"]
)
def test_publish_condemns_corrupt_current_generation(tmp_path, damage):
    """A current generation whose manifest is unreadable, fails its
    checksum, or is missing an array file must NOT rotate into ``.prev``
    on the next publish — that would evict the only intact snapshot and
    install garbage as the rollback target. It gets condemned aside
    (*.corrupt) and the intact ``.prev`` survives."""
    src, dst, v = _community_graph()
    store, *_ = _publish_base(tmp_path, src, dst, v)
    snap1 = store.load()
    arrays = dict(snap1.arrays)
    store.publish(arrays, fingerprint=snap1.fingerprint)  # v2, rotates v1
    man = os.path.join(store._gen(), "manifest.json")
    if damage == "not_json":
        with open(man, "w") as f:
            f.write("{not json")
    elif damage == "bad_checksum":
        # parseable JSON, damaged body: the loader's checksum verdict
        body = json.load(open(man))
        body["run_id"] = "tampered"
        with open(man, "w") as f:
            json.dump(body, f)
    else:  # intact manifest, GB-scale damage: an array file vanished
        os.remove(os.path.join(store._gen(), "labels.npy"))
    snap = store.publish(arrays, fingerprint=snap1.fingerprint)
    # chain continued from the intact .prev (v1), not reset to 1
    assert snap.version == 2 and snap.parent == snap1.snapshot_id
    assert store.load().version == 2
    # .prev still holds the intact v1; the damaged dir is set aside
    with open(os.path.join(store._prev(), "manifest.json")) as f:
        assert json.load(f)["version"] == 1
    assert any(".corrupt" in p for p in os.listdir(store.root))


def test_corrupt_generation_rolls_back_to_prev(tmp_path):
    src, dst, v = _community_graph()
    sink = _sink()
    store, *_ = _publish_base(tmp_path, src, dst, v, sink=sink)
    snap1 = store.load()
    store.publish(dict(snap1.arrays), fingerprint=snap1.fingerprint)
    # damage one array of the CURRENT generation; load must roll back to
    # the rotated .prev and keep serving
    faults.corrupt_file(os.path.join(store._gen(), "labels.npy"))
    snap = store.load(sink=sink)
    assert snap is not None and snap.version == 1
    assert [r["phase"] for r in sink.records if "rollback" in r["phase"]] == [
        "checkpoint_rollback", "checkpoint_rollback_ok"
    ]
    # condemned generation preserved for forensics
    assert any(".corrupt" in p for p in os.listdir(store.root))
    # both generations damaged -> loud, names the files tried
    faults.corrupt_file(os.path.join(store._gen(), "labels.npy"))
    with pytest.raises(CheckpointCorruptionError):
        store.load()


# ---- delta validation / splice --------------------------------------------


def test_from_pairs_wire_validation():
    """JSON-wire hygiene: integral floats are accepted (encoders emit
    40.0 for 40), fractional or non-numeric ids raise ValueError — never
    a silent truncation of 1.9 to vertex 1, never a TypeError."""
    d = EdgeDelta.from_pairs(insert=[[40.0, 12.0]])
    assert d.insert_src.tolist() == [40] and d.insert_dst.tolist() == [12]
    with pytest.raises(ValueError, match="integers"):
        EdgeDelta.from_pairs(insert=[[1.9, 2.7]])
    with pytest.raises(ValueError, match="integers"):
        EdgeDelta.from_pairs(delete=[[1, None]])
    with pytest.raises(ValueError, match="pairs"):
        EdgeDelta.from_pairs(insert=None)


def test_validate_delta_quarantines_bad_rows():
    delta = EdgeDelta.from_pairs(
        insert=[(1, 2), (-3, 4), (10**9, 2)],
        delete=[(0, 1), (999, 0), (-1, -1)],
    )
    clean, q = validate_delta(delta, num_vertices=40)
    assert clean.num_inserts == 1 and clean.num_deletes == 1
    assert q == {"out_of_range_ids": 2, "unmatched_deletes": 2}


def test_splice_multiset_delete():
    src = np.asarray([0, 0, 0, 1], np.int32)
    dst = np.asarray([1, 1, 1, 2], np.int32)
    delta = EdgeDelta.from_pairs(delete=[(0, 1), (0, 1), (5, 5)])
    src2, dst2, v2, stats = splice_edges(src, dst, 3, delta)
    # exactly two of the three (0,1) occurrences removed; (5,5) unmatched
    assert list(zip(src2.tolist(), dst2.tolist())) == [(0, 1), (1, 2)]
    assert stats == {"inserted": 0, "deleted": 2, "unmatched_deletes": 1}
    assert v2 == 3


def test_splice_insert_grows_vertex_space():
    src = np.asarray([0], np.int32)
    dst = np.asarray([1], np.int32)
    src2, dst2, v2, stats = splice_edges(
        src, dst, 2, EdgeDelta.from_pairs(insert=[(5, 1)])
    )
    assert v2 == 6 and stats["inserted"] == 1
    assert (src2.tolist(), dst2.tolist()) == ([0, 5], [1, 1])


# ---- warm-start repair equivalence (the correctness gate) -----------------


@pytest.mark.parametrize(
    "insert,delete",
    [
        # insert-only: a new vertex joins clique 2, plus intra-clique fill
        ([(40, 12), (40, 13), (40, 14), (0, 5)], []),
        # delete-only: thin out clique 1 and cut clique 3 internally
        ([], [(0, 1), (0, 2), (26, 27)]),
        # mixed: grow one community while shrinking another
        ([(40, 26), (40, 27), (40, 28)], [(12, 13), (12, 14)]),
    ],
    ids=["insert_only", "delete_only", "mixed"],
)
def test_repair_equals_cold_recompute(insert, delete):
    src, dst, v = _community_graph()
    g = build_graph(src, dst, num_vertices=v)
    labels, cc, _ = cold_recompute(g)
    delta, _ = validate_delta(EdgeDelta.from_pairs(insert, delete), v)
    src2, dst2, v2, _ = splice_edges(src, dst, v, delta)
    g2 = build_graph(src2, dst2, num_vertices=v2)
    result = repair_labels(g2, labels, cc, delta)
    assert result.method == "warm", result.fallback_reason
    cold_l, cold_c, _ = cold_recompute(g2)
    np.testing.assert_array_equal(result.labels, cold_l)
    np.testing.assert_array_equal(result.cc_labels, cold_c)


def test_cc_repair_exact_on_random_graph():
    """CC repair is exact BY CONSTRUCTION (monotone min from valid upper
    bounds) — pin it on an adversarial random graph where components
    split and merge, not just cliques."""
    rng = np.random.default_rng(3)
    v, e = 300, 500
    src = rng.integers(0, v, e).astype(np.int32)
    dst = rng.integers(0, v, e).astype(np.int32)
    g = build_graph(src, dst, num_vertices=v)
    _, cc, _ = cold_recompute(g)
    delta, _ = validate_delta(
        EdgeDelta.from_pairs(
            insert=[(int(a), int(b)) for a, b in
                    zip(rng.integers(0, v, 20), rng.integers(0, v, 20))],
            delete=[(int(s), int(d)) for s, d in
                    zip(src[:25].tolist(), dst[:25].tolist())],
        ),
        v,
    )
    src2, dst2, v2, _ = splice_edges(src, dst, v, delta)
    g2 = build_graph(src2, dst2, num_vertices=v2)
    from graphmine_tpu.serve.delta import _warm_cc, cc_repair_init

    repaired, _, conv = _warm_cc(
        g2, cc_repair_init(cc, v2, delta), frontier_budget(v2, v2)
    )
    assert conv
    from graphmine_tpu.ops.cc import connected_components

    np.testing.assert_array_equal(
        repaired, np.asarray(connected_components(g2))
    )


@pytest.mark.faults
def test_repair_fallback_on_injected_corruption(tmp_path):
    """The tripwire path: silent corruption of the repaired state (a
    poison_labels-style mutator at the delta_repair seam) must be caught
    by the sampled exact check, emit repair_fallback, and republish the
    cold-recompute labels — never the garbage."""
    src, dst, v = _community_graph()
    sink = _sink()
    store, g, labels, cc = _publish_base(tmp_path, src, dst, v, sink=sink)
    ing = DeltaIngestor(store, sink=sink, lof_k=4, check_samples=16)
    delta = EdgeDelta.from_pairs(insert=[(40, 12), (40, 13)])
    inj = faults.FaultInjector()
    inj.add("delta_repair", faults.poison_labels(shard=0, num_shards=1))
    with inj.installed():
        snap = ing.apply(delta)
    assert inj.fired("delta_repair") == 1
    fb = [r for r in sink.records if r["phase"] == "repair_fallback"]
    assert len(fb) == 1 and "sampled exact check failed" in fb[0]["reason"]
    rec = [r for r in sink.records if r["phase"] == "delta_apply"][-1]
    assert rec["method"] == "full_recompute"
    src2, dst2, v2, _ = splice_edges(src, dst, v, delta)
    cold_l, cold_c, _ = cold_recompute(build_graph(src2, dst2, num_vertices=v2))
    np.testing.assert_array_equal(snap["labels"], cold_l)
    np.testing.assert_array_equal(snap["cc_labels"], cold_c)
    assert validate_records(sink.records) == []


def test_delta_chain_versions_and_lof(tmp_path):
    """Consecutive deltas chain parent ids, keep LOF scores finite for
    every vertex, and the streaming scorer reuses its state across
    batches instead of retraining."""
    src, dst, v = _community_graph()
    sink = _sink()
    store, *_ = _publish_base(tmp_path, src, dst, v, sink=sink)
    ing = DeltaIngestor(store, sink=sink, lof_k=4, check_samples=8)
    s1 = ing.apply(EdgeDelta.from_pairs(insert=[(40, 12), (40, 13)]))
    s2 = ing.apply(EdgeDelta.from_pairs(delete=[(0, 1)]))
    assert (s1.version, s2.version) == (2, 3)
    assert s2.parent == s1.snapshot_id
    assert np.isfinite(s2["lof"]).all() and len(s2["lof"]) == 41
    applies = [r for r in sink.records if r["phase"] == "delta_apply"]
    assert [r["method"] for r in applies] == ["warm", "warm"]
    # span-joined: every serving record carries full trace identity
    for r in applies:
        assert {"run_id", "trace_id", "span_id", "span_path"} <= set(r)


def test_published_snapshot_arrays_immutable_under_later_deltas(tmp_path):
    """Double-buffer contract: a QueryEngine built on a published
    snapshot must never observe a later delta mutating its arrays — the
    LOF splice used to write through the publish-time alias on
    no-growth deltas (torn reads on the live engine)."""
    src, dst, v = _community_graph()
    store, *_ = _publish_base(tmp_path, src, dst, v)
    ing = DeltaIngestor(store, lof_k=4, check_samples=8)
    s1 = ing.apply(EdgeDelta.from_pairs(insert=[(40, 12), (40, 13), (40, 14)]))
    eng = QueryEngine(s1, device=False)
    lof_before = eng.lof.copy()
    labels_before = eng.labels.copy()
    # no vertex growth: the repaired LOF column is spliced, not rebuilt
    ing.apply(EdgeDelta.from_pairs(delete=[(40, 14)]))
    np.testing.assert_array_equal(eng.lof, lof_before)
    np.testing.assert_array_equal(eng.labels, labels_before)
    assert not np.shares_memory(ing.lof, s1["lof"])


def test_tiny_graph_delta_skips_lof_refresh(tmp_path):
    """A <=2-vertex graph cannot be LOF-scored (k would be < 1): the
    apply must keep the existing scores and publish, never crash the
    batch — and the scorer bootstraps normally once the graph grows."""
    src = np.asarray([0], np.int32)
    dst = np.asarray([1], np.int32)
    store, *_ = _publish_base(tmp_path, src, dst, 2)
    ing = DeltaIngestor(store, lof_k=4, check_samples=4)
    snap = ing.apply(EdgeDelta.from_pairs(delete=[(0, 1)]))
    assert snap.version == 2 and len(snap["lof"]) == 2
    assert np.isfinite(snap["lof"]).all()
    snap = ing.apply(EdgeDelta.from_pairs(insert=[(0, 1), (1, 2), (2, 3)]))
    assert len(snap["lof"]) == 4 and np.isfinite(snap["lof"]).all()


def test_delta_check_samples_vary_across_applies(tmp_path, monkeypatch):
    """The random half of the sampled exact check must rotate across
    applies (seeded from the snapshot version) — a fixed seed would
    re-probe the identical vertex set on every delta, gutting the
    tripwire's long-run coverage outside the frontier."""
    import graphmine_tpu.serve.delta as delta_mod

    seen = []
    real = delta_mod.sampled_exact_check

    def spy(graph, labels, samples, kind="lpa", shards=None):
        if kind == "lpa":
            seen.append(np.asarray(samples).copy())
        return real(graph, labels, samples, kind=kind, shards=shards)

    monkeypatch.setattr(delta_mod, "sampled_exact_check", spy)
    src, dst, v = _community_graph()
    store, *_ = _publish_base(tmp_path, src, dst, v)
    ing = DeltaIngestor(store, lof_k=4, check_samples=16)
    # identical affected set {0, 1} both times: any sample difference is
    # the rotating random half, not the frontier
    ing.apply(EdgeDelta.from_pairs(insert=[(0, 1)]))
    ing.apply(EdgeDelta.from_pairs(delete=[(0, 1)]))
    assert len(seen) == 2
    assert not np.array_equal(seen[0], seen[1])


def _publish_weighted(tmp_path, intra=2.0):
    """A weighted community graph snapshot: heavy intra-clique edges +
    weak bridges between cliques. Weighted LPA's weight-sum mode keeps
    the cliques despite the bridges (the case unweighted repair would
    get wrong — bridges count as full votes unweighted), and the
    fixpoint is reachable from any init, which makes warm-vs-cold
    equality decidable."""
    src, dst, v = _community_graph(extra=[(0, 12), (12, 26)])
    w = np.full(len(src), intra, np.float32)
    w[-2:] = 0.25  # the bridges
    g = build_graph(src, dst, num_vertices=v, edge_weights=w)
    labels, cc, _ = cold_recompute(g)
    store = SnapshotStore(str(tmp_path / "snap"))
    store.publish(
        {
            "src": src, "dst": dst, "weights": w, "labels": labels,
            "cc_labels": cc, "lof": np.zeros(v, np.float32),
        },
        fingerprint=graph_fingerprint(src, dst, w),
    )
    return store, src, dst, w, v


@pytest.mark.parametrize(
    "insert,delete",
    [
        ([(40, 12, 2.0), (40, 13, 2.0), (40, 14, 2.0)], []),
        ([], [(0, 1), (0, 2), (26, 27)]),
        ([(40, 26, 2.0), (40, 27, 2.0), (40, 28, 2.0)], [(12, 13), (12, 14)]),
    ],
    ids=["insert_only", "delete_only", "mixed"],
)
def test_weighted_delta_repair_matches_cold_weighted(tmp_path, insert, delete):
    """Weighted snapshots ingest deltas end-to-end (ISSUE 8): the spliced
    weights thread through warm repair and the sampled exact check via
    the weighted-LPA supersteps, and the published labels equal a cold
    WEIGHTED recompute of the spliced graph — the parity pin that says
    weighted delta semantics are the batch pipeline's, not an unweighted
    approximation."""
    from graphmine_tpu.serve.delta import splice_edges as _splice

    store, src, dst, w, v = _publish_weighted(tmp_path)
    sink = _sink()
    ing = DeltaIngestor(store, sink=sink, lof_k=4, check_samples=16)
    delta = EdgeDelta.from_pairs(insert=insert, delete=delete)
    snap = ing.apply(delta)
    rec = [r for r in sink.records if r["phase"] == "delta_apply"][-1]
    assert rec["method"] == "warm", rec
    clean, _ = validate_delta(delta, v)
    s2, d2, w2, v2, _ = _splice(src, dst, v, clean, weights=w)
    cold_l, cold_c, _ = cold_recompute(
        build_graph(s2, d2, num_vertices=v2, edge_weights=w2)
    )
    np.testing.assert_array_equal(snap["labels"], cold_l)
    np.testing.assert_array_equal(snap["cc_labels"], cold_c)
    np.testing.assert_array_equal(snap["weights"], w2)
    assert validate_records(sink.records) == []


def test_weighted_delta_default_weight_and_chaining(tmp_path):
    """Weightless insert rows against a weighted snapshot default to
    weight 1.0, and consecutive weighted deltas chain (the spliced
    weights array stays edge-aligned across applies)."""
    store, src, dst, w, v = _publish_weighted(tmp_path)
    ing = DeltaIngestor(store, lof_k=4, check_samples=16)
    ing.apply(EdgeDelta.from_pairs(insert=[(40, 12), (40, 13)]))
    assert ing.weights is not None and len(ing.weights) == len(ing.src)
    assert ing.weights[-1] == 1.0  # the defaulted insert
    snap = ing.apply(EdgeDelta.from_pairs(delete=[(40, 12)]))
    assert len(snap["weights"]) == len(snap["src"]) == len(src) + 1
    # loads refuse under the wrong (unweighted) fingerprint: weighted
    # and unweighted dynamics must never share a snapshot identity
    with pytest.raises(FingerprintMismatch):
        store.load(fingerprint=graph_fingerprint(snap["src"], snap["dst"]))


def test_weighted_delta_refusals():
    """The loud refusals that REMAIN after weighted ingest landed —
    genuinely unsupported shapes only: a weighted delta against an
    unweighted snapshot (silently dropping client weights would change
    semantics), misaligned weights arrays, malformed wire weights."""
    from graphmine_tpu.serve.delta import splice_edges as _splice

    src, dst, v = _community_graph()
    weighted_delta = EdgeDelta.from_pairs(insert=[(1, 2, 3.5)])
    with pytest.raises(ValueError, match="unweighted"):
        _splice(src, dst, v, weighted_delta)
    with pytest.raises(ValueError, match="entries for"):
        _splice(src, dst, v, weighted_delta,
                weights=np.ones(3, np.float32))
    with pytest.raises(ValueError, match="uniformly"):
        EdgeDelta.from_pairs(insert=[(1, 2, 3.5), (1, 2)])
    with pytest.raises(ValueError, match="non-negative"):
        EdgeDelta.from_pairs(insert=[(1, 2, -1.0)])
    with pytest.raises(ValueError, match="non-negative"):
        EdgeDelta.from_pairs(insert=[(1, 2, float("nan"))])


def test_weighted_snapshot_misaligned_weights_refused(tmp_path):
    """A weights column that doesn't align with the edge arrays is a
    damaged/incompatible store — the ingestor refuses loudly instead of
    repairing with garbage."""
    src, dst, v = _community_graph()
    g = build_graph(src, dst, num_vertices=v)
    labels, cc, _ = cold_recompute(g)
    store = SnapshotStore(str(tmp_path / "snap"))
    store.publish(
        {
            "src": src, "dst": dst, "labels": labels, "cc_labels": cc,
            "weights": np.ones(len(src) - 3, np.float32),
        },
        fingerprint=graph_fingerprint(src, dst),
    )
    with pytest.raises(ValueError, match="damaged"):
        DeltaIngestor(store)


def test_reload_rebases_ingestor_on_external_publish(tmp_path):
    """An externally published snapshot + /reload must rebase the
    server's delta path: a delta applied after the reload builds on the
    external snapshot's edges, not the server's stale pre-reload state."""
    from graphmine_tpu.serve.server import SnapshotServer

    src, dst, v = _community_graph()
    sink = _sink()
    store, *_ = _publish_base(tmp_path, src, dst, v, sink=sink)
    server = SnapshotServer(store, sink=sink)
    host, port = server.start()
    try:
        # server-side delta #1 creates the (soon stale) ingestor @ v2
        _post(host, port, "/delta", {"insert": [[40, 12], [40, 13]]})
        # an EXTERNAL process publishes v3 with one more edge
        ext = DeltaIngestor(store, sink=_sink(), lof_k=4, check_samples=8)
        ext.apply(EdgeDelta.from_pairs(insert=[(41, 0), (41, 1)]))
        out = _post(host, port, "/reload", {})
        assert out == {"version": 3, "swapped": True}
        # a post-reload delta must build on v3's edges (vertex 41 kept)
        out = _post(host, port, "/delta", {"insert": [[41, 2]]})
        assert out["version"] == 4
        assert _get(host, port, "/vertex?v=41")["label"] == 0
        nbrs = _get(host, port, "/neighbors?v=41")["neighbors"]
        assert sorted(set(nbrs)) == [0, 1, 2]
    finally:
        server.stop()
    assert validate_records(sink.records) == []


# ---- sharded repair entry -------------------------------------------------


def test_sharded_lpa_fixpoint_matches_single_device():
    import jax.numpy as jnp

    from graphmine_tpu.parallel.mesh import make_mesh
    from graphmine_tpu.parallel.sharded import (
        partition_graph,
        shard_graph_arrays,
        sharded_lpa_fixpoint,
    )
    from graphmine_tpu.serve.delta import _warm_lpa

    src, dst, v = _community_graph(extra=[(0, 12), (5, 30)])
    g = build_graph(src, dst, num_vertices=v)
    init = np.arange(v, dtype=np.int32)
    init[:12] = 0  # a warm (partially-converged) seed, not identity
    mesh = make_mesh(8)
    sg = shard_graph_arrays(partition_graph(g, mesh=mesh), mesh)
    lbl_s, it_s, conv_s = sharded_lpa_fixpoint(
        sg, mesh, max_iter=64, init_labels=jnp.asarray(init)
    )
    lbl_1, it_1, conv_1 = _warm_lpa(g, init, 64)
    assert conv_s and conv_1 and it_s == it_1
    np.testing.assert_array_equal(np.asarray(lbl_s), lbl_1)


def test_sharded_lpa_fixpoint_budget_exhaustion():
    """converged=False when the budget ends before quiescence — the
    signal the serving layer's full-recompute fallback keys off."""
    import jax.numpy as jnp

    from graphmine_tpu.parallel.mesh import make_mesh
    from graphmine_tpu.parallel.sharded import (
        partition_graph,
        shard_graph_arrays,
        sharded_lpa_fixpoint,
    )

    src, dst, v = _community_graph()
    g = build_graph(src, dst, num_vertices=v)
    mesh = make_mesh(8)
    sg = shard_graph_arrays(partition_graph(g, mesh=mesh), mesh)
    _, it, conv = sharded_lpa_fixpoint(
        sg, mesh, max_iter=1,
        init_labels=jnp.asarray(np.arange(v, dtype=np.int32)),
    )
    assert it == 1 and not conv


def test_sharded_ingestor_repair_matches_cold(tmp_path):
    """DeltaIngestor(num_shards=8) routes repair through the sharded
    entries (virtual mesh) — published labels identical to the cold
    recompute, same as the single-device path."""
    src, dst, v = _community_graph()
    sink = _sink()
    store, *_ = _publish_base(tmp_path, src, dst, v, sink=sink)
    ing = DeltaIngestor(
        store, sink=sink, lof_k=4, check_samples=16, num_shards=8
    )
    delta = EdgeDelta.from_pairs(
        insert=[(40, 12), (40, 13), (40, 14)], delete=[(0, 1)]
    )
    snap = ing.apply(delta)
    rec = [r for r in sink.records if r["phase"] == "delta_apply"][-1]
    assert rec["method"] == "warm"
    clean, _ = validate_delta(delta, v)
    src2, dst2, v2, _ = splice_edges(src, dst, v, clean)
    cold_l, cold_c, _ = cold_recompute(build_graph(src2, dst2, num_vertices=v2))
    np.testing.assert_array_equal(snap["labels"], cold_l)
    np.testing.assert_array_equal(snap["cc_labels"], cold_c)
    # a second, shape-changing delta (V grows past the pad boundary)
    # exercises the shard jit-cache eviction path and must still repair
    delta2 = EdgeDelta.from_pairs(
        insert=[(i, 26) for i in range(41, 50)]
    )
    snap2 = ing.apply(delta2)
    clean2, _ = validate_delta(delta2, v2)
    src3, dst3, v3, _ = splice_edges(src2, dst2, v2, clean2)
    cold_l3, cold_c3, _ = cold_recompute(
        build_graph(src3, dst3, num_vertices=v3)
    )
    np.testing.assert_array_equal(snap2["labels"], cold_l3)
    np.testing.assert_array_equal(snap2["cc_labels"], cold_c3)


@pytest.mark.faults
def test_sharded_fallback_routes_through_sharded_entries(tmp_path):
    """Corrupted sharded repair must fall back through the SHARDED
    check/recompute entries (the single-device funnel would OOM exactly
    the working sets that needed sharding) and still republish labels
    identical to the exact cold recompute."""
    src, dst, v = _community_graph()
    sink = _sink()
    store, *_ = _publish_base(tmp_path, src, dst, v, sink=sink)
    ing = DeltaIngestor(
        store, sink=sink, lof_k=4, check_samples=16, num_shards=8
    )
    delta = EdgeDelta.from_pairs(insert=[(40, 12), (40, 13)])
    inj = faults.FaultInjector()
    inj.add("delta_repair", faults.poison_labels(shard=0, num_shards=8))
    with inj.installed():
        snap = ing.apply(delta)
    assert inj.fired("delta_repair") == 1
    rec = [r for r in sink.records if r["phase"] == "delta_apply"][-1]
    assert rec["method"] == "full_recompute"
    clean, _ = validate_delta(delta, v)
    src2, dst2, v2, _ = splice_edges(src, dst, v, clean)
    cold_l, cold_c, _ = cold_recompute(build_graph(src2, dst2, num_vertices=v2))
    np.testing.assert_array_equal(snap["labels"], cold_l)
    np.testing.assert_array_equal(snap["cc_labels"], cold_c)


def test_sampled_exact_check_sharded_parity():
    """The sharded one-superstep check must agree with the single-device
    twin: a genuine fixpoint passes, a corrupted one fails."""
    from graphmine_tpu.parallel.mesh import make_mesh
    from graphmine_tpu.parallel.sharded import (
        partition_graph,
        shard_graph_arrays,
    )
    from graphmine_tpu.serve.delta import sampled_exact_check

    src, dst, v = _community_graph()
    g = build_graph(src, dst, num_vertices=v)
    labels, cc, _ = cold_recompute(g)
    mesh = make_mesh(8)
    shards = (shard_graph_arrays(partition_graph(g, mesh=mesh), mesh), mesh)
    samples = np.arange(v)
    for kind, fix in (("lpa", labels), ("cc", cc)):
        ok_s, _ = sampled_exact_check(g, fix, samples, kind=kind, shards=shards)
        ok_1, _ = sampled_exact_check(g, fix, samples, kind=kind)
        assert ok_s and ok_1
        bad = fix.copy()
        bad[5] = (int(bad[5]) + 1) % v  # in-range but wrong
        ok_s, _ = sampled_exact_check(g, bad, samples, kind=kind, shards=shards)
        ok_1, _ = sampled_exact_check(g, bad, samples, kind=kind)
        assert not ok_s and not ok_1


def test_sharded_cold_recompute_livelock_parity():
    """Period-2 LPA livelock (complete bipartite): the sharded cold
    recompute must land on the same cycle-stopped labels as the
    single-device oracle, not a budget-parity-dependent cycle phase."""
    from graphmine_tpu.parallel.mesh import make_mesh
    from graphmine_tpu.parallel.sharded import (
        partition_graph,
        shard_graph_arrays,
    )
    from graphmine_tpu.serve.delta import _warm_lpa

    a, b = np.arange(0, 3), np.arange(3, 6)
    s, d = np.meshgrid(a, b)
    src = s.ravel().astype(np.int32)
    dst = d.ravel().astype(np.int32)
    g = build_graph(src, dst, num_vertices=6)
    _, _, conv = _warm_lpa(g, np.arange(6, dtype=np.int32), 64)
    assert not conv, "fixture must genuinely livelock"
    mesh = make_mesh(2)
    shards = (shard_graph_arrays(partition_graph(g, mesh=mesh), mesh), mesh)
    l1, c1, _ = cold_recompute(g)
    ls, cs, _ = cold_recompute(g, shards=shards)
    np.testing.assert_array_equal(ls, l1)
    np.testing.assert_array_equal(cs, c1)


def test_streaming_lof_seeded_centers_skip_training():
    from graphmine_tpu.ops.ann import default_n_clusters, kmeans
    from graphmine_tpu.ops.streaming_lof import StreamingLOF

    rng = np.random.default_rng(0)
    capacity, f = 256, 4
    pts = rng.normal(size=(capacity, f)).astype(np.float32)
    centers = np.asarray(kmeans(pts, default_n_clusters(capacity), seed=0))
    s = StreamingLOF(k=8, capacity=capacity, impl="ivf", centers=centers)
    s.update(pts)  # full window: the IVF path runs immediately
    s.update(rng.normal(size=(32, f)).astype(np.float32))
    assert s.ivf_retrains == 0, "seeded centers must not retrain Lloyd"
    assert s._ivf_fits >= 1


# ---- query engine ---------------------------------------------------------


def test_query_engine_single_and_batched_agree(tmp_path):
    src, dst, v = _community_graph()
    store, g, labels, cc = _publish_base(tmp_path, src, dst, v)
    eng = QueryEngine(store.load())
    ids = np.asarray([0, 13, 27, 39, 5])
    batch = eng.query_batch(ids)
    for i, vtx in enumerate(ids):
        assert batch["label"][i] == eng.membership(vtx) == labels[vtx]
        assert batch["component"][i] == eng.component(vtx) == cc[vtx]
        assert batch["lof"][i] == pytest.approx(eng.score(vtx))
        assert batch["community_size"][i] == eng.community_size(vtx)
    # every batch length resolves correctly through the padded device
    # gather (ids are bucketed to powers of two; results must be exact
    # prefixes, never padding rows)
    for n in (1, 2, 3, 4, 5):
        part = eng.query_batch(ids[:n])
        np.testing.assert_array_equal(part["label"], batch["label"][:n])
        np.testing.assert_array_equal(part["lof"], batch["lof"][:n])
        assert len(part["component"]) == n
    # neighbors: one CSR row == the graph's message neighborhood
    nbrs = eng.neighbors(0)
    assert sorted(set(nbrs.tolist())) == list(range(1, 12))
    # top-k: descending LOF, members of the right community only
    community = eng.membership(26)
    top = eng.top_outliers(community, 5)
    scores = [s for _, s in top]
    assert scores == sorted(scores, reverse=True)
    assert all(labels[vtx] == community for vtx, _ in top)
    # the highest-LOF member of that community heads the list
    members = np.flatnonzero(labels == community)
    want = members[np.argmax(eng.lof[members])]
    assert top[0][0] == want
    # deciles are ranks in [0, 9]
    assert 0 <= eng.community_decile(0) <= 9
    with pytest.raises(KeyError):
        eng.membership(v + 7)
    with pytest.raises(KeyError):
        eng.query_batch([0, v + 7])
    # wire hygiene matches the delta path: integral floats ok,
    # fractional ids never silently truncate to the wrong vertex
    assert eng.query_batch([3.0])["label"][0] == labels[3]
    with pytest.raises(ValueError, match="integers"):
        eng.query_batch([1.5])
    with pytest.raises(KeyError):
        eng.top_outliers(10**6, 3)


# ---- HTTP front end -------------------------------------------------------


def _get(host, port, path):
    with urllib.request.urlopen(f"http://{host}:{port}{path}", timeout=30) as r:
        return json.loads(r.read())


def _post(host, port, path, payload):
    req = urllib.request.Request(
        f"http://{host}:{port}{path}", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=120) as r:
        return json.loads(r.read())


def test_server_swap_under_live_queries(tmp_path):
    """The double-buffer acceptance pin: queries hammer the server from
    several threads while a delta publishes; zero dropped/failed queries,
    every response is internally one version, and the swap is observed."""
    from graphmine_tpu.serve.server import SnapshotServer

    src, dst, v = _community_graph()
    sink = _sink()
    store, *_ = _publish_base(tmp_path, src, dst, v, sink=sink)
    server = SnapshotServer(store, sink=sink)
    host, port = server.start()
    try:
        assert _get(host, port, "/healthz")["version"] == 1
        errors, versions = [], set()
        stop = threading.Event()

        def hammer():
            while not stop.is_set():
                try:
                    out = _post(host, port, "/query", {"vertices": [0, 13, 27]})
                    versions.add(out["version"])
                    if len(out["label"]) != 3:
                        raise AssertionError(f"short response: {out}")
                except Exception as e:  # noqa: BLE001 — collect, assert later
                    errors.append(e)

        threads = [threading.Thread(target=hammer) for _ in range(4)]
        for t in threads:
            t.start()
        out = _post(
            host, port, "/delta",
            {"insert": [[40, 12], [40, 13], [40, 14]], "delete": [[0, 1]]},
        )
        assert out["version"] == 2 and out["num_vertices"] == 41
        # post-swap queries resolve against the new snapshot
        assert _get(host, port, "/healthz")["version"] == 2
        assert _get(host, port, "/vertex?v=40")["label"] == 12
        stop.set()
        for t in threads:
            t.join(timeout=30)
        assert errors == []
        assert versions <= {1, 2} and versions  # no torn/mixed versions
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(host, port, "/vertex?v=999999")
        assert e.value.code == 400
        top = _get(host, port, "/topk?community=12&k=3")
        assert len(top["top"]) == 3
    finally:
        server.stop()
    assert validate_records(sink.records) == []


def test_server_rejects_null_fields_with_400(tmp_path):
    """Malformed-but-parseable JSON (null where a list belongs) must get
    a 400 JSON error, never a killed connection — the serving layer's
    never-crash-on-bad-input contract — and the server keeps serving."""
    from graphmine_tpu.serve.server import SnapshotServer

    src, dst, v = _community_graph()
    store, *_ = _publish_base(tmp_path, src, dst, v)
    server = SnapshotServer(store)
    host, port = server.start()
    try:
        for path, payload in (
            ("/query", {"vertices": None}),
            ("/query", {"vertices": [1, None]}),
            ("/query", {"vertices": [1.5]}),
            ("/delta", {"insert": [[1, 2]], "delete": None}),
            ("/delta", {"insert": [[1.9, 2.7]]}),
        ):
            with pytest.raises(urllib.error.HTTPError) as e:
                _post(host, port, path, payload)
            assert e.value.code == 400
        # still alive and consistent afterwards
        assert _get(host, port, "/healthz")["version"] == 1
    finally:
        server.stop()


# ---- driver / obs integration ---------------------------------------------


def _write_edgelist(tmp_path, src, dst):
    p = tmp_path / "edges.txt"
    p.write_text("".join(f"n{s} n{d}\n" for s, d in zip(src, dst)))
    return str(p)


def test_driver_publishes_snapshot_and_serves(tmp_path):
    """--snapshot-out end to end: run_pipeline publishes as its final
    phase; the snapshot loads, fingerprints match the run's edge arrays,
    and a DeltaIngestor can repair on top of it."""
    from graphmine_tpu.pipeline.config import PipelineConfig
    from graphmine_tpu.pipeline.driver import run_pipeline

    src, dst, v = _community_graph()
    cfg = PipelineConfig(
        data_path=_write_edgelist(tmp_path, src, dst),
        data_format="edgelist",
        outlier_method="lof",
        lof_k=8,
        num_devices=1,
        snapshot_out=str(tmp_path / "snap"),
    )
    res = run_pipeline(cfg)
    pub = [r for r in res.metrics.records if r["phase"] == "snapshot_publish"]
    assert len(pub) == 1 and pub[0]["version"] == 1
    assert {"run_id", "trace_id", "span_id", "span_path"} <= set(pub[0])
    store = SnapshotStore(str(tmp_path / "snap"))
    snap = store.load(
        fingerprint=graph_fingerprint(res.edge_table.src, res.edge_table.dst)
    )
    np.testing.assert_array_equal(snap["labels"], res.labels)
    assert {"src", "dst", "labels", "cc_labels", "lof", "census_present",
            "census_sizes", "census_edges"} <= set(snap.arrays)
    assert snap.meta["run_id"] == res.metrics.tracer.run_id
    # and the store is delta-ready (labels here are maxIter-bounded, so
    # the repair may legitimately re-fixpoint or fall back — either way
    # the published labels must be a verified fixpoint)
    ing = DeltaIngestor(store, sink=res.metrics, lof_k=4, check_samples=8)
    snap2 = ing.apply(EdgeDelta.from_pairs(insert=[(3, 17)]))
    assert snap2.version == 2
    assert validate_records(res.metrics.records) == []


def test_obs_report_renders_serving_section(tmp_path):
    """query_batch / delta_apply / snapshot_publish all surface in the
    obs_report output (the acceptance render pin)."""
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
    from graphmine_tpu.serve.server import SnapshotServer

    src, dst, v = _community_graph()
    stream = tmp_path / "metrics.jsonl"
    sink = MetricsSink(stream_path=str(stream), tracer=Tracer())
    sink.emit("run_start", pid=os.getpid())
    store, *_ = _publish_base(tmp_path, src, dst, v, sink=sink)
    server = SnapshotServer(store, sink=sink)
    host, port = server.start()
    try:
        _post(host, port, "/query", {"vertices": [0, 1, 2]})
        _post(host, port, "/delta", {"insert": [[40, 12], [40, 13]]})
    finally:
        server.stop()
    sink.emit("run_end", ok=True)
    sink.finalize(str(stream))
    import obs_report

    records, bad = obs_report.load_records(str(stream))
    assert bad == 0
    report = obs_report.build_report(records)
    assert "-- serving (snapshots / deltas / queries) --" in report
    assert "snapshot_publish" in report and "delta_apply" in report
    assert "queries[query]" in report
    assert validate_records(records) == []


def test_serve_cli_query_and_delta(tmp_path):
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
    import serve_cli

    src, dst, v = _community_graph()
    store, *_ = _publish_base(tmp_path, src, dst, v)
    root = store.root
    rc = serve_cli.main(["info", "--store", root])
    assert rc == 0
    rc = serve_cli.main([
        "query", "--store", root, "--vertex", "0", "13",
        "--community", "0", "--topk", "3",
    ])
    assert rc == 0
    rc = serve_cli.main([
        "delta", "--store", root, "--insert", "40,12", "--insert", "40,13",
        "--delete", "0,1",
    ])
    assert rc == 0
    assert SnapshotStore(root).load().version == 2
