"""IVF-flat approximate kNN (r5): contract, recall, determinism, and the
size-capped sublist machinery.

Exactness is NOT the contract — recall is. The bounds here are 3x-slack
versions of measured values (gaussian 0.977, blobs 0.9999 at the default
knobs) so a structural regression (broken inversion, leaked junk rows,
wrong merge mapping) fails loudly while backend float jitter does not.
"""

import numpy as np
import pytest

from graphmine_tpu.ops.ann import ivf_knn, kmeans
from graphmine_tpu.ops.knn import knn

pytestmark = pytest.mark.ann  # the --ann-only tier-1 lane


@pytest.fixture(scope="module")
def clouds():
    rng = np.random.default_rng(1)
    n, f = 20000, 8
    gauss = rng.normal(size=(n, f)).astype(np.float32)
    blob_c = rng.normal(size=(8, f)).astype(np.float32) * 3
    blobs = (
        blob_c[rng.integers(0, 8, n)]
        + rng.normal(size=(n, f)).astype(np.float32)
    )
    return {"gauss": gauss, "blobs": blobs}


def _recall(exact_idx, got_idx, k):
    return np.mean([
        len(set(exact_idx[i]) & set(got_idx[i])) / k
        for i in range(len(exact_idx))
    ])


@pytest.mark.parametrize("cloud", ["gauss", "blobs"])
def test_ivf_contract_and_recall(clouds, cloud):
    pts = clouds[cloud]
    n, k = pts.shape[0], 32
    exact_i = np.asarray(knn(pts, k=k, impl="xla")[1])
    d2, gid = ivf_knn(pts, k=k, n_probe=16)
    d2, gid = np.asarray(d2), np.asarray(gid)
    # contract: ascending distances, self excluded, real ids only (a
    # leaked merge-padding junk row would surface as -1)
    assert (np.diff(d2, axis=1) >= -1e-6).all()
    assert (gid != np.arange(n)[:, None]).all()
    assert ((gid >= 0) & (gid < n)).all()
    # returned distances are EXACT for the returned candidates
    for i in range(0, n, 997):
        dd = ((pts[i] - pts[gid[i]]) ** 2).sum(-1)
        np.testing.assert_allclose(dd, d2[i], rtol=1e-4, atol=1e-4)
    # recall: measured 0.977 (gauss — the worst case for IVF) and 0.9999
    # (blobs); assert with slack
    rec = _recall(exact_i, gid, k)
    assert rec > (0.9 if cloud == "gauss" else 0.99), rec
    # determinism: same seed, same index
    _, gid2 = ivf_knn(pts, k=k, n_probe=16)
    np.testing.assert_array_equal(gid, np.asarray(gid2))


def test_ivf_sublist_capping_on_skewed_clusters():
    """Moderate skew (one cluster a few multiples of l_cap): the capped
    sublists (the fix for the 262K first-run blowup) stay on the FAST
    path and must return correct, junk-free results with high recall."""
    rng = np.random.default_rng(3)
    n, f, k = 12000, 8, 16
    # ~40% of mass in one tight blob: its k-means cluster splits into a
    # handful of sublists (> 1, below the 4x-probe skew fallback)
    tight = rng.normal(size=(int(n * 0.4), f)).astype(np.float32) * 0.1
    rest = rng.normal(size=(n - tight.shape[0], f)).astype(np.float32) * 5
    pts = np.concatenate([tight, rest]).astype(np.float32)
    exact_i = np.asarray(knn(pts, k=k, impl="xla")[1])
    d2, gid = ivf_knn(pts, k=k, n_clusters=16, n_probe=8)
    d2, gid = np.asarray(d2), np.asarray(gid)
    assert ((gid >= 0) & (gid < n)).all() and (gid != np.arange(n)[:, None]).all()
    assert (np.diff(d2, axis=1) >= -1e-6).all()
    assert _recall(exact_i, gid, k) > 0.9


def test_ivf_pathological_skew_falls_back_to_exact():
    """A cloud k-means cannot structure must take the exact path — the
    approximate machinery would otherwise blow up its pair tables
    (code-review r5) or leak inf rows into LOF, which zeroes EVERY score
    through the duplicate-floor eps. The natural trigger is DUPLICATE
    rows (discrete graph features are full of them): every duplicate
    ties its center assignment to the same argmin winner, so one cluster
    absorbs them all and its sublist expansion blows past the 4x-probe
    skew bound. (A merely *dense* blob does NOT trigger this — sampled
    k-means init drops ~90% of centers inside it and splits it fine,
    which the moderate-skew test above exercises.)"""
    rng = np.random.default_rng(4)
    n, f, k = 8000, 8, 16
    dup = np.tile(rng.normal(size=(1, f)).astype(np.float32), (int(n * 0.9), 1))
    rest = rng.normal(size=(n - dup.shape[0], f)).astype(np.float32) * 8
    pts = np.concatenate([dup, rest]).astype(np.float32)
    want_d, want_i = knn(pts, k=k, impl="xla")
    d2, gid = ivf_knn(pts, k=k, n_clusters=64, n_probe=8)
    # exact fallback -> identical result, and in particular no inf/-1
    np.testing.assert_array_equal(np.asarray(gid), np.asarray(want_i))
    np.testing.assert_allclose(
        np.asarray(d2), np.asarray(want_d), rtol=1e-5, atol=1e-5
    )


def test_ivf_small_cloud_falls_back_to_exact():
    rng = np.random.default_rng(0)
    pts = rng.normal(size=(200, 8)).astype(np.float32)
    want = np.asarray(knn(pts, k=8, impl="xla")[1])
    got = np.asarray(ivf_knn(pts, k=8)[1])
    np.testing.assert_array_equal(got, want)


def test_ivf_rejects_bad_k():
    rng = np.random.default_rng(0)
    pts = rng.normal(size=(100, 4)).astype(np.float32)
    with pytest.raises(ValueError):
        ivf_knn(pts, k=0)
    with pytest.raises(ValueError):
        ivf_knn(pts, k=100)


def test_kmeans_deterministic_and_shaped():
    rng = np.random.default_rng(2)
    pts = rng.normal(size=(5000, 8)).astype(np.float32)
    c1 = np.asarray(kmeans(pts, 32, iters=3, seed=5))
    c2 = np.asarray(kmeans(pts, 32, iters=3, seed=5))
    np.testing.assert_array_equal(c1, c2)
    assert c1.shape == (32, 8)
    assert not np.array_equal(c1, np.asarray(kmeans(pts, 32, iters=3, seed=6)))
    with pytest.raises(ValueError):
        kmeans(pts[:10], 32)


def test_lof_ivf_tracks_exact(clouds):
    """lof_scores(impl='ivf') stays close to the exact scorer — the
    on-silicon harness measured AUROC 0.9895 vs 0.9905; here the scores
    themselves must correlate tightly on both cloud shapes."""
    from graphmine_tpu.ops.lof import lof_scores

    for cloud in ("gauss", "blobs"):
        pts = clouds[cloud][:8000]
        exact = np.asarray(lof_scores(pts, k=32, impl="xla"))
        approx = np.asarray(lof_scores(pts, k=32, impl="ivf"))
        frac_close = np.mean(np.abs(exact - approx) < 0.05 * np.abs(exact) + 0.01)
        assert frac_close > 0.95, (cloud, frac_close)


def test_ivf_guard_fallback_warns_and_records():
    """ADVICE r5: a pathology guard routing ivf_knn to the exact path
    must warn and (with a sink) emit an ivf_fallback record naming the
    guard — a silent bypass once mislabeled bench timings as 'ivf'."""
    from graphmine_tpu.ops.ann import ivf_knn
    from graphmine_tpu.ops.knn import knn
    from graphmine_tpu.pipeline.metrics import MetricsSink

    rng = np.random.default_rng(0)
    pts = rng.normal(size=(64, 4)).astype(np.float32)
    m = MetricsSink()
    with pytest.warns(UserWarning, match="ivf_knn guard"):
        d2, idx = ivf_knn(pts, k=40, n_clusters=8, sink=m)
    rec = m.of_phase("ivf_fallback")
    assert rec and rec[0]["guard"] == "k_unfillable"
    assert "k=40" in rec[0]["detail"]
    # the fallback result IS the exact result
    d2x, _ = knn(pts, 40, impl="auto")
    np.testing.assert_allclose(np.asarray(d2), np.asarray(d2x), atol=1e-5)
    # lof_scores threads the sink through to the same record
    from graphmine_tpu.ops.lof import lof_scores

    m2 = MetricsSink()
    with pytest.warns(UserWarning, match="ivf_knn guard"):
        lof_scores(pts, k=40, impl="ivf", sink=m2)
    assert m2.of_phase("ivf_fallback")
