"""Memory-plane observability (ISSUE 14, marker `mem`):

- the analytical HBM footprint inventory EXACT against HAND-COMPUTED
  tiny plans (ring-4 / star-21, all three superstep families, fused +
  sharded, weighted payload doubling — the test_costmodel.py
  discipline) and both LOF impl workspaces;
- the planner byte-constant derivation: one inventory, two consumers
  (pipeline/planner.py delegates to obs/memmodel.py bit-identically);
- the `mem` sub-record: schema shape, half-stamped validation failure,
  the schema_lint inline-mem rule;
- memory_watermark emission: the builder contract, the driver e2e (every
  LPA/LOF phase emits schema-valid watermarks, obs_report renders the
  memory waterfall + a recalibration suggestion from the JSONL alone —
  THE acceptance criterion), and the fault-injected OOM e2e whose
  degrade record carries the inventory + last watermark joinable by
  span path;
- plan-time pre-degrade under a squeezed budget;
- satellites: device_hbm_bytes min-across-devices, /profilez
  device-memory capture, heartbeat device-memory cache, serve /statusz
  memory section + graphmine_memory_* gauges + the low-headroom alert
  rule, bench_diff's memory sub-record gate (bytes regress UP).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from graphmine_tpu.graph.container import build_graph
from graphmine_tpu.obs import memmodel
from graphmine_tpu.obs.schema import (
    MEM_KEYS,
    validate_record,
    validate_records,
)
from graphmine_tpu.obs.spans import Tracer
from graphmine_tpu.pipeline.metrics import MetricsSink

from conftest import cached_edgelist

pytestmark = pytest.mark.mem

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO, "tools")
if TOOLS not in sys.path:
    sys.path.insert(0, TOOLS)

import bench_diff  # noqa: E402


def ring4(weights=None):
    """Directed 4-ring; symmetric message CSR => M=8, every degree 2."""
    src = np.array([0, 1, 2, 3], np.int32)
    dst = np.array([1, 2, 3, 0], np.int32)
    return build_graph(src, dst, num_vertices=4, edge_weights=weights)


def star21(weights=None):
    """Hub of degree 21: bucketed rows 21x1 (leaves) + 1x22 (hub) = 43
    padded slots over M=42 (the test_costmodel.py fixture)."""
    src = np.zeros(21, np.int32)
    dst = np.arange(1, 22, dtype=np.int32)
    return build_graph(src, dst, num_vertices=22, edge_weights=weights)


# ---------------------------------------------------------------------------
# one inventory, two consumers: the planner derives from memmodel
# ---------------------------------------------------------------------------


def test_planner_constants_derive_from_memmodel():
    from graphmine_tpu.pipeline import planner

    assert planner._BYTES_PER_EDGE == memmodel.BYTES_PER_EDGE
    assert planner._BYTES_PER_EDGE_WEIGHTED == memmodel.BYTES_PER_EDGE_WEIGHTED
    assert planner._SINGLE_BYTES_PER_VERTEX == memmodel.SINGLE_BYTES_PER_VERTEX
    assert (planner._REPLICATED_BYTES_PER_VERTEX
            == memmodel.REPLICATED_BYTES_PER_VERTEX)
    assert planner._RING_BYTES_PER_VERTEX == memmodel.RING_BYTES_PER_VERTEX
    # bit-identical accept/reject arithmetic across the whole grid
    for sched in ("single", "replicated", "ring"):
        for w in (False, True):
            for d in (1, 4, 7):
                assert planner.estimate_bytes_per_device(
                    sched, 100_000, 2_000_000, d, w
                ) == memmodel.schedule_bytes_per_device(
                    sched, 100_000, 2_000_000, d, w
                )
    with pytest.raises(ValueError):
        memmodel.schedule_bytes_per_device("mesh2d", 10, 10, 1)


def test_schedule_inventory_decomposes_the_seeds():
    # single, unweighted: 36 B/edge + 8 B/vertex, component-exact
    inv = memmodel.schedule_inventory("single", 1000, 5000, 1)
    assert inv == {
        "edge_endpoints": 40_000,   # 8 B/edge
        "message_csr": 80_000,      # 16 B/edge
        "plan_mats": 30_000,        # 6 B/edge
        "gather_transient": 30_000, # 6 B/edge
        "labels": 8_000,            # 8 B/vertex
    }
    assert sum(inv.values()) == memmodel.schedule_bytes_per_device(
        "single", 1000, 5000, 1
    )
    # weighted adds 8+8 B/edge; replicated/ring carry their vertex terms
    invw = memmodel.schedule_inventory("single", 1000, 5000, 1, weighted=True)
    assert invw["msg_weights"] == 40_000 and invw["weight_mats"] == 40_000
    invr = memmodel.schedule_inventory("replicated", 1000, 5000, 4)
    assert invr["labels_replicated"] == 8_000
    assert invr["exchange_buffer"] == 8_000
    invg = memmodel.schedule_inventory("ring", 1000, 5000, 4)
    assert invg["labels_sharded"] == 2_000 and invg["ring_chunks"] == 4_000
    est = memmodel.schedule_footprint("single", 1000, 5000, 1)
    assert est.total_bytes == 188_000 and est.exact is False


# ---------------------------------------------------------------------------
# fused footprints: hand-computed exactness
# ---------------------------------------------------------------------------


def test_prebuild_footprints_anchor_to_the_planner_seeds():
    """Without a plan, the fused bucketed estimate IS the schedule model
    the planner accepted the run with (an admitted run can never
    spuriously pre-degrade off its own family); sort drops the
    plan-mats term; blocked adds the stream pair + tile the 36 B/edge
    seed predates."""
    bu = memmodel.superstep_footprint("lpa_superstep", "bucketed", 4, 8,
                                      num_edges=4)
    assert bu.inventory == memmodel.schedule_inventory("single", 4, 4, 1)
    assert bu.total_bytes == memmodel.schedule_bytes_per_device(
        "single", 4, 4, 1
    )
    so = memmodel.superstep_footprint("lpa_superstep", "sort", 4, 8,
                                      num_edges=4)
    assert "plan_mats" not in so.inventory
    assert so.total_bytes == bu.total_bytes - 4 * 6  # 6 B/edge plan term
    bl = memmodel.superstep_footprint("lpa_superstep", "blocked", 4, 8,
                                      num_edges=4)
    assert bl.inventory["stream"] == 2 * 4 * 8
    assert bl.inventory["tile"] == 4 * 8        # min(M, tile-slot seed)
    assert bl.total_bytes == bu.total_bytes + 64 + 32
    assert not any(e.exact for e in (bu, so, bl))
    # weighted adds the seed's 16 B/edge payload terms
    ew = memmodel.superstep_footprint("lpa_superstep", "sort", 4, 8,
                                      num_edges=4, weighted=True)
    assert ew.inventory["msg_weights"] == 4 * 8
    assert ew.inventory["weight_mats"] == 4 * 8
    with pytest.raises(ValueError):
        memmodel.superstep_footprint("x", "mesh2d", 4, 8)


def test_bucketed_footprint_exact_ring_and_star():
    from graphmine_tpu.ops.bucketed_mode import BucketedModePlan

    plan = BucketedModePlan.from_graph(ring4(), with_send=True)
    e = memmodel.superstep_footprint(
        "lpa_superstep", "bucketed", 4, 8, num_edges=4, plan=plan
    )
    # 4 vertices x width-2 rows = 8 padded slots, 4 vertex ids
    assert e.inventory["plan_mats"] == 4 * 8
    assert e.inventory["plan_vertex_ids"] == 4 * 4
    assert e.inventory["gather_transient"] == 4 * 8
    assert (e.family, e.exact) == ("bucketed", True)
    assert e.total_bytes == 32 + 84 + 32 + 32 + 16 + 32 == 228

    plan2 = BucketedModePlan.from_graph(star21(), with_send=True)
    e2 = memmodel.superstep_footprint(
        "lpa_superstep", "bucketed", 22, 42, num_edges=21, plan=plan2
    )
    # hand-computed: 21 leaves x w=1 + hub x w=22 = 43 padded slots,
    # 22 owning vertex ids; csr = 4*(2*42 + 23) = 428
    assert e2.inventory["plan_mats"] == 4 * 43
    assert e2.inventory["plan_vertex_ids"] == 4 * 22
    assert e2.inventory["message_csr"] == 428
    assert e2.total_bytes == 168 + 428 + 176 + 172 + 88 + 172

    # weighted star: slot-aligned weight mats ride the same 43 slots
    gw = star21(weights=np.ones(21, np.float32) * 2.0)
    planw = BucketedModePlan.from_graph(gw, with_send=True)
    ew = memmodel.superstep_footprint(
        "lpa_superstep", "bucketed", 22, 42, num_edges=21, plan=planw
    )
    assert ew.weighted is True
    assert ew.inventory["weight_mats"] == 4 * 43
    assert ew.inventory["msg_weights"] == 4 * 42


def test_blocked_footprint_exact_and_weighted():
    from graphmine_tpu.ops.blocking import BlockedPlan

    plan = BlockedPlan.from_graph(ring4())
    e = memmodel.superstep_footprint(
        "lpa_superstep", "blocked", 4, 8, num_edges=4, plan=plan
    )
    # stream pair 2*4*8; tile = the plan's real alloc; 8 reduce-row
    # slots + 4 owners; transient rides the rows
    assert e.inventory["stream"] == 2 * 4 * 8
    assert e.inventory["tile"] == 4 * int(plan.tile_alloc)
    assert e.inventory["reduce_rows"] == 4 * 8
    assert e.inventory["row_vertex"] == 4 * 4
    assert e.inventory["gather_transient"] == 4 * 8
    assert (e.family, e.exact) == ("blocked", True)

    gw = star21(weights=np.ones(21, np.float32) * 2.0)
    planw = BlockedPlan.from_graph(gw)
    ew = memmodel.superstep_footprint(
        "lpa_superstep", "blocked", 22, 42, num_edges=21, plan=planw
    )
    # weight mats align with the 43 padded reduce-row slots
    assert ew.inventory["reduce_rows"] == 4 * 43
    assert ew.inventory["weight_mats"] == 4 * 43
    assert ew.inventory["msg_weights"] == 4 * 42
    # the family ladder shrinks strictly: blocked > bucketed > sort
    fams = [
        memmodel.superstep_footprint(
            "lpa_superstep", f, 22, 42, num_edges=21
        ).total_bytes
        for f in ("blocked", "bucketed", "sort")
    ]
    assert fams[0] > fams[1] > fams[2]


def test_sharded_footprint_exact_all_families():
    from graphmine_tpu.parallel.sharded import partition_graph

    src = np.arange(16, dtype=np.int32)
    dst = (src + 1) % 16
    g = build_graph(src, dst, num_vertices=16, to_device=False)

    # sort shard body: [2, 16] message arrays, Vc=8, D=2
    sg = partition_graph(g, num_shards=2)
    e = memmodel.sharded_superstep_footprint("lpa_superstep", sg)
    assert (e.family, e.devices, e.exact) == ("sort", 2, True)
    assert e.inventory["shard_messages"] == 2 * 4 * 16  # recv + send
    assert e.inventory["degrees"] == 4 * 8
    assert e.inventory["labels_replicated"] == 2 * 4 * 16
    assert e.inventory["exchange_buffer"] == 2 * 4 * 8 * 2
    assert e.inventory["gather_transient"] == 4 * 16
    assert e.total_bytes == 480

    # the ring schedule drops the replicated V-term entirely
    er = memmodel.sharded_superstep_footprint(
        "lpa_superstep", sg, schedule="ring"
    )
    assert "labels_replicated" not in er.inventory
    assert er.inventory["labels_sharded"] == 2 * 4 * 8
    assert er.inventory["ring_chunks"] == 2 * 4 * 8
    assert er.inventory["exchange_staging"] == 2 * 4 * 8
    assert er.total_bytes == 480 - 256 + 192 == 416
    assert er.total_bytes < e.total_bytes

    # stacked bucket plan: [2, 8, 2] mats -> 64 B/chip + [2, 8] targets
    sgb = partition_graph(g, num_shards=2, build_bucket_plan=True)
    eb = memmodel.sharded_superstep_footprint("lpa_superstep", sgb)
    assert eb.family == "bucketed"
    assert eb.inventory["plan_mats"] == 4 * 8 * 2
    assert eb.inventory["plan_vertex_ids"] == 4 * 8
    assert eb.total_bytes == 576

    # blocked bin groups: stream pair + shard-local tile + [2, 8, 2] rows
    sgk = partition_graph(g, num_shards=2, build_blocked_plan=True)
    ek = memmodel.sharded_superstep_footprint("lpa_superstep", sgk)
    assert ek.family == "blocked"
    assert ek.inventory["stream"] == 2 * 4 * 16
    assert ek.inventory["tile"] == 4 * int(sgk.blk_tile_alloc)
    assert ek.inventory["reduce_rows"] == 4 * 8 * 2
    assert ek.total_bytes > eb.total_bytes > e.total_bytes


def test_lof_footprint_exact_and_ivf_workspace():
    e = memmodel.lof_footprint("exact", 100, 5, features=8)
    assert e.inventory == {
        "features": 4 * 100 * 8,
        "scores": 4 * 100,
        "distance_tile": 4 * 100 * 100,
        "topk_workspace": 2 * 4 * 100 * 5,
    }
    assert e.total_bytes == 47_600
    # the ring-sharded exact scorer splits the distance rows 1/D
    e2 = memmodel.lof_footprint("exact", 100, 5, features=8, devices=2)
    assert e2.inventory["distance_tile"] == 4 * 50 * 100
    assert e2.inventory["topk_workspace"] == 2 * 4 * 50 * 5

    # IVF: C = max(8, round(sqrt(64)/8)*8) = 8, batch b = 2*64/8+1 = 17
    i = memmodel.lof_footprint("ivf", 64, 5, features=8)
    assert memmodel.ivf_model_clusters(64) == 8
    b = 17
    assert i.inventory["centers"] == 4 * 8 * 8
    assert i.inventory["assignments"] == 2 * 4 * 64
    assert i.inventory["cluster_batch"] == 4 * (b * 8 + b * b + 2 * b * 5)
    # the bounded-candidate index is the exact scorer's OOM rescue rung:
    # strictly leaner at equal n
    assert (memmodel.lof_footprint("ivf", 100, 5).total_bytes
            < memmodel.lof_footprint("exact", 100, 5).total_bytes)
    with pytest.raises(ValueError):
        memmodel.lof_footprint("pallas", 100, 5)


# ---------------------------------------------------------------------------
# mem sub-record: schema + lint
# ---------------------------------------------------------------------------


def test_mem_record_shape_matches_schema_and_half_stamped_fails():
    est = memmodel.superstep_footprint("lpa_superstep", "sort", 4, 8,
                                       num_edges=4)
    assert set(est.record().keys()) == set(MEM_KEYS)
    rec = {"phase": "memory_watermark", "t": 1.0, "op": "lpa_superstep",
           "predicted_bytes": est.total_bytes, "achieved_bytes": 10,
           "headroom_frac": None, "source": "rss", "mem": est.record()}
    assert validate_record(rec) == []
    broken = dict(rec)
    broken["mem"] = {"family": "sort"}
    problems = validate_record(broken)
    assert problems and "half-stamped mem" in problems[0]
    broken["mem"] = "not-a-dict"
    assert any("not dict" in p for p in validate_record(broken))


def test_schema_lint_flags_inline_mem_literals(tmp_path):
    import schema_lint

    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "bad.py").write_text(
        'sink.emit("memory_watermark", mem={"family": "sort"})\n'
        "# a comment mentioning mem={...} must NOT trip the lint\n"
        'sink.emit("memory_watermark", mem=dict(family="sort"))\n'
        'sink.emit("memory_watermark", mem=estimate.record())\n'
        "mem = list(ladder)  # assignment, not a kwarg literal\n"
        'emit("x", memory={"a": 1})  # different kwarg\n'
    )
    hits = schema_lint.scan_inline_mems(str(pkg))
    assert [line for _, line in hits] == [1, 3]
    # and the real package is clean (the builder lives in memmodel.py)
    assert schema_lint.scan_inline_mems() == []


# ---------------------------------------------------------------------------
# watermark emission + pre-degrade units
# ---------------------------------------------------------------------------


def _sink():
    return MetricsSink(tracer=Tracer())


def test_emit_memory_watermark_contract():
    est = memmodel.superstep_footprint("lpa_superstep", "sort", 4, 8,
                                       num_edges=4)
    m = _sink()
    rec = memmodel.emit_memory_watermark(
        m, "lpa_superstep", est,
        {"bytes_in_use": 700, "peak_bytes_in_use": 1000,
         "bytes_limit": 4000, "source": "device"},
        budget_bytes=4000, iteration=3,
    )
    assert rec["predicted_bytes"] == est.total_bytes
    # achieved is the phase-attributable CURRENT in-use; the lifetime
    # allocator peak rides as context and drives the headroom forecast
    assert rec["achieved_bytes"] == 700
    assert rec["peak_bytes_in_use"] == 1000
    assert rec["headroom_frac"] == pytest.approx(0.75)  # (4000-1000)/4000
    assert rec["source"] == "device" and rec["iteration"] == 3
    assert validate_record(rec) == []
    # no sink / no estimate / no measurement => no record claiming one
    assert memmodel.emit_memory_watermark(None, "x", est, {"a": 1}) is None
    assert memmodel.emit_memory_watermark(m, "x", None, {"a": 1}) is None
    assert memmodel.emit_memory_watermark(m, "x", est, None) is None
    assert memmodel.emit_memory_watermark(m, "x", est, {"source": "d"}) is None
    # rss fallback exists on Linux and is schema-valid
    s = memmodel.rss_sample()
    if s is not None:
        rec2 = memmodel.emit_memory_watermark(m, "x", est, s)
        assert rec2["source"] == "rss"
    assert validate_records(m.records) == []


def test_predegrade_walks_to_fit():
    v, mcount, e = 160, 1600, 800
    bu = memmodel.superstep_footprint(
        "lpa_superstep", "bucketed", v, mcount, num_edges=e
    ).total_bytes
    so = memmodel.superstep_footprint(
        "lpa_superstep", "sort", v, mcount, num_edges=e
    ).total_bytes
    # generous budget: the requested family fits, no steps
    fam, fit, steps = memmodel.predegrade_superstep(
        "blocked", v, mcount, e, False, 1 << 30
    )
    assert (fam, steps) == ("blocked", []) and fit.family == "blocked"
    # budget between sort and bucketed: bucketed steps down exactly once
    fam, fit, steps = memmodel.predegrade_superstep(
        "bucketed", v, mcount, e, False, (bu + so) // 2
    )
    assert fam == "sort" and fit.total_bytes == so
    assert [(a, b) for a, b, _ in steps] == [("bucketed", "sort")]
    assert steps[0][2].total_bytes == bu
    # below even the sort floor: the floor is returned (there is nothing
    # leaner; the reactive ladder owns what happens next)
    fam, fit, steps = memmodel.predegrade_superstep(
        "blocked", v, mcount, e, False, 16
    )
    assert fam == "sort" and len(steps) == 2


# ---------------------------------------------------------------------------
# satellites: device_hbm_bytes min, heartbeat cache
# ---------------------------------------------------------------------------


class _FakeDev:
    def __init__(self, stats):
        self._stats = stats

    def memory_stats(self):
        if isinstance(self._stats, Exception):
            raise self._stats
        return self._stats


def test_device_hbm_bytes_takes_min_across_devices():
    from graphmine_tpu.pipeline.driver import device_hbm_bytes

    devs = [
        _FakeDev({"bytes_limit": 32 << 30}),
        _FakeDev({"bytes_limit": 16 << 30}),   # the smallest chip governs
        _FakeDev({"bytes_limit": 95 << 30}),
    ]
    assert device_hbm_bytes(devs) == 16 << 30
    # unreporting / raising devices are skipped, not fatal
    devs2 = [
        _FakeDev(None),
        _FakeDev(RuntimeError("tunneled runtime")),
        _FakeDev({"bytes_limit": 8 << 30}),
    ]
    assert device_hbm_bytes(devs2) == 8 << 30
    assert device_hbm_bytes([_FakeDev(None)]) is None
    assert device_hbm_bytes([]) is None


def test_heartbeat_carries_cached_device_memory():
    from graphmine_tpu.obs import heartbeat as hb

    sample = [{"device": 0, "bytes_in_use": 100,
               "peak_bytes_in_use": 200, "bytes_limit": 1000}]
    hb.note_device_memory(sample)
    try:
        beat = hb.Heartbeat(_sink()).beat()
        assert beat["device_memory"]["per_device"] == sample
        assert beat["device_memory"]["age_s"] >= 0
        assert validate_record(beat) == []
    finally:
        hb._DEV_MEM = None  # don't leak the cache into other tests
    # without a cache the key is absent (RSS-only, the pre-ISSUE-14 shape)
    beat2 = hb.Heartbeat(_sink()).beat()
    assert "device_memory" not in beat2


# ---------------------------------------------------------------------------
# driver e2e: the acceptance criterion
# ---------------------------------------------------------------------------

_E2E: dict = {}


def _edgelist_path() -> str:
    if "path" not in _E2E:
        rng = np.random.default_rng(7)
        v, e = 160, 800
        src = rng.integers(0, v, e)
        dst = (src + rng.integers(1, v // 2, e)) % v
        text = "".join(f"{s} {t}\n" for s, t in zip(src, dst))
        _E2E["path"] = cached_edgelist("graphmine_mem", text)
    return _E2E["path"]


def _run_driver(tmp_path, **kw):
    from graphmine_tpu.pipeline.config import PipelineConfig
    from graphmine_tpu.pipeline.driver import run_pipeline
    from graphmine_tpu.pipeline.resilience import ResilienceConfig

    base = dict(
        data_path=_edgelist_path(), data_format="edgelist",
        outlier_method="none", num_devices=1, max_iter=5,
        metrics_out=str(tmp_path / "metrics.jsonl"),
        resilience=ResilienceConfig(backoff_base_s=0.001, backoff_max_s=0.01),
    )
    base.update(kw)
    return run_pipeline(PipelineConfig(**base))


def test_driver_e2e_watermarks_and_report_renders(tmp_path):
    """Acceptance: a CPU driver run emits schema-valid memory_watermark
    records for the LPA and LOF phases, the plan record carries the full
    inventory, and obs_report renders the memory section (waterfall +
    recalibration suggestion) from the JSONL alone."""
    res = _run_driver(tmp_path, outlier_method="lof")
    recs = res.metrics.records
    assert validate_records(recs) == []
    marks = [r for r in recs if r["phase"] == "memory_watermark"]
    assert {r["op"] for r in marks} >= {"lpa_superstep", "lof_knn"}
    for r in marks:
        assert r["predicted_bytes"] > 0
        assert r["achieved_bytes"] > 0
        assert r["source"] in ("device", "rss")
        assert set(r["mem"].keys()) == set(MEM_KEYS)
        assert r["span_path"].startswith("run/")
    (plan,) = [r for r in recs if r["phase"] == "plan"]
    # one inventory, two consumers: the plan record's mem total IS the
    # planner's accept/reject number on the single-device path
    assert plan["mem"]["total_bytes"] == plan["bytes_per_device"]

    out = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "obs_report.py"),
         str(tmp_path / "metrics.jsonl")],
        capture_output=True, text=True,
    )
    assert out.returncode == 0, out.stderr
    assert "-- memory (predicted vs peak) --" in out.stdout
    assert "lpa_superstep" in out.stdout and "lof_knn" in out.stdout
    assert "recalibration:" in out.stdout


def test_oom_degrade_carries_watermark_and_inventory(tmp_path):
    """Acceptance: a fault-injected OOM's degrade record carries the
    failed operating point's modeled inventory AND the last
    memory_watermark, joinable back to the full record by span path —
    model-miss vs fragmentation is triageable from the JSONL alone."""
    from graphmine_tpu.pipeline.driver import run_pipeline  # noqa: F401
    from graphmine_tpu.testing import faults

    inj = faults.FaultInjector()
    inj.add("lpa_superstep", faults.oom_error, at=2)
    with inj.installed():
        res = _run_driver(tmp_path)
    recs = res.metrics.records
    assert validate_records(recs) == []
    deg = [r for r in recs if r["phase"] == "degrade"]
    assert deg and deg[0]["to"] == "single_sort"
    # the failed point's modeled inventory rides the record
    assert deg[0]["mem"]["family"] == "bucketed"
    assert deg[0]["mem"]["total_bytes"] > 0
    assert "inventory" in deg[0]["mem"]
    # ... and its last watermark, joinable by span path
    w = deg[0]["last_watermark"]
    marks = [r for r in recs if r["phase"] == "memory_watermark"]
    assert w["span_path"] in {r["span_path"] for r in marks}
    assert w["achieved_bytes"] > 0 and w["source"] in ("device", "rss")
    # the report renders the OOM join
    out = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "obs_report.py"),
         str(tmp_path / "metrics.jsonl")],
        capture_output=True, text=True,
    )
    assert out.returncode == 0, out.stderr
    assert "OOM DEGRADE" in out.stdout
    assert "last watermark:" in out.stdout


def test_plan_time_predegrade_e2e(tmp_path, monkeypatch):
    """A budget squeezed between the blocked and bucketed footprints
    makes the driver consume the family rung at PLAN time: a degrade
    record with kind=mem_plan and the oversized inventory, the bucketed
    kernel actually deployed — and degradation='off' keeps the family.
    (The bucketed pre-build estimate IS the planner's accepted model,
    so only the blocked family — whose stream + tile the 36 B/edge seed
    predates — can exceed a budget the planner admitted.)"""
    v, e = 160, 800
    bl = memmodel.superstep_footprint(
        "lpa_superstep", "blocked", v, 2 * e, num_edges=e
    ).total_bytes
    floor = memmodel.schedule_bytes_per_device("single", v, e, 1)
    assert floor < bl, "fixture must leave a pre-degrade window"
    budget = (bl + floor) // 2
    monkeypatch.setenv("GRAPHMINE_SUPERSTEP_FAMILY", "blocked")
    monkeypatch.setenv("GRAPHMINE_HBM_BYTES", str(int(budget / 0.9) + 1))
    res = _run_driver(tmp_path, max_iter=3)
    recs = res.metrics.records
    pre = [r for r in recs if r["phase"] == "degrade"
           and r.get("kind") == "mem_plan"]
    assert len(pre) == 1 and pre[0]["to"] == "bucketed"
    assert pre[0]["stage"] == "plan_superstep"
    assert pre[0]["mem"]["family"] == "blocked"
    assert pre[0]["mem"]["total_bytes"] == bl > budget
    (sel,) = [r for r in recs if r["phase"] == "impl_selected"]
    assert sel["impl"] == "bucketed" and "pre-degraded" in sel["reason"]
    assert validate_records(recs) == []
    # labels match an unsqueezed (blocked) run: the rung trades memory,
    # not results — blocked/bucketed label parity is the r7 contract
    monkeypatch.setenv("GRAPHMINE_HBM_BYTES", str(1 << 34))
    res2 = _run_driver(tmp_path, max_iter=3,
                       metrics_out=str(tmp_path / "m2.jsonl"))
    np.testing.assert_array_equal(res.labels, res2.labels)
    # an admitted bucketed run NEVER pre-degrades: the pre-build model
    # is the planner's own arithmetic (the one-owner guarantee)
    monkeypatch.delenv("GRAPHMINE_SUPERSTEP_FAMILY")
    monkeypatch.setenv(
        "GRAPHMINE_HBM_BYTES", str(int(floor / 0.9) + 2)
    )
    res4 = _run_driver(tmp_path, max_iter=1,
                       metrics_out=str(tmp_path / "m4.jsonl"))
    assert not [r for r in res4.metrics.records
                if r["phase"] == "degrade" and r.get("kind") == "mem_plan"]
    # degradation="off": the operator wants the OOM, not a leaner family
    from graphmine_tpu.pipeline.resilience import ResilienceConfig

    monkeypatch.setenv("GRAPHMINE_SUPERSTEP_FAMILY", "blocked")
    monkeypatch.setenv("GRAPHMINE_HBM_BYTES", str(int(budget / 0.9) + 1))
    res3 = _run_driver(
        tmp_path, max_iter=1, metrics_out=str(tmp_path / "m3.jsonl"),
        resilience=ResilienceConfig(degradation="off"),
    )
    assert not [r for r in res3.metrics.records
                if r["phase"] == "degrade" and r.get("kind") == "mem_plan"]


# ---------------------------------------------------------------------------
# serve: /statusz memory section, gauges, alert rule, /profilez memory
# ---------------------------------------------------------------------------


def _serve_store(tmp_path):
    from graphmine_tpu.serve.snapshot import SnapshotStore

    store = SnapshotStore(str(tmp_path / "snap"))
    v = 50
    src = np.arange(v, dtype=np.int32)
    dst = (src + 1) % v
    store.publish({
        "src": src, "dst": dst, "labels": np.zeros(v, np.int32),
        "cc_labels": np.zeros(v, np.int32),
        "lof": np.ones(v, np.float32),
    })
    return store


def test_serve_memory_section_and_gauges(tmp_path):
    from graphmine_tpu.serve.server import SnapshotServer

    srv = SnapshotServer(_serve_store(tmp_path), wal=True)
    st = srv.statusz()
    mem = st["memory"]
    # byte accounting decomposes: snapshot arrays (50 vertices x 5
    # arrays x 4 B) vs the derived index, WAL retained bytes, RSS
    assert mem["snapshot_bytes"] == 5 * 50 * 4
    assert mem["index_bytes"] > 0
    assert mem["wal_segment_bytes"] >= 0
    assert mem["rss_bytes"] is None or mem["rss_bytes"] > 0
    text = srv.metrics_text()
    assert "graphmine_memory_rss_bytes" in text
    assert "graphmine_memory_snapshot_bytes" in text
    assert "graphmine_memory_wal_segment_bytes" in text
    # the low-headroom rule reads the same metric the section serves
    values = srv._alert_values()
    if mem["headroom_frac"] is not None:
        assert values["memory_headroom_frac"] == pytest.approx(
            mem["headroom_frac"], abs=0.05
        )


def test_serve_mem_budget_env_and_alert_rule(tmp_path, monkeypatch):
    from graphmine_tpu.obs.alerts import AlertManager, default_rules
    from graphmine_tpu.serve.server import SnapshotServer

    rules = {r.name: r for r in default_rules()}
    assert rules["mem_headroom_low"].op == "<"
    assert rules["mem_headroom_low"].threshold == pytest.approx(0.1)
    monkeypatch.setenv("GRAPHMINE_ALERT_MEM_HEADROOM", "0.5")
    assert {r.name: r for r in default_rules()}[
        "mem_headroom_low"].threshold == 0.5
    m = _sink()
    mgr = AlertManager(sink=m)
    mgr.evaluate({"memory_headroom_frac": 0.4})
    assert "mem_headroom_low" in mgr.firing()
    recs = [r for r in m.records if r.get("phase") == "alert"]
    assert recs and recs[0]["name"] == "mem_headroom_low"
    # an env budget drives headroom deterministically; malformed raises
    monkeypatch.setenv("GRAPHMINE_SERVE_MEM_BUDGET_BYTES", "1e12")
    srv = SnapshotServer(_serve_store(tmp_path))
    mem = srv.memory_payload()
    assert mem["budget_bytes"] == 10 ** 12
    if mem["rss_bytes"] is not None:
        assert 0 < mem["headroom_frac"] <= 1
    monkeypatch.setenv("GRAPHMINE_SERVE_MEM_BUDGET_BYTES", "plenty")
    with pytest.raises(ValueError, match="GRAPHMINE_SERVE_MEM_BUDGET"):
        SnapshotServer(_serve_store(tmp_path / "b"))


def test_profilez_memory_capture(tmp_path, monkeypatch):
    """/profilez kind=memory (satellite): 200 + a capture file under the
    single-flight lock, 501 when the profiler is unavailable, 403
    without a capture dir, 400-class on an unknown kind (HTTP layer)."""
    import jax

    from graphmine_tpu.serve.server import SnapshotServer

    srv = SnapshotServer(
        _serve_store(tmp_path), sink=_sink(),
        profilez_dir=str(tmp_path / "prof"),
    )
    monkeypatch.setattr(
        jax.profiler, "device_memory_profile", lambda: b"fake-pprof"
    )
    status, body = srv.profilez(kind="memory")
    assert status == 200 and body["kind"] == "memory"
    assert os.path.exists(body["path"]) and body["bytes"] == 10
    caps = [r for r in srv.sink.records if r["phase"] == "profile_capture"]
    assert caps and caps[-1]["ok"] and caps[-1]["kind"] == "memory"
    # single-flight: a concurrent capture answers 409
    assert srv._profilez_lock.acquire(blocking=False)
    try:
        assert srv.profilez(kind="memory")[0] == 409
    finally:
        srv._profilez_lock.release()

    def _boom():
        raise RuntimeError("profiler unavailable")

    monkeypatch.setattr(jax.profiler, "device_memory_profile", _boom)
    status, body = srv.profilez(kind="memory")
    assert status == 501 and "unavailable" in body["error"]
    assert SnapshotServer(_serve_store(tmp_path / "n")).profilez(
        kind="memory"
    )[0] == 403


# ---------------------------------------------------------------------------
# obs_report: under-estimate flag + suggestion directions
# ---------------------------------------------------------------------------


def _wm(op, predicted, achieved, source="device", **kv):
    est = memmodel.superstep_footprint("lpa_superstep", "sort", 4, 8,
                                       num_edges=4)
    rec = {"phase": "memory_watermark", "t": 1.0, "op": op,
           "predicted_bytes": predicted, "achieved_bytes": achieved,
           "headroom_frac": 0.5, "source": source, "mem": est.record()}
    rec.update(kv)
    return rec


def test_obs_report_memory_flags_and_suggestions():
    import obs_report

    # device-measured peak 1.5x model: flagged + "raise the seeds"
    report = obs_report.build_report(
        [_wm("lpa_superstep", 1000, 1500)]
    )
    assert "<< model under-estimates" in report
    assert "recalibration: measured peak is 1.50x" in report
    assert "BYTES_PER_EDGE 36 -> 54" in report
    # conservative model: the seeds-can-come-down direction
    low = obs_report.build_report([_wm("lpa_superstep", 1000, 500)])
    assert "conservative" in low
    # within noise: keep the seeds
    ok = obs_report.build_report([_wm("lpa_superstep", 1000, 1000)])
    assert "keep the" in ok and "<< model under-estimates" not in ok
    # rss-only streams never flag against the HBM model
    rss = obs_report.build_report(
        [_wm("lpa_superstep", 1000, 99_000_000, source="rss")]
    )
    assert "<< model under-estimates" not in rss
    assert "host-RSS only" in rss


# ---------------------------------------------------------------------------
# bench: per-tier memory sub-record + bench_diff gate
# ---------------------------------------------------------------------------


def _bench_file(tmp_path, name, n, value, mem=None):
    rec = {"metric": "lpa_edges_per_sec_per_chip", "value": value,
           "unit": "edges/s/chip", "vs_baseline": 1.0}
    if mem is not None:
        rec["detail"] = {"memory": mem}
    path = tmp_path / name
    path.write_text(json.dumps({
        "n": n, "cmd": "python bench.py", "rc": 0,
        "tail": json.dumps(rec) + "\n",
        "parsed": {"metric": "x", "suite": {"tiers": {"chip": {
            "m": rec["metric"], "v": value, "u": rec["unit"], "vs": 1.0,
        }}}},
    }))
    return str(path)


def _mem(peak, upper=False, model=None):
    out = {"peak_rss_bytes": peak, "upper_bound": upper,
           "source": "rusage_children"}
    if model is not None:
        out["model_bytes"] = model
    return out


def test_bench_diff_memory_gate_bytes_regress_up(tmp_path, capsys):
    a = _bench_file(tmp_path, "BENCH_r90.json", 90, 1e8,
                    _mem(1_000_000_000, model=900_000_000))
    b = _bench_file(tmp_path, "BENCH_r91.json", 91, 1e8,
                    _mem(1_300_000_000))
    assert bench_diff.main([a, b]) == 1       # +30% past the ±25% band
    err = capsys.readouterr().err
    assert "chip.memory.peak_rss_bytes" in err
    assert "bytes regress UP" in err
    # within tolerance: clean; DOWN is an improvement, never gates
    c = _bench_file(tmp_path, "BENCH_r92.json", 92, 1e8,
                    _mem(1_200_000_000))
    assert bench_diff.main([a, c]) == 0
    d = _bench_file(tmp_path, "BENCH_r93.json", 93, 1e8,
                    _mem(400_000_000))
    assert bench_diff.main([a, d]) == 0
    # an upper-bound sample (the child never raised the cumulative
    # rusage max) is not comparable and must not gate
    e = _bench_file(tmp_path, "BENCH_r94.json", 94, 1e8,
                    _mem(1_300_000_000, upper=True))
    assert bench_diff.main([a, e]) == 0
    # per-run tolerance override
    assert bench_diff.main([a, b, "--tolerance", "memory=0.5"]) == 0
    capsys.readouterr()


def test_bench_diff_manifest_tracks_memory_subrecord(tmp_path):
    with_mem = _bench_file(tmp_path, "BENCH_r90.json", 90, 1e8,
                           _mem(1_000_000_000))
    without = _bench_file(tmp_path, "BENCH_r89.json", 89, 1e8)
    caps = [bench_diff.load_bench(p) for p in (without, with_mem)]
    manifest = bench_diff.silicon_manifest(caps)
    assert manifest["sub_records"]["chip.memory"] == "silicon"
    assert "serve.memory" in manifest["pending"]
    # ... and the committed trajectory predates the sub-record: pending
    committed = []
    for p in bench_diff.committed_bench_files(REPO):
        try:
            committed.append(bench_diff.load_bench(p))
        except bench_diff.BenchLoadError:
            pass  # r01 is a dead-tunnel capture with no records
    assert committed
    assert "chip.memory" in bench_diff.silicon_manifest(committed)["pending"]


def test_bench_tier_memory_subrecord_shape():
    """bench.py's orchestrator-side injection: the helper stamps a
    schema-stable memory sub-record (peak + upper_bound + model when the
    record names its workload) onto a parsed tier record. ``before`` is
    the cumulative reaped-children max sampled before the child spawned
    — a tier that did not raise it (including one whose apparent raise
    came from a NON-tier child like the backend audit) reports the
    bound with upper_bound=true and never feeds the gate."""
    sys.path.insert(0, REPO)
    import bench

    # spawn one real child so RUSAGE_CHILDREN is non-zero
    subprocess.run([sys.executable, "-c", "print('x' * 100000)"],
                   capture_output=True)
    rec = {"metric": "x", "detail": {"num_vertices": 1000,
                                     "num_edges": 5000}}
    mem = bench._tier_memory_subrecord(rec, before=0)
    assert mem is not None
    assert mem["peak_rss_bytes"] > 0
    assert mem["upper_bound"] is False      # this "child" raised the max
    assert mem["model_bytes"] == memmodel.schedule_bytes_per_device(
        "single", 1000, 5000, 1
    )
    # a tier that did not raise the cumulative max reports the bound —
    # another child's peak is never attributed to it
    now = bench._children_maxrss_bytes()
    mem2 = bench._tier_memory_subrecord({"metric": "y"}, before=now)
    assert mem2["upper_bound"] is True
    assert "model_bytes" not in mem2
