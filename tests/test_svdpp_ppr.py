"""SVD++ and parallel personalized PageRank (GraphFrames capability rows).

GraphFrames 0.6.0 exposes ``svdPlusPlus`` and ``parallelPersonalizedPageRank``
on the GraphFrame object the reference constructs (``Graphframes.py:78``);
neither is called by the script, but both belong to the dependency
capability surface (SURVEY §2.2).
"""

import numpy as np
import pytest

from graphmine_tpu.frames import GraphFrame
from graphmine_tpu.graph.container import build_graph
from graphmine_tpu.ops.pagerank import pagerank, parallel_personalized_pagerank
from graphmine_tpu.ops.svdpp import svd_plus_plus, svdpp_predict


def rating_data(n_users=40, n_items=30, rank=3, density=0.5, seed=1):
    """Low-rank synthetic ratings; items indexed after users."""
    rng = np.random.default_rng(seed)
    u_f = rng.normal(size=(n_users, rank)) / np.sqrt(rank)
    i_f = rng.normal(size=(n_items, rank)) / np.sqrt(rank)
    full = 3.0 + u_f @ i_f.T  # centered at 3 stars
    mask = rng.random((n_users, n_items)) < density
    uu, ii = np.nonzero(mask)
    ratings = np.clip(full[uu, ii] + rng.normal(0, 0.05, len(uu)), 0.0, 5.0)
    return (
        uu.astype(np.int32),
        (n_users + ii).astype(np.int32),
        ratings.astype(np.float32),
        n_users + n_items,
    )


def test_svdpp_training_reduces_rmse():
    src, dst, ratings, v = rating_data()
    model, hist = svd_plus_plus(src, dst, ratings, num_vertices=v, rank=8, max_iter=100)
    hist = np.asarray(hist)
    # training error must drop well below the mean-only predictor's
    baseline = float(np.sqrt(np.mean((ratings - ratings.mean()) ** 2)))
    assert hist[-1] < 0.5 * baseline
    assert hist[-1] < hist[0]
    pred = np.asarray(svdpp_predict(model, src, dst, src, dst))
    assert pred.shape == ratings.shape
    assert float(np.sqrt(np.mean((pred - ratings) ** 2))) < baseline


def test_svdpp_model_shapes_and_isolated_vertices():
    src, dst, ratings, v = rating_data(n_users=10, n_items=8, density=0.4)
    v_padded = v + 5  # vertices with no ratings at all
    model, _ = svd_plus_plus(src, dst, ratings, num_vertices=v_padded, rank=4, max_iter=3)
    assert model.p.shape == (v_padded, 4) and model.bu.shape == (v_padded,)
    assert np.all(np.isfinite(np.asarray(model.p)))
    assert np.all(np.isfinite(np.asarray(model.y)))


def test_parallel_ppr_matches_single_source():
    rng = np.random.default_rng(0)
    v, e = 64, 256
    src = rng.integers(0, v, e).astype(np.int32)
    dst = rng.integers(0, v, e).astype(np.int32)
    g = build_graph(src, dst, num_vertices=v, symmetric=False)
    sources = [3, 17, 42]
    batched = np.asarray(parallel_personalized_pagerank(g, sources, max_iter=60))
    assert batched.shape == (v, 3)
    for j, s in enumerate(sources):
        reset = np.zeros(v, np.float32)
        reset[s] = 1.0
        single = np.asarray(pagerank(g, reset=reset, max_iter=60))
        np.testing.assert_allclose(batched[:, j], single, atol=1e-5)
    # each column is a probability distribution
    np.testing.assert_allclose(batched.sum(axis=0), np.ones(3), atol=1e-4)


def test_graphframe_surface():
    src = np.array([0, 1, 2], np.int32)
    dst = np.array([1, 2, 0], np.int32)
    gf = GraphFrame((src, dst), vertices={"name": np.array(["a", "b", "c"])})
    pp = gf.parallelPersonalizedPageRank([0])
    assert pp.shape == (3, 1)
    model, hist = gf.svdPlusPlus(np.array([5.0, 1.0, 3.0], np.float32), max_iter=2)
    assert model.p.shape[0] == 3 and hist.shape == (2,)
    t = gf.triplets()
    assert t.columns == ["src", "dst", "src_name", "dst_name"]
    assert list(t["src_name"]) == ["a", "b", "c"]
    assert list(t["dst_name"]) == ["b", "c", "a"]


def test_review_fixes_predict_coercion_ppr_range():
    src, dst, ratings, v = rating_data(n_users=8, n_items=6, density=0.6)
    model, _ = svd_plus_plus(src, dst, ratings, num_vertices=v, rank=4, max_iter=2)
    # list inputs coerce; output clipped to the training range
    pred = np.asarray(svdpp_predict(model, list(src[:3]), list(dst[:3]),
                                    list(src), list(dst)))
    assert pred.shape == (3,) and pred.min() >= 0.0 and pred.max() <= 5.0
    g = build_graph(np.array([0, 1], np.int32), np.array([1, 0], np.int32),
                    num_vertices=2, symmetric=False)
    with pytest.raises(ValueError):
        parallel_personalized_pagerank(g, [7])


def test_ppr_empty_sources():
    g = build_graph(np.array([0], np.int32), np.array([1], np.int32),
                    num_vertices=2, symmetric=False)
    out = parallel_personalized_pagerank(g, [])
    assert out.shape == (2, 0)
