"""2D edge partition + neighbor-only frontier exchange (r16, ISSUE 15).

The ``sharded_2d`` family replaces the per-superstep label all_gather —
O(V) bytes per chip regardless of the live frontier — with per-peer
boundary ``ppermute`` shifts carrying exactly the label slots each
peer's bins read. This suite pins, on the 8-virtual-device CPU mesh:

* LPA **and** CC bit-parity against the single-device sort oracle over
  power-law / ring / self-loop / isolated-vertex / duplicate-edge
  graphs, weighted included (the r8 order-independence contract);
* per-peer boundary index-table exactness on hand-built 3-shard graphs
  (the gather tables reconstruct the blocked stream's global sender ids
  slot-for-slot);
* the crossover policy + env-override pins (the single policy owner in
  ``ops/blocking.select_superstep_family``) and the degradation rung
  back to the one-all_gather family;
* costmodel / memmodel exact arithmetic for the new family (modeled
  exchange bytes strictly below the 4·Vc·(D-1) ladder);
* plan-time per-peer-buffer pre-degrade with the inventory in the
  record (the r15 contract);
* the serve warm-repair e2e through the 2D family (sampled exact check
  still passes) and the exchange bench tier's CPU-fallback capture.
"""

import json
import os
import subprocess
import sys

import jax
import numpy as np
import pytest

from graphmine_tpu.graph.container import build_graph
from graphmine_tpu.ops.cc import connected_components
from graphmine_tpu.ops.lpa import label_propagation
from graphmine_tpu.parallel import make_mesh
from graphmine_tpu.parallel.sharded import (
    partition_graph,
    shard_graph_arrays,
    sharded_connected_components,
    sharded_label_propagation,
    sharded_lpa_fixpoint,
)

pytestmark = pytest.mark.sharded2d

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def mesh8():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return make_mesh(8)


def _graphs(rng):
    """The parity graph zoo: power-law, ring (high diameter — the
    local-pointer-jump CC convergence case), self-loops, isolated
    vertices, duplicate edges."""
    v = 96
    deg = rng.pareto(1.2, 400)
    pl_src = np.minimum((deg * v / 40).astype(np.int64), v - 1).astype(np.int32)
    pl_dst = rng.integers(0, v, 400).astype(np.int32)
    ring_src = np.arange(64, dtype=np.int32)
    ring_dst = ((ring_src + 1) % 64).astype(np.int32)
    loops = np.arange(0, 40, 2, dtype=np.int32)
    dup = rng.integers(0, 30, 50).astype(np.int32)
    return [
        ("powerlaw", pl_src, pl_dst, v),
        ("ring", ring_src, ring_dst, 64),
        ("self_loops", np.concatenate([pl_src[:100], loops]),
         np.concatenate([pl_dst[:100], loops]), v),
        # vertices 90..95 isolated (edges only touch [0, 90))
        ("isolated", pl_src[:200] % 90, pl_dst[:200] % 90, v),
        ("duplicates", np.concatenate([dup, dup]),
         np.concatenate([dup[::-1], dup[::-1]]), 30),
    ]


def _partition_2d(g, mesh, **kw):
    return shard_graph_arrays(
        partition_graph(g, mesh=mesh, build_plan2d=True, **kw), mesh
    )


# ---- bit-parity vs the sort oracle -----------------------------------------


def test_2d_lpa_cc_bit_parity(mesh8, rng):
    for name, src, dst, v in _graphs(rng):
        g = build_graph(src, dst, num_vertices=v)
        sg = _partition_2d(g, mesh8)
        assert sg.blk_src is None and sg.x2d_src_local is not None, name
        want = np.asarray(label_propagation(g, max_iter=4))
        got = np.asarray(sharded_label_propagation(sg, mesh8, max_iter=4))
        np.testing.assert_array_equal(got, want, err_msg=f"lpa/{name}")
        want_cc = np.asarray(connected_components(g))
        got_cc = np.asarray(sharded_connected_components(sg, mesh8))
        np.testing.assert_array_equal(got_cc, want_cc, err_msg=f"cc/{name}")


def test_2d_weighted_lpa_bit_parity(mesh8, rng):
    v, e = 80, 400
    src = rng.integers(0, v, e).astype(np.int32)
    dst = rng.integers(0, v, e).astype(np.int32)
    w = rng.uniform(0.1, 3.0, e).astype(np.float32)
    g = build_graph(src, dst, num_vertices=v, edge_weights=w)
    want = np.asarray(label_propagation(g, max_iter=4))
    sg = _partition_2d(g, mesh8)
    assert sg.blk_row_weight, "weighted partition must carry weight mats"
    got = np.asarray(sharded_label_propagation(sg, mesh8, max_iter=4))
    np.testing.assert_array_equal(got, want)


def test_2d_matches_blocked_family_per_superstep(mesh8, rng):
    """Stronger than final-label parity for LPA: every superstep count
    agrees with the one-all_gather blocked family (the tile contents are
    value-for-value identical by construction)."""
    v, e = 70, 300
    src = rng.integers(0, v, e).astype(np.int32)
    dst = rng.integers(0, v, e).astype(np.int32)
    g = build_graph(src, dst, num_vertices=v)
    mesh = mesh8
    sg_blk = shard_graph_arrays(
        partition_graph(g, mesh=mesh, build_blocked_plan=True), mesh
    )
    sg_2d = _partition_2d(g, mesh)
    for it in (1, 2, 3, 5):
        a = np.asarray(sharded_label_propagation(sg_blk, mesh, max_iter=it))
        b = np.asarray(sharded_label_propagation(sg_2d, mesh, max_iter=it))
        np.testing.assert_array_equal(a, b, err_msg=f"superstep {it}")


def test_2d_fixpoint_and_warm_start(mesh8, rng):
    """The serve repair entry: warm-started fixpoint through the 2D
    family converges to the same labels as the cold oracle, and a
    fixpoint stays a fixpoint under one more superstep (the sampled
    exact check's predicate)."""
    v, e = 90, 350
    src = rng.integers(0, v, e).astype(np.int32)
    dst = rng.integers(0, v, e).astype(np.int32)
    g = build_graph(src, dst, num_vertices=v)
    sg = _partition_2d(g, mesh8)
    labels, it, conv = sharded_lpa_fixpoint(sg, mesh8, max_iter=64)
    assert conv and it >= 1
    import jax.numpy as jnp

    again, it2, conv2 = sharded_lpa_fixpoint(
        sg, mesh8, max_iter=1, init_labels=jnp.asarray(labels)
    )
    assert conv2
    np.testing.assert_array_equal(np.asarray(again), np.asarray(labels))


def test_2d_multi_axis_mesh_rejected(rng):
    from graphmine_tpu.parallel.mesh import make_multislice_mesh

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    mesh = make_multislice_mesh(2, 2)
    src = rng.integers(0, 40, 200).astype(np.int32)
    dst = rng.integers(0, 40, 200).astype(np.int32)
    g = build_graph(src, dst, num_vertices=40)
    sg = _partition_2d(g, mesh)
    with pytest.raises(ValueError, match="1-D mesh"):
        sharded_label_propagation(sg, mesh, max_iter=2)


# ---- per-peer index tables (hand-built 3-shard graphs) ---------------------


def _decode_table_ids(sg):
    """Global sender id of every compact-table slot, per shard — padding
    slots decode arbitrarily but are never referenced by real stream
    entries (asserted by the caller via the blocked twin)."""
    d, vc, b = sg.num_shards, sg.chunk_size, sg.x2d_boundary
    tab = np.asarray(sg.x2d_send_tab)
    ids = np.zeros((d, vc + (d - 1) * b + 1), dtype=np.int64)
    for s in range(d):
        ids[s, :vc] = s * vc + np.arange(vc)
        for r in range(1, d):
            owner = (s - r) % d
            ids[s, vc + (r - 1) * b: vc + r * b] = (
                owner * vc + tab[owner, r - 1]
            )
        ids[s, -1] = vc * d  # the sentinel slot decodes to the sentinel id
    return ids


def test_index_tables_reconstruct_stream_3_shards(rng):
    """Decoding each shard's compact table through the send tables must
    reproduce the blocked family's global sender stream slot-for-slot —
    the strongest statement that every peer ships exactly (and only)
    the label slots its neighbor's bins read."""
    v = 18
    src = np.array([0, 3, 7, 11, 15, 17, 2, 9, 9, 4], dtype=np.int32)
    dst = np.array([6, 13, 1, 5, 0, 12, 2, 16, 16, 10], dtype=np.int32)
    for pad in (1, 8):
        blk = partition_graph(
            src, dst, num_vertices=v, num_shards=3,
            build_blocked_plan=True, pad_multiple=pad,
        )
        sg = partition_graph(
            src, dst, num_vertices=v, num_shards=3,
            build_plan2d=True, pad_multiple=pad,
        )
        ids = _decode_table_ids(sg)
        decoded = np.take_along_axis(
            ids, np.asarray(sg.x2d_src_local, np.int64), axis=1
        )
        np.testing.assert_array_equal(decoded, np.asarray(blk.blk_src))


def test_boundary_sets_are_unique_sorted_and_exact():
    """Hand-computed boundary sets on a 3-shard graph (pad_multiple=1 →
    Vc = 2): shard 0 owns {0,1}, shard 1 {2,3}, shard 2 {4,5}. Edges are
    symmetric messages, so each endpoint is a sender toward the other."""
    # edges: 0-2, 1-4, 3-5  (messages both directions)
    src = np.array([0, 1, 3], dtype=np.int32)
    dst = np.array([2, 4, 5], dtype=np.int32)
    sg = partition_graph(
        src, dst, num_vertices=6, num_shards=3,
        build_plan2d=True, pad_multiple=1,
    )
    d, vc, b = 3, sg.chunk_size, sg.x2d_boundary
    assert vc == 2
    tab = np.asarray(sg.x2d_send_tab)
    # need(shard, offset r) == what owner (shard - r) % 3 ships at shift r
    # shard 0 reads: sender 2 (owner 1, r=2), sender 4 (owner 2, r=1)
    # shard 1 reads: sender 0 (owner 0, r=1), sender 5 (owner 2, r=2)
    # shard 2 reads: sender 1 (owner 0, r=2), sender 3 (owner 1, r=1)
    want = {
        # (owner, r) -> local ids shipped
        (2, 1): [0],   # 4 -> shard 0
        (1, 2): [0],   # 2 -> shard 0
        (0, 1): [0],   # 0 -> shard 1
        (2, 2): [1],   # 5 -> shard 1
        (1, 1): [1],   # 3 -> shard 2
        (0, 2): [1],   # 1 -> shard 2
    }
    for (owner, r), ids in want.items():
        got = tab[owner, r - 1, : len(ids)].tolist()
        assert got == ids, ((owner, r), got, ids)
    assert sg.x2d_boundary_total == 6
    assert b >= 1


def test_plan2d_mutually_exclusive_with_bucket_plan(rng):
    src = rng.integers(0, 20, 50).astype(np.int32)
    dst = rng.integers(0, 20, 50).astype(np.int32)
    with pytest.raises(ValueError, match="mutually exclusive"):
        partition_graph(
            src, dst, num_vertices=20, num_shards=2,
            build_bucket_plan=True, build_plan2d=True,
        )


# ---- crossover policy + planner ladder -------------------------------------


def test_policy_selects_2d_past_crossover():
    from graphmine_tpu.ops.blocking import (
        SHARDED2D_MIN_MESSAGES,
        select_superstep_family,
    )

    fam, reason = select_superstep_family(
        1 << 16, SHARDED2D_MIN_MESSAGES, num_devices=8
    )
    assert fam == "sharded_2d" and "neighbor-only" in reason
    # below the message floor: not 2D
    fam, _ = select_superstep_family(
        1 << 16, SHARDED2D_MIN_MESSAGES - 1, num_devices=8
    )
    assert fam != "sharded_2d"
    # single device: never 2D, whatever the size
    fam, _ = select_superstep_family(1 << 22, 1 << 23, num_devices=1)
    assert fam != "sharded_2d"


def test_policy_requested_2d_on_one_device_is_loud():
    from graphmine_tpu.ops.blocking import select_superstep_family

    with pytest.raises(ValueError, match="2-device mesh"):
        select_superstep_family(100, 100, requested="sharded_2d")
    fam, reason = select_superstep_family(
        100, 100, requested="sharded_2d", num_devices=4
    )
    assert fam == "sharded_2d" and "requested" in reason


def test_policy_env_overrides(monkeypatch):
    from graphmine_tpu.ops.blocking import (
        crossover_thresholds,
        select_superstep_family,
    )

    monkeypatch.setenv("GRAPHMINE_SHARDED2D_MIN_MESSAGES", "10")
    monkeypatch.setenv("GRAPHMINE_SHARDED2D_MIN_DEVICES", "3")
    thr = crossover_thresholds()
    assert thr["sharded2d_min_messages"] == 10
    assert thr["sharded2d_min_devices"] == 3
    fam, _ = select_superstep_family(100, 10, num_devices=3)
    assert fam == "sharded_2d"
    fam, _ = select_superstep_family(100, 10, num_devices=2)
    assert fam != "sharded_2d", "moved device floor must hold"
    # the process-wide family override applies to sharded resolutions
    # but silently does NOT apply on one device (fused ops keep working)
    monkeypatch.setenv("GRAPHMINE_SUPERSTEP_FAMILY", "sharded_2d")
    fam, reason = select_superstep_family(100, 5, num_devices=2)
    assert fam == "sharded_2d" and "env override" in reason
    fam, _ = select_superstep_family(100, 5, num_devices=1)
    assert fam != "sharded_2d"


def test_planner_ladder_degrades_2d_to_one_allgather():
    from graphmine_tpu.obs.memmodel import FAMILY_DEGRADE
    from graphmine_tpu.pipeline.planner import (
        _SUPERSTEP_DEGRADE,
        plan_superstep,
    )

    assert _SUPERSTEP_DEGRADE["sharded_2d"] == "blocked"
    assert FAMILY_DEGRADE["sharded_2d"] == "blocked"
    plan = plan_superstep(1 << 16, 1 << 14, num_devices=8)
    assert plan.family == "sharded_2d" and plan.degrade_to == "blocked"
    # single-device resolution is byte-identical to the pre-r16 policy
    plan1 = plan_superstep(1 << 16, 1 << 14)
    assert plan1.family != "sharded_2d"


# ---- costmodel / memmodel exact arithmetic ---------------------------------


def _tiny_2d_partition(rng, v=4096, e=8192, d=4):
    # power-law-skewed sources (the bench graph's shape): boundaries
    # stay well under Vc, so the strictly-below pins have real margin
    raw = rng.pareto(1.2, e)
    src = np.minimum((raw * v / 50).astype(np.int64), v - 1).astype(np.int32)
    dst = rng.integers(0, v, e).astype(np.int32)
    return partition_graph(
        src, dst, num_vertices=v, num_shards=d, build_plan2d=True
    )


def test_costmodel_exchange_bytes_exact_and_below_ladder(rng):
    from graphmine_tpu.obs.costmodel import (
        allgather_exchange_bytes,
        neighbor_exchange_bytes,
        neighbor_frontier_bytes,
        sharded_superstep_cost,
    )

    for d in (4, 8):
        sg = _tiny_2d_partition(rng, d=d)
        cost = sharded_superstep_cost("lpa_superstep", sg, 8192)
        assert cost.family == "sharded_2d"
        assert cost.devices == d
        # WIRE bytes, exact: (D-1) padded shared-width buffers per chip
        assert cost.exchange_bytes == 4 * (d - 1) * sg.x2d_boundary
        assert cost.exchange_bytes == neighbor_exchange_bytes(sg)
        # frontier floor, exact: ceil(unpadded total / D) * 4 bytes
        frontier = neighbor_frontier_bytes(sg)
        assert frontier == 4 * -(-sg.x2d_boundary_total // d)
        assert frontier <= cost.exchange_bytes
        ladder = allgather_exchange_bytes(sg)
        assert ladder == 4 * sg.chunk_size * (d - 1)
        # the acceptance pin: strictly below the one-all_gather model —
        # for the honest WIRE bytes, padding included
        assert cost.exchange_bytes < ladder
        # compute model matches the blocked family's shapes
        mp = int(np.asarray(sg.x2d_src_local).shape[1])
        rows = sum(
            int(r.shape[1]) * int(r.shape[2]) for r in sg.blk_row_idx
        )
        assert cost.padded_slots == mp + rows


def test_memmodel_footprint_exact_against_shapes(rng):
    from graphmine_tpu.obs.memmodel import sharded_superstep_footprint

    d = 4
    sg = _tiny_2d_partition(rng, d=d)
    est = sharded_superstep_footprint("lpa_superstep", sg)
    assert est.family == "sharded_2d" and est.exact
    b = sg.x2d_boundary
    inv = est.inventory
    assert inv["exchange_send_tab"] == 4 * (d - 1) * b
    assert inv["exchange_recv_bufs"] == 4 * (d - 1) * b
    assert inv["labels_sharded"] == 2 * 4 * sg.chunk_size
    assert "labels_replicated" not in inv and "exchange_buffer" not in inv
    mp = int(np.asarray(sg.x2d_src_local).shape[1])
    assert inv["stream"] == 4 * mp + 4 * mp  # src_local + blk_pos
    # the record round-trips through the schema's mem sub-record shape
    rec = est.record()
    assert rec["family"] == "sharded_2d" and rec["total_bytes"] > 0


def test_predegrade_per_peer_buffers(monkeypatch):
    """A plan whose per-peer buffer footprint exceeds the budget
    pre-degrades at plan time, with the oversized inventory carried in
    the steps trail (r15 contract); a generous budget keeps the 2D
    family."""
    from graphmine_tpu.obs.memmodel import (
        predegrade_superstep,
        superstep_footprint,
    )

    v, m, e, d = 1 << 16, 1 << 17, 1 << 16, 8
    est = superstep_footprint(
        "lpa_superstep", "sharded_2d", v, m, num_edges=e, num_devices=d
    )
    assert not est.exact and est.devices == d
    vc = -(-v // d)
    assert est.inventory["exchange_send_tab"] == 4 * vc * (d - 1)
    # budget below the 2D model: walks off the family, first rung is the
    # one-all_gather blocked family, inventory attached
    fam, _fit, steps = predegrade_superstep(
        "sharded_2d", v, m, e, False, est.total_bytes // 4, num_devices=d
    )
    assert fam != "sharded_2d" and steps
    assert steps[0][0] == "sharded_2d" and steps[0][1] == "blocked"
    assert steps[0][2].total_bytes == est.total_bytes
    # generous budget: stays
    fam2, _f, steps2 = predegrade_superstep(
        "sharded_2d", v, m, e, False, 1 << 40, num_devices=d
    )
    assert fam2 == "sharded_2d" and not steps2
    with pytest.raises(ValueError, match="num_devices >= 2"):
        superstep_footprint(
            "lpa_superstep", "sharded_2d", v, m, num_edges=e
        )


def test_shard_exchange_record_shape(rng):
    import time

    from graphmine_tpu.obs.costmodel import emit_shard_exchange
    from graphmine_tpu.obs.schema import validate_record

    class Sink:
        def emit(self, phase, **kv):
            return dict(phase=phase, t=time.time(), **kv)

    sg = _tiny_2d_partition(rng)
    rec = emit_shard_exchange(Sink(), "delta_repair", sg)
    assert validate_record(rec) == []
    assert rec["family"] == "sharded_2d" and rec["peers"] == 3
    assert rec["frontier_bytes"] <= rec["exchange_bytes"]
    assert rec["frontier_frac"] == round(
        rec["frontier_bytes"] / rec["ladder_bytes"], 4
    )
    # the one-all_gather families emit frac 1.0 by construction
    sg_sort = partition_graph(
        np.arange(8, dtype=np.int32), np.arange(8, dtype=np.int32)[::-1],
        num_vertices=8, num_shards=2,
    )
    rec2 = emit_shard_exchange(Sink(), "delta_repair", sg_sort)
    assert rec2["family"] == "sort" and rec2["frontier_frac"] == 1.0
    assert emit_shard_exchange(None, "x", sg) is None


# ---- serve warm-repair e2e -------------------------------------------------


def _community_edges(rng, v=60):
    half = v // 2
    src = np.concatenate(
        [rng.integers(0, half, 120), rng.integers(half, v, 120)]
    ).astype(np.int32)
    dst = np.concatenate(
        [rng.integers(0, half, 120), rng.integers(half, v, 120)]
    ).astype(np.int32)
    return src, dst


def test_serve_warm_repair_selects_2d(tmp_path, monkeypatch, rng):
    """The acceptance e2e: past the (env-lowered) crossover the sharded
    ingestor repairs through the 2D family — asserted from the
    shard_exchange record and last_shard_family — and the published
    labels still pass the sampled exact check (method == warm) and match
    the cold oracle."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from graphmine_tpu.obs.spans import Tracer
    from graphmine_tpu.pipeline.checkpoint import graph_fingerprint
    from graphmine_tpu.pipeline.metrics import MetricsSink
    from graphmine_tpu.serve.delta import (
        DeltaIngestor,
        EdgeDelta,
        cold_recompute,
        splice_edges,
        validate_delta,
    )
    from graphmine_tpu.serve.snapshot import SnapshotStore

    monkeypatch.setenv("GRAPHMINE_SHARDED2D_MIN_MESSAGES", "1")
    v = 60
    src, dst = _community_edges(rng, v)
    g = build_graph(src, dst, num_vertices=v)
    labels, cc, _ = cold_recompute(g)
    sink = MetricsSink(tracer=Tracer())
    store = SnapshotStore(str(tmp_path / "snap"))
    store.publish(
        {"src": src, "dst": dst, "labels": labels, "cc_labels": cc,
         "lof": np.zeros(v, np.float32)},
        fingerprint=graph_fingerprint(src, dst), sink=sink,
    )
    ing = DeltaIngestor(
        store, sink=sink, lof_k=4, check_samples=16, num_shards=8,
        quality=False,
    )
    delta = EdgeDelta.from_pairs(
        insert=[(40, 12), (40, 13), (40, 14)], delete=[(0, 1)]
    )
    snap = ing.apply(delta)
    assert ing.last_shard_family == "sharded_2d"
    ex = [r for r in sink.records if r.get("phase") == "shard_exchange"]
    assert ex and ex[-1]["family"] == "sharded_2d"
    # at this toy scale the pad_multiple floor dominates the WIRE bytes;
    # the exact frontier content is what the tiny repair saves
    assert ex[-1]["frontier_bytes"] < ex[-1]["ladder_bytes"]
    rec = [r for r in sink.records if r.get("phase") == "delta_apply"][-1]
    assert rec["method"] == "warm"
    clean, _ = validate_delta(delta, v)
    src2, dst2, v2, _ = splice_edges(src, dst, v, clean)
    cold_l, cold_c, _ = cold_recompute(build_graph(src2, dst2, num_vertices=v2))
    np.testing.assert_array_equal(snap["labels"], cold_l)
    np.testing.assert_array_equal(snap["cc_labels"], cold_c)


def test_serve_predegrades_2d_on_tiny_budget(tmp_path, monkeypatch, rng):
    """A per-peer buffer footprint past the HBM budget pre-degrades at
    plan time: the repair routes through the one-all_gather partition,
    the degrade record carries the oversized memmodel inventory, and the
    published labels are still exact."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from graphmine_tpu.obs.spans import Tracer
    from graphmine_tpu.pipeline.checkpoint import graph_fingerprint
    from graphmine_tpu.pipeline.metrics import MetricsSink
    from graphmine_tpu.serve.delta import (
        DeltaIngestor,
        EdgeDelta,
        cold_recompute,
    )
    from graphmine_tpu.serve.snapshot import SnapshotStore

    monkeypatch.setenv("GRAPHMINE_SHARDED2D_MIN_MESSAGES", "1")
    monkeypatch.setenv("GRAPHMINE_HBM_BYTES", "512")  # nothing 2D fits
    v = 60
    src, dst = _community_edges(rng, v)
    g = build_graph(src, dst, num_vertices=v)
    labels, cc, _ = cold_recompute(g)
    sink = MetricsSink(tracer=Tracer())
    store = SnapshotStore(str(tmp_path / "snap"))
    store.publish(
        {"src": src, "dst": dst, "labels": labels, "cc_labels": cc,
         "lof": np.zeros(v, np.float32)},
        fingerprint=graph_fingerprint(src, dst), sink=sink,
    )
    ing = DeltaIngestor(
        store, sink=sink, lof_k=4, check_samples=16, num_shards=8,
        quality=False,
    )
    ing.apply(EdgeDelta.from_pairs(insert=[(40, 12), (40, 13)]))
    assert ing.last_shard_family == "sort"
    deg = [
        r for r in sink.records
        if r.get("phase") == "degrade" and r.get("kind") == "mem_plan"
    ]
    assert deg and deg[0]["stage"] == "delta_repair_plan"
    assert deg[0]["mem"]["family"] == "sharded_2d"
    assert "exchange_send_tab" in deg[0]["mem"]["inventory"]
    ex = [r for r in sink.records if r.get("phase") == "shard_exchange"]
    assert ex and ex[-1]["family"] == "sort"


# ---- bench exchange tier ---------------------------------------------------


def test_exchange_tier_body_cpu_smoke():
    """Run ``main_exchange``'s ACTUAL measurement body end-to-end on an
    8-virtual-device CPU mesh at env-capped tiny scale (the blocking
    tier's convention), and pin the acceptance criterion: modeled 2D
    exchange bytes strictly below the one-all_gather 4·Vc·(D-1) on the
    bench power-law graph at D >= 4, read from the costmodel-derived
    record of the CPU-fallback capture."""
    sys.path.insert(0, _REPO)
    try:
        import __graft_entry__

        env = __graft_entry__._load_envscrub().virtual_cpu_env(8)
    finally:
        sys.path.pop(0)
    env.update(
        GRAPHMINE_BENCH_CPU_FALLBACK="1",
        _GRAPHMINE_BENCH_CHILD="1",
        GRAPHMINE_EXCHANGE_VERTICES=str(1 << 13),
        GRAPHMINE_EXCHANGE_EDGES=str(1 << 14),
        GRAPHMINE_EXCHANGE_ITERS="2",
    )
    out = subprocess.run(
        [sys.executable, os.path.join(_REPO, "bench.py"), "--tier",
         "exchange"],
        capture_output=True, text=True, timeout=420, env=env, cwd=_REPO,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(
        [l for l in out.stdout.splitlines() if l.startswith("{")][-1]
    )
    assert rec["metric"] == "exchange_neighbor_bytes_frac_cpu_fallback"
    assert 0 < rec["value"] < 1
    d = rec["detail"]
    assert d["neighbor_vs_allgather"] > 0
    for dd in ("2", "4", "8"):
        row = d["per_devices"][dd]
        assert row["agree"], f"parity failed at D={dd}"
        # the ladder model exactly: 4·Vc·(D-1), Vc = ceil(V/D) padded
        # to the partitioner's multiple of 8
        n = int(dd)
        vc = -(-(-(-d["num_vertices"] // n)) // 8) * 8
        assert row["allgather_exchange_bytes"] == 4 * vc * (n - 1)
    # THE acceptance pin: strictly below the ladder at D >= 4
    for dd in ("4", "8"):
        row = d["per_devices"][dd]
        assert (
            row["neighbor_exchange_bytes"] < row["allgather_exchange_bytes"]
        ), f"2D exchange bytes not below the all_gather ladder at D={dd}"


def test_exchange_tier_registered():
    """Tier order / timeout / manifest / bench_diff registration — the
    next silicon window captures the crossover alongside the blocking
    backlog."""
    sys.path.insert(0, _REPO)
    try:
        import importlib

        bench = importlib.import_module("bench")
        sys.path.insert(0, os.path.join(_REPO, "tools"))
        try:
            bench_diff = importlib.import_module("bench_diff")
        finally:
            sys.path.pop(0)
    finally:
        sys.path.pop(0)
    assert "exchange" in bench._TIER_ORDER
    assert "exchange" in bench._FALLBACK_TIERS
    assert "exchange" in bench._CHILD_TIMEOUT_S
    assert tuple(bench._TIER_ORDER) == bench_diff.ALL_TIERS
    assert bench_diff.SUB_RECORDS["exchange"] == ("neighbor_vs_allgather",)
    assert "frac" in bench_diff.LOWER_BETTER_UNITS
    # the orchestrator hands the exchange child a virtual multi-device
    # mesh unless the operator marks a real multi-chip window
    env = bench._tier_child_env("exchange", dict(os.environ))
    assert env.get("GRAPHMINE_BENCH_CPU_FALLBACK") == "1"
    assert "xla_force_host_platform_device_count=8" in env.get("XLA_FLAGS", "")
