"""Write-path admission control suite (marker ``admission``):
tools/run_tier1.sh --admission-only.

The acceptance pins (ISSUE 8):

- ONE policy owner resolves accept/queue/coalesce/shed; every bound
  trips its own rung, every bound is ``GRAPHMINE_ADMIT_*``
  env-overridable, and every resolution leaves an ``admission``
  provenance record;
- coalescing is ORDER-EXACT: splicing the merged delta produces
  byte-identical edge arrays to splicing the batches sequentially,
  including cross-batch insert-then-delete cancellation and weighted
  batches;
- THE chaos test: an injected burst against a slowed repair must
  coalesce, keep ``repair_debt_rows`` under the configured bound, shed
  visibly (503 + Retry-After + ``delta_shed`` record), never crash, and
  never serve a label state the sampled exact check disputes;
- a live-query hammer across a shed sees zero drops and no mixed
  versions (the PR 5 double-buffer guarantee survives overload).
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from graphmine_tpu.graph.container import build_graph
from graphmine_tpu.obs.schema import validate_records
from graphmine_tpu.obs.spans import Tracer
from graphmine_tpu.pipeline.checkpoint import graph_fingerprint
from graphmine_tpu.pipeline.metrics import MetricsSink
from graphmine_tpu.serve import (
    AdmissionBounds,
    AdmissionController,
    DeltaIngestor,
    EdgeDelta,
    SnapshotStore,
    coalesce_deltas,
)
from graphmine_tpu.serve.delta import (
    RepairDebt,
    cold_recompute,
    sampled_exact_check,
    splice_edges,
    validate_delta,
)
from graphmine_tpu.serve.server import SnapshotServer
from graphmine_tpu.testing import faults

pytestmark = pytest.mark.admission


# ---- fixtures -------------------------------------------------------------


def _clique(lo, hi):
    ids = np.arange(lo, hi)
    s, d = np.meshgrid(ids, ids)
    m = s.ravel() < d.ravel()
    return s.ravel()[m], d.ravel()[m]


def _community_graph():
    parts = [_clique(0, 12), _clique(12, 26), _clique(26, 40)]
    src = np.concatenate([p[0] for p in parts]).astype(np.int32)
    dst = np.concatenate([p[1] for p in parts]).astype(np.int32)
    return src, dst, 40


def _sink():
    return MetricsSink(tracer=Tracer())


def _publish_base(tmp_path, sink=None):
    src, dst, v = _community_graph()
    g = build_graph(src, dst, num_vertices=v)
    labels, cc, _ = cold_recompute(g)
    store = SnapshotStore(str(tmp_path / "snap"))
    store.publish(
        {
            "src": src, "dst": dst, "labels": labels, "cc_labels": cc,
            "lof": np.zeros(v, np.float32),
        },
        fingerprint=graph_fingerprint(src, dst),
        sink=sink,
    )
    return store, src, dst, v


def _post(host, port, path, payload, timeout=120):
    req = urllib.request.Request(
        f"http://{host}:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def _get(host, port, path, timeout=30):
    with urllib.request.urlopen(
        f"http://{host}:{port}{path}", timeout=timeout
    ) as r:
        return json.loads(r.read())


# ---- policy unit ----------------------------------------------------------


def test_each_bound_trips_its_rung():
    """Every configured bound trips exactly its own verdict, with the
    deciding numbers in the reason string."""
    ctl = AdmissionController(bounds=AdmissionBounds(
        max_pending_rows=100, max_queue_depth=3, max_ingest_lag_s=5.0,
        defer_frac=0.5,
    ))
    debt = RepairDebt()
    empty = debt.snapshot()
    assert ctl.resolve(10, 0, empty).verdict == "accept"
    assert ctl.resolve(10, 0, empty, applying=True).verdict == "queue"
    assert ctl.resolve(10, 1, empty).verdict == "coalesce"
    d = ctl.resolve(10, 3, empty)
    assert d.verdict == "shed" and "queue_depth 3" in d.reason
    assert d.retry_after_s > 0
    debt.submitted(95)
    d = ctl.resolve(10, 0, debt.snapshot())
    assert d.verdict == "shed" and "pending_rows 95 + 10" in d.reason
    # lag bound: an old submitted entry ages the queue
    debt2 = RepairDebt()
    debt2.submitted(1, t=time.time() - 10)
    d = ctl.resolve(1, 0, debt2.snapshot())
    assert d.verdict == "shed" and "ingest_lag" in d.reason
    counts = ctl.snapshot()["verdicts"]
    assert counts["shed"] == 3 and counts["accept"] == 1
    assert counts["queue"] == 1 and counts["coalesce"] == 1


def test_defer_rung_flips_lof_mode_without_shedding():
    """Rung 2: pressure past defer_frac defers the LOF refresh but still
    admits — and never defers on a shed (nothing will apply)."""
    ctl = AdmissionController(bounds=AdmissionBounds(
        max_pending_rows=100, defer_frac=0.5,
    ))
    debt = RepairDebt()
    debt.submitted(60)
    d = ctl.resolve(10, 0, debt.snapshot())
    assert d.verdict == "accept" and d.lof_mode == "defer"
    assert "lof deferred" in d.reason
    assert ctl.lof_mode(debt.snapshot()) == "defer"
    drained = RepairDebt()
    assert ctl.resolve(10, 0, drained.snapshot()).lof_mode == "refresh"


def test_bounds_env_overrides(monkeypatch):
    """Every bound follows the GRAPHMINE_ADMIT_* convention; explicit
    kwargs beat env; malformed env raises loudly."""
    monkeypatch.setenv("GRAPHMINE_ADMIT_MAX_PENDING_ROWS", "123")
    monkeypatch.setenv("GRAPHMINE_ADMIT_MAX_LAG_S", "7.5")
    monkeypatch.setenv("GRAPHMINE_ADMIT_MAX_QUEUE_DEPTH", "4")
    monkeypatch.setenv("GRAPHMINE_ADMIT_DEFER_FRAC", "0.25")
    monkeypatch.setenv("GRAPHMINE_ADMIT_DEADLINE_S", "9")
    monkeypatch.setenv("GRAPHMINE_ADMIT_RETRY_AFTER_S", "3")
    b = AdmissionBounds.from_env()
    assert (b.max_pending_rows, b.max_ingest_lag_s, b.max_queue_depth) == (
        123, 7.5, 4
    )
    assert (b.defer_frac, b.deadline_s, b.retry_after_s) == (0.25, 9.0, 3.0)
    assert AdmissionBounds.from_env(max_queue_depth=8).max_queue_depth == 8
    monkeypatch.setenv("GRAPHMINE_ADMIT_MAX_PENDING_ROWS", "lots")
    with pytest.raises(ValueError, match="GRAPHMINE_ADMIT_MAX_PENDING_ROWS"):
        AdmissionBounds.from_env()


def test_bounds_validation():
    with pytest.raises(ValueError):
        AdmissionBounds(max_queue_depth=0)
    with pytest.raises(ValueError):
        AdmissionBounds(max_ingest_lag_s=0)
    with pytest.raises(ValueError):
        AdmissionBounds(defer_frac=-1)


def test_every_resolution_emits_provenance():
    sink = _sink()
    ctl = AdmissionController(
        bounds=AdmissionBounds(max_queue_depth=2), sink=sink
    )
    debt = RepairDebt().snapshot()
    for depth in (0, 1, 2):
        ctl.resolve(5, depth, debt)
    recs = [r for r in sink.records if r["phase"] == "admission"]
    assert [r["verdict"] for r in recs] == ["accept", "coalesce", "shed"]
    for r in recs:
        assert r["queue_depth"] in (0, 1, 2) and r["rows"] == 5
        assert isinstance(r["repair_debt"], dict)
    assert validate_records(sink.records) == []


def test_overloaded_matches_shed_verdict():
    """The /healthz drain signal and the shed verdict share one
    saturation test — no duplicated thresholds to drift."""
    ctl = AdmissionController(bounds=AdmissionBounds(max_pending_rows=10))
    debt = RepairDebt()
    over, _ = ctl.overloaded(0, debt.snapshot())
    assert not over
    debt.submitted(10)
    over, why = ctl.overloaded(0, debt.snapshot())
    assert over and "pending_rows" in why
    assert ctl.resolve(1, 0, debt.snapshot()).verdict == "shed"


# ---- coalescing -----------------------------------------------------------


def test_coalesce_cancellation_orders():
    """The cross-batch interaction table: deletes prefer base
    occurrences, then the OLDEST surviving in-window insert; a batch
    never deletes its own inserts; unmatched deletes drop."""
    base_src = np.asarray([0, 0, 1], np.int64)   # (0,1) twice, (1,2) once
    base_dst = np.asarray([1, 1, 2], np.int64)
    batches = [
        # A: inserts (5,6); deletes one base (0,1)
        EdgeDelta.from_pairs(insert=[(5, 6)], delete=[(0, 1)]),
        # B: deletes (5,6) -> cancels A's insert (base has none);
        #    deletes (0,1) -> second base occurrence;
        #    deletes (7,8) -> unmatched; inserts (5,6) fresh
        EdgeDelta.from_pairs(
            insert=[(5, 6)], delete=[(5, 6), (0, 1), (7, 8)]
        ),
        # C: deletes (5,6) AND inserts (5,6): must consume B's insert,
        #    NOT its own
        EdgeDelta.from_pairs(insert=[(5, 6)], delete=[(5, 6)]),
    ]
    merged, info = coalesce_deltas(batches, base_src, base_dst)
    assert info["cancelled_pairs"] == 2 and info["unmatched_deletes"] == 1
    # survivors: C's insert; base-deletes: (0,1) twice
    assert merged.num_inserts == 1 and merged.num_deletes == 2
    # and the spliced result equals the sequential one
    s, d, v = base_src, base_dst, 9
    for b in batches:
        clean, _ = validate_delta(b, v)
        s, d, v, _ = splice_edges(s, d, v, clean)
    s2, d2, v2, _ = splice_edges(base_src, base_dst, 9, merged)
    np.testing.assert_array_equal(s, s2)
    np.testing.assert_array_equal(d, d2)


@pytest.mark.parametrize("weighted", [False, True], ids=["unweighted", "weighted"])
def test_coalesce_equals_sequential(weighted):
    """Randomized parity: splice(coalesce(batches)) is byte-identical to
    sequential splices — edges, weights, vertex space. Batches reuse hot
    keys so cross-batch insert/delete collisions actually occur."""
    rng = np.random.default_rng(11)
    src = rng.integers(0, 20, 300).astype(np.int32)
    dst = rng.integers(0, 20, 300).astype(np.int32)
    w = rng.random(300).astype(np.float32) if weighted else None
    batches = []
    for i in range(6):
        n = int(rng.integers(3, 12))
        ins = rng.integers(0, 24, size=(n, 2))
        if weighted:
            ins_rows = [
                (int(a), int(b), float(rng.integers(1, 5))) for a, b in ins
            ]
        else:
            ins_rows = [(int(a), int(b)) for a, b in ins]
        m = int(rng.integers(1, 8))
        dels = [
            (int(a), int(b))
            for a, b in zip(rng.integers(0, 24, m), rng.integers(0, 24, m))
        ]
        batches.append(EdgeDelta.from_pairs(insert=ins_rows, delete=dels))
    # sequential
    s, d, wseq, v = src, dst, w, 20
    for b in batches:
        clean, _ = validate_delta(b, v)
        if weighted:
            s, d, wseq, v, _ = splice_edges(s, d, v, clean, weights=wseq)
        else:
            s, d, v, _ = splice_edges(s, d, v, clean)
    # coalesced — validation tracks vertex growth across the group, as
    # the server's worker does: each batch sees the vertex space grown
    # by the batches before it, never the fixed base count
    cleans, v_cur = [], 20
    for b in batches:
        clean, _ = validate_delta(b, v_cur)
        cleans.append(clean)
        if clean.num_inserts:
            v_cur = max(
                v_cur,
                int(clean.insert_src.max()) + 1,
                int(clean.insert_dst.max()) + 1,
            )
    merged, info = coalesce_deltas(cleans, src, dst)
    assert info["rows_out"] <= info["rows_in"]
    if weighted:
        s2, d2, w2, v2, _ = splice_edges(src, dst, 20, merged, weights=w)
        np.testing.assert_array_equal(wseq, w2)
    else:
        s2, d2, v2, _ = splice_edges(src, dst, 20, merged)
    assert v == v2
    np.testing.assert_array_equal(s, s2)
    np.testing.assert_array_equal(d, d2)


def test_coalesced_delete_of_earlier_batch_new_vertex_edge(tmp_path):
    """The cross-batch growth case: batch 1 inserts an edge to a NEW
    vertex, batch 2 deletes that same edge. Coalesced through the
    server's worker, the pair must cancel exactly as sequential applies
    would — validating batch 2 against the pre-group vertex count would
    quarantine its delete and serve an edge that should be gone."""
    # unit leg: validation with running-V, then coalesce
    base_src = np.asarray([0, 1], np.int64)
    base_dst = np.asarray([1, 2], np.int64)
    b1 = EdgeDelta.from_pairs(insert=[(5, 1)])
    b2 = EdgeDelta.from_pairs(delete=[(5, 1)])
    c1, _ = validate_delta(b1, 3)
    c2, q2 = validate_delta(b2, 6)  # the grown space batch 2 really sees
    assert q2["unmatched_deletes"] == 0
    merged, info = coalesce_deltas([c1, c2], base_src, base_dst)
    assert info["cancelled_pairs"] == 1
    assert merged.num_inserts == 0 and merged.num_deletes == 0
    # server leg: hold the worker on a slow apply so both batches queue
    # and coalesce, then check the served edges
    sink = _sink()
    store, src, dst, v = _publish_base(tmp_path, sink=sink)
    server = SnapshotServer(store, sink=sink)
    host, port = server.start()
    inj = faults.FaultInjector()
    inj.add("delta_repair", faults.slow_repair(0.8), at=1, repeat=1)
    results = []

    def fire(payload):
        results.append(_post(host, port, "/delta", payload))

    try:
        with inj.installed():
            t0 = threading.Thread(target=fire, args=({"insert": [[0, 13]]},))
            t0.start()
            time.sleep(0.25)  # batch 0 mid-apply; the next two will queue
            t1 = threading.Thread(
                target=fire, args=({"insert": [[v, 0], [v, 1]]},)
            )
            t1.start()
            time.sleep(0.1)
            t2 = threading.Thread(target=fire, args=({"delete": [[v, 0]]},))
            t2.start()
            for t in (t0, t1, t2):
                t.join(timeout=60)
        assert [r[0] for r in results] == [200, 200, 200]
        assert results[1][1]["coalesced"] == 2  # the queued pair merged
        eng = server.engine
        edges = set(
            zip(np.asarray(eng.snapshot["src"]).tolist(),
                np.asarray(eng.snapshot["dst"]).tolist())
        )
        assert (v, 1) in edges      # the surviving insert
        assert (v, 0) not in edges  # deleted by the later queued batch
    finally:
        server.stop()
    assert validate_records(sink.records) == []


def test_coalesce_single_and_empty():
    with pytest.raises(ValueError):
        coalesce_deltas([], np.empty(0), np.empty(0))
    d = EdgeDelta.from_pairs(insert=[(1, 2)])
    merged, info = coalesce_deltas([d], np.empty(0), np.empty(0))
    assert info["batches"] == 1 and merged.num_inserts == 1


@pytest.mark.parametrize("weighted", [False, True], ids=["unweighted", "weighted"])
def test_coalesce_insert_only_fast_path_parity(weighted):
    """The no-deletes fast path (pure concatenation) must keep the same
    order-exact contract as the cancellation walk — and the same
    info shape."""
    rng = np.random.default_rng(4)
    src = rng.integers(0, 10, 50).astype(np.int32)
    dst = rng.integers(0, 10, 50).astype(np.int32)
    batches = []
    for i in range(4):
        ins = rng.integers(0, 12, size=(5, 2))
        rows = (
            [(int(a), int(b), float(i + 1)) for a, b in ins] if weighted
            else [(int(a), int(b)) for a, b in ins]
        )
        batches.append(EdgeDelta.from_pairs(insert=rows))
    s, d, v = src, dst, 10
    wseq = np.ones(50, np.float32) if weighted else None
    for b in batches:
        clean, _ = validate_delta(b, v)
        if weighted:
            s, d, wseq, v, _ = splice_edges(s, d, v, clean, weights=wseq)
        else:
            s, d, v, _ = splice_edges(s, d, v, clean)
    cleans = [validate_delta(b, 10)[0] for b in batches]
    merged, info = coalesce_deltas(cleans, src, dst)
    assert info["deletes"] == 0 and info["rows_in"] == info["rows_out"] == 20
    if weighted:
        s2, d2, w2, v2, _ = splice_edges(
            src, dst, 10, merged, weights=np.ones(50, np.float32)
        )
        np.testing.assert_array_equal(wseq, w2)
    else:
        s2, d2, v2, _ = splice_edges(src, dst, 10, merged)
    assert v == v2
    np.testing.assert_array_equal(s, s2)
    np.testing.assert_array_equal(d, d2)


def test_weighted_delta_against_unweighted_server_400s_alone(tmp_path):
    """A weighted delta against an unweighted snapshot is refused at the
    front door (400) BEFORE it can queue — merged into a coalesced
    group, its splice-time failure would take every innocent batch in
    the group down with it."""
    store, *_ = _publish_base(tmp_path)
    server = SnapshotServer(store)
    host, port = server.start()
    try:
        code, body, _ = _post(
            host, port, "/delta", {"insert": [[0, 13, 2.5]]}
        )
        assert code == 400 and "unweighted" in body["error"]
        # the server is untouched: a normal delta still lands
        code, out, _ = _post(host, port, "/delta", {"insert": [[0, 13]]})
        assert code == 200 and out["version"] == 2
        assert server.debt.snapshot()["pending_rows"] == 0
    finally:
        server.stop()


# ---- LOF defer rung -------------------------------------------------------


def test_defer_skips_lof_and_next_refresh_clears(tmp_path):
    """A deferred apply publishes lof_stale with labels still verified;
    the next refresh apply re-scores the backlog and clears the flag."""
    sink = _sink()
    store, src, dst, v = _publish_base(tmp_path, sink=sink)
    ing = DeltaIngestor(store, sink=sink, lof_k=4, check_samples=16)
    snap = ing.apply(
        EdgeDelta.from_pairs(insert=[(40, 12), (40, 13)]), lof_mode="defer"
    )
    assert snap.meta.get("lof_stale") is True
    assert len(snap["lof"]) == len(snap["labels"]) == 41  # padded for growth
    rec = [r for r in sink.records if r["phase"] == "delta_apply"][-1]
    assert rec["lof_mode"] == "defer" and rec["lof_stale"] is True
    # labels still rode the exact-check gate
    g2 = build_graph(snap["src"], snap["dst"], num_vertices=41)
    ok, _ = sampled_exact_check(
        g2, snap["labels"], np.arange(41), kind="lpa"
    )
    assert ok
    snap2 = ing.apply(EdgeDelta.from_pairs(insert=[(40, 14)]))
    assert not snap2.meta.get("lof_stale", False)
    rec2 = [r for r in sink.records if r["phase"] == "delta_apply"][-1]
    assert rec2["lof_mode"] == "refresh" and rec2["lof_stale"] is False
    assert validate_records(sink.records) == []


def test_stale_loaded_snapshot_recovers_on_refresh(tmp_path):
    """An ingestor (re)started on an already-stale snapshot has no
    backlog list; its first refresh apply re-scores everything and
    publishes fresh."""
    store, src, dst, v = _publish_base(tmp_path)
    ing = DeltaIngestor(store, lof_k=4, check_samples=16)
    ing.apply(EdgeDelta.from_pairs(insert=[(0, 13)]), lof_mode="defer")
    ing2 = DeltaIngestor(store, lof_k=4, check_samples=16)  # restart
    snap = ing2.apply(EdgeDelta.from_pairs(insert=[(0, 26)]))
    assert not snap.meta.get("lof_stale", False)


def test_server_serves_staleness_flag(tmp_path):
    """defer_frac=0 arms the defer rung permanently: delta responses,
    /healthz, /vertex and /query all carry the staleness flag."""
    store, *_ = _publish_base(tmp_path)
    server = SnapshotServer(store, admission=AdmissionController(
        bounds=AdmissionBounds(defer_frac=0.0)
    ))
    host, port = server.start()
    try:
        code, out, _ = _post(host, port, "/delta", {"insert": [[0, 13]]})
        assert code == 200 and out["lof_stale"] is True
        assert _get(host, port, "/healthz")["lof_stale"] is True
        assert _get(host, port, "/vertex?v=0")["lof_stale"] is True
        code, out, _ = _post(host, port, "/query", {"vertices": [0, 1]})
        assert out["lof_stale"] is True
        assert _get(host, port, "/statusz")["admission"]["lof_deferred"] >= 1
    finally:
        server.stop()


# ---- deadline shedding ----------------------------------------------------


def test_deadline_shed_while_queued(tmp_path):
    """A batch still queued when its deadline passes is shed with the
    structured 503 — and its debt entry drains (no phantom backlog)."""
    sink = _sink()
    store, *_ = _publish_base(tmp_path, sink=sink)
    server = SnapshotServer(store, sink=sink, admission=AdmissionController(
        bounds=AdmissionBounds(deadline_s=0.6), sink=sink,
    ))
    host, port = server.start()
    inj = faults.FaultInjector()
    inj.add("delta_repair", faults.slow_repair(1.5), at=1, repeat=1)
    results = []

    def fire(payload):
        results.append(_post(host, port, "/delta", payload))

    try:
        with inj.installed():
            t1 = threading.Thread(target=fire, args=({"insert": [[0, 13]]},))
            t1.start()
            time.sleep(0.3)  # the slow apply is in flight
            t2 = threading.Thread(target=fire, args=({"insert": [[0, 26]]},))
            t2.start()
            t1.join(timeout=60)
            t2.join(timeout=60)
        codes = sorted(r[0] for r in results)
        assert codes == [200, 503], codes
        shed = next(r for r in results if r[0] == 503)
        assert shed[1]["verdict"] == "shed" and "deadline" in shed[1]["reason"]
        assert int(shed[2]["Retry-After"]) >= 1
        sheds = [r for r in sink.records if r["phase"] == "delta_shed"]
        assert len(sheds) == 1 and sheds[0]["stage"] == "deadline"
        assert server.debt.snapshot()["pending_rows"] == 0
        assert server.debt.snapshot()["sheds_total"] == 1
    finally:
        server.stop()
    assert validate_records(sink.records) == []


# ---- THE chaos acceptance test --------------------------------------------


def test_overload_chaos_burst_with_slow_repair(tmp_path):
    """ISSUE 8 acceptance: injected burst + slowed repair → deltas
    coalesce, repair_debt_rows never exceeds the bound, at least one
    structured shed, no crash, and every served label state passes the
    sampled exact check. Deterministic on CPU: the burst is staged so
    the first batch is mid-apply before the rest arrive."""
    sink = _sink()
    store, src, dst, v = _publish_base(tmp_path, sink=sink)
    bounds = AdmissionBounds(
        max_pending_rows=400, max_queue_depth=3, deadline_s=30.0,
        defer_frac=0.5,
    )
    server = SnapshotServer(store, sink=sink, admission=AdmissionController(
        bounds=bounds, sink=sink,
    ))
    host, port = server.start()
    inj = faults.FaultInjector()
    inj.add("delta_repair", faults.slow_repair(0.7), at=1, repeat=100)
    results, debt_seen, hammer_errors, versions = [], [], [], set()
    stop = threading.Event()

    def sampler():
        while not stop.is_set():
            h = _get(host, port, "/healthz")
            debt_seen.append(h["repair_debt_rows"])
            time.sleep(0.02)

    def hammer():
        while not stop.is_set():
            try:
                code, out, _ = _post(
                    host, port, "/query", {"vertices": [0, 13, 27]}
                )
                if code != 200 or len(out["label"]) != 3:
                    raise AssertionError(f"bad query reply: {code} {out}")
                versions.add(out["version"])
            except Exception as e:  # noqa: BLE001 — collect, assert later
                hammer_errors.append(e)

    def fire(payload):
        results.append(_post(host, port, "/delta", payload))

    bursts = faults.delta_burst(
        v, batches=10, rows_per_batch=24, seed=3, delete_frac=0.25,
        base_src=src, base_dst=dst,
    )
    threads = []
    try:
        with inj.installed():
            smp = threading.Thread(target=sampler)
            hmr = [threading.Thread(target=hammer) for _ in range(3)]
            smp.start()
            for t in hmr:
                t.start()
            t0 = threading.Thread(target=fire, args=(bursts[0],))
            t0.start()
            threads.append(t0)
            time.sleep(0.25)  # batch 0 is mid-apply (slow_repair holds it)
            for payload in bursts[1:]:
                t = threading.Thread(target=fire, args=(payload,))
                t.start()
                threads.append(t)
                time.sleep(0.01)
            for t in threads:
                t.join(timeout=180)
            stop.set()
            smp.join(timeout=30)
            for t in hmr:
                t.join(timeout=30)

        assert len(results) == 10  # no crash: every request was answered
        oks = [r for r in results if r[0] == 200]
        sheds = [r for r in results if r[0] == 503]
        assert {r[0] for r in results} <= {200, 503}
        # (1) coalescing happened: queued batches merged into one publish
        assert any(r[1].get("coalesced", 1) > 1 for r in oks)
        assert any(
            r["phase"] == "delta_coalesce" and r["batches"] > 1
            for r in sink.records
        )
        # (2) debt stayed inside the bound, the whole time
        assert debt_seen and max(debt_seen) <= bounds.max_pending_rows
        # (3) at least one STRUCTURED shed: 503 + Retry-After + record
        assert sheds
        for code, body, headers in sheds:
            assert body["verdict"] == "shed" and body["reason"]
            assert int(headers["Retry-After"]) >= 1
        assert any(r["phase"] == "delta_shed" for r in sink.records)
        # (4) live readers never dropped or saw a torn version
        assert hammer_errors == []
        assert versions and len(versions) <= 1 + len(oks)
        # (5) the served labels are a state the exact operator accepts
        eng = server.engine
        g_now = build_graph(
            np.asarray(eng.snapshot["src"]), np.asarray(eng.snapshot["dst"]),
            num_vertices=eng.num_vertices,
        )
        ok_l, bad = sampled_exact_check(
            g_now, eng.labels, np.arange(eng.num_vertices), kind="lpa"
        )
        assert ok_l, f"{bad} label disagreements in served state"
        ok_c, bad_c = sampled_exact_check(
            g_now, eng.cc_labels, np.arange(eng.num_vertices), kind="cc"
        )
        assert ok_c, f"{bad_c} cc disagreements in served state"
        # (6) the ledger settled: accepted work drained, sheds accounted
        debt = server.debt.snapshot()
        assert debt["pending_rows"] == 0
        assert debt["sheds_total"] == len(sheds)
    finally:
        stop.set()
        server.stop()
    assert validate_records(sink.records) == []
    # (7) the offline report renders the admission timeline
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
    import obs_report

    report = obs_report.build_report(sink.records)
    assert "admission timeline" in report
    assert "shed" in report and "coalesce" in report


def test_slow_client_does_not_stall_other_requests(tmp_path):
    """The slow-client injector: one socket dribbling its POST body must
    not block other handlers (ThreadingHTTPServer's per-connection
    threads are the isolation; this pins it under the new write path)."""
    store, *_ = _publish_base(tmp_path)
    server = SnapshotServer(store)
    host, port = server.start()
    done = {}

    def slow():
        done["slow"] = faults.slow_client_post(
            host, port, "/delta",
            {"insert": [[0, 13], [0, 14], [12, 26]]},
            chunk_bytes=4, delay_s=0.03,
        )

    try:
        t = threading.Thread(target=slow)
        t.start()
        t0 = time.perf_counter()
        fast = _get(host, port, "/healthz")
        fast_s = time.perf_counter() - t0
        assert fast["ok"] and fast_s < 1.0  # not serialized behind the dribble
        t.join(timeout=60)
        status, body = done["slow"]
        assert status == 200 and body["version"] == 2
    finally:
        server.stop()
