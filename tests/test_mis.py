"""Luby MIS and repeated-MIS coloring: property-tested (independence,
maximality, proper coloring) — the correctness criteria are exact even
though the algorithms are randomized."""

import numpy as np
import pytest

from graphmine_tpu.graph.container import build_graph
from graphmine_tpu.ops.mis import greedy_color, maximal_independent_set


def random_graph(v=80, e=400, seed=0):
    rng = np.random.default_rng(seed)
    src = rng.integers(0, v, e).astype(np.int32)
    dst = rng.integers(0, v, e).astype(np.int32)
    keep = src != dst
    return src[keep], dst[keep], v


def undirected_pairs(src, dst):
    return set(map(tuple, np.stack([np.minimum(src, dst),
                                    np.maximum(src, dst)], 1).tolist()))


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_mis_is_independent_and_maximal(seed):
    src, dst, v = random_graph(seed=seed)
    g = build_graph(src, dst, num_vertices=v)
    mis = np.asarray(maximal_independent_set(g, seed=seed))
    # independence: no edge inside the set
    assert not (mis[src] & mis[dst]).any()
    # maximality: every outsider has a member neighbor
    nbr_in = np.zeros(v, dtype=bool)
    np.logical_or.at(nbr_in, src, mis[dst])
    np.logical_or.at(nbr_in, dst, mis[src])
    assert (mis | nbr_in).all()


def test_mis_deterministic_and_isolated_vertices_join():
    src, dst, v = random_graph(seed=4)
    g = build_graph(src, dst, num_vertices=v + 5)  # 5 isolated vertices
    a = np.asarray(maximal_independent_set(g, seed=7))
    b = np.asarray(maximal_independent_set(g, seed=7))
    np.testing.assert_array_equal(a, b)
    assert a[v:].all()  # isolated vertices always belong
    assert np.asarray(maximal_independent_set(g, seed=8)).shape == a.shape


def test_greedy_color_is_proper_and_complete():
    src, dst, v = random_graph(v=120, e=700, seed=5)
    g = build_graph(src, dst, num_vertices=v)
    colors = np.asarray(greedy_color(g, seed=5))
    assert (colors >= 0).all()
    assert not (colors[src] == colors[dst]).any()  # proper
    # color count is sane: at most max-degree + 1
    deg = np.bincount(np.concatenate([src, dst]), minlength=v)
    assert colors.max() <= deg.max()


def test_self_loops_ignored():
    # triangle plus a self-loop on vertex 0: MIS stays maximal, coloring
    # stays complete and proper on the non-loop edges
    src = np.array([0, 1, 2, 0], np.int32)
    dst = np.array([1, 2, 0, 0], np.int32)
    g = build_graph(src, dst, num_vertices=3)
    mis = np.asarray(maximal_independent_set(g, seed=0))
    assert mis.sum() == 1  # triangle: exactly one member
    colors = np.asarray(greedy_color(g, seed=0))
    assert (colors >= 0).all()
    real = src != dst
    assert not (colors[src[real]] == colors[dst[real]]).any()


def test_greedy_color_cap_leaves_sentinel():
    # triangle needs 3 colors; cap at 2 -> one vertex keeps the documented
    # -1 sentinel
    g = build_graph(np.array([0, 1, 2], np.int32), np.array([1, 2, 0], np.int32),
                    num_vertices=3)
    colors = np.asarray(greedy_color(g, seed=0, max_colors=2))
    assert (colors == -1).sum() == 1


def test_mis_requires_symmetric():
    src, dst, v = random_graph()
    g = build_graph(src, dst, num_vertices=v, symmetric=False)
    with pytest.raises(ValueError, match="symmetric"):
        maximal_independent_set(g)
    with pytest.raises(ValueError, match="symmetric"):
        greedy_color(g)


def test_frame_methods():
    from graphmine_tpu.frames import GraphFrame

    src, dst, v = random_graph(seed=6)
    gf = GraphFrame((src, dst))
    mis = np.asarray(gf.maximal_independent_set())
    colors = np.asarray(gf.greedy_color())
    assert mis.dtype == bool and colors.min() >= 0
