"""Golden-result ingestion tests on the bundled CommonCrawl parquet.

Fixture numbers measured from the reference data (BASELINE.md): 18,399 raw
rows, 18,398 after the null-domain filter, 4,613 distinct domain vertices,
7,742 distinct directed edges, 0 self-loops, max undirected degree 1,223.
"""

import numpy as np

from graphmine_tpu.io.factorize import factorize
from graphmine_tpu.io.edges import from_arrays


def test_bundled_golden_counts(bundled_edges):
    et = bundled_edges
    assert et.num_rows_raw == 18399
    assert et.num_edges == 18398
    assert et.num_vertices == 4613
    assert len(et.distinct_edges()) == 7742
    assert np.sum(et.src == et.dst) == 0  # no self-loops


def test_bundled_degree_stats(bundled_graph):
    deg = np.asarray(bundled_graph.degrees())
    assert deg.max() == 1223  # measured max undirected degree (BASELINE.md)
    assert bundled_graph.num_messages == 2 * 18398


def test_factorize_dense_and_stable():
    a = np.array(["b.com", "a.com", "b.com"])
    b = np.array(["c.com", "a.com", "b.com"])
    (ca, cb), uniq = factorize(a, b)
    assert list(uniq) == ["b.com", "a.com", "c.com"]  # first-appearance order
    assert ca.tolist() == [0, 1, 0] and cb.tolist() == [2, 1, 0]
    assert ca.dtype == np.int32


def test_null_filter():
    from graphmine_tpu.io.edges import _from_string_columns

    parent = np.array(["a", None, "b"], dtype=object)
    child = np.array(["b", "c", None], dtype=object)
    et = _from_string_columns(parent, child, 3)
    assert et.num_edges == 1 and et.num_rows_raw == 3


def test_from_arrays_roundtrip():
    et = from_arrays([0, 1, 1], [1, 2, 2])
    assert et.num_vertices == 3
    assert len(et.distinct_edges()) == 2  # duplicates kept in src/dst, deduped here


def test_streaming_parquet_matches_bulk():
    """Batched ingestion (the reference's abandoned 'data slicer' done
    right): identical graph as the bulk path — names, name-keyed edges,
    null filter, duplicates — under a batch size far below the row count."""
    import os

    import pytest

    from graphmine_tpu.io.edges import load_parquet_edges
    from tests.conftest import REFERENCE_PARQUET

    if not os.path.exists(REFERENCE_PARQUET):
        pytest.skip("bundled reference parquet not available")
    bulk = load_parquet_edges(REFERENCE_PARQUET)
    stream = load_parquet_edges(REFERENCE_PARQUET, batch_rows=1000)
    assert stream.num_rows_raw == bulk.num_rows_raw == 18399
    assert stream.num_edges == bulk.num_edges == 18398
    assert stream.num_vertices == bulk.num_vertices == 4613
    assert set(stream.names.tolist()) == set(bulk.names.tolist())
    bulk_edges = set(zip(bulk.names[bulk.src], bulk.names[bulk.dst]))
    stream_edges = set(zip(stream.names[stream.src], stream.names[stream.dst]))
    assert stream_edges == bulk_edges
    # duplicate multiplicity preserved too (multiset equality by name)
    import collections
    bc = collections.Counter(zip(bulk.names[bulk.src], bulk.names[bulk.dst]))
    sc = collections.Counter(zip(stream.names[stream.src], stream.names[stream.dst]))
    assert bc == sc

    import pytest
    with pytest.raises(ValueError, match="positive"):
        load_parquet_edges(REFERENCE_PARQUET, batch_rows=0)


def test_weighted_edge_list_loading(tmp_path):
    """r2: 3-column weighted edge lists (`src dst weight`) load via
    weight_col and feed weighted LPA end-to-end."""
    import pytest

    from graphmine_tpu.graph.container import graph_from_edge_table
    from graphmine_tpu.io.edges import load_edge_list
    from graphmine_tpu.ops.lpa import label_propagation

    p = tmp_path / "weighted.txt"
    # vertex c hears a (weight 1) and b (weight 8): b must win the mode
    p.write_text("# comment line\na c 1.0\nb c 8.0\na b 0.5\n")
    et = load_edge_list(str(p), weight_col=2)
    assert et.weights is not None and et.weights.dtype == np.float32
    np.testing.assert_allclose(et.weights, [1.0, 8.0, 0.5])

    g = graph_from_edge_table(et)
    assert g.msg_weight is not None
    labels = np.asarray(label_propagation(g, max_iter=1))
    b, c = [int(np.flatnonzero(et.names == n)[0]) for n in ("b", "c")]
    assert labels[c] == b  # weight 8 beats weight 1

    # unweighted parse of the same file ignores the column
    et_u = load_edge_list(str(p))
    assert et_u.weights is None and et_u.num_edges == 3

    with pytest.raises(ValueError, match="weight_col"):
        load_edge_list(str(p), weight_col=5)
    with pytest.raises(ValueError, match="weight_col"):
        load_edge_list(str(p), weight_col=1)
