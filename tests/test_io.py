"""Golden-result ingestion tests on the bundled CommonCrawl parquet.

Fixture numbers measured from the reference data (BASELINE.md): 18,399 raw
rows, 18,398 after the null-domain filter, 4,613 distinct domain vertices,
7,742 distinct directed edges, 0 self-loops, max undirected degree 1,223.
"""

import numpy as np

from graphmine_tpu.io.factorize import factorize
from graphmine_tpu.io.edges import from_arrays


def test_bundled_golden_counts(bundled_edges):
    et = bundled_edges
    assert et.num_rows_raw == 18399
    assert et.num_edges == 18398
    assert et.num_vertices == 4613
    assert len(et.distinct_edges()) == 7742
    assert np.sum(et.src == et.dst) == 0  # no self-loops


def test_bundled_degree_stats(bundled_graph):
    deg = np.asarray(bundled_graph.degrees())
    assert deg.max() == 1223  # measured max undirected degree (BASELINE.md)
    assert bundled_graph.num_messages == 2 * 18398


def test_factorize_dense_and_stable():
    a = np.array(["b.com", "a.com", "b.com"])
    b = np.array(["c.com", "a.com", "b.com"])
    (ca, cb), uniq = factorize(a, b)
    assert list(uniq) == ["b.com", "a.com", "c.com"]  # first-appearance order
    assert ca.tolist() == [0, 1, 0] and cb.tolist() == [2, 1, 0]
    assert ca.dtype == np.int32


def test_null_filter():
    from graphmine_tpu.io.edges import _from_string_columns

    parent = np.array(["a", None, "b"], dtype=object)
    child = np.array(["b", "c", None], dtype=object)
    et = _from_string_columns(parent, child, 3)
    assert et.num_edges == 1 and et.num_rows_raw == 3


def test_from_arrays_roundtrip():
    et = from_arrays([0, 1, 1], [1, 2, 2])
    assert et.num_vertices == 3
    assert len(et.distinct_edges()) == 2  # duplicates kept in src/dst, deduped here


def test_streaming_parquet_matches_bulk():
    """Batched ingestion (the reference's abandoned 'data slicer' done
    right): identical graph as the bulk path — names, name-keyed edges,
    null filter, duplicates — under a batch size far below the row count."""
    import os

    import pytest

    from graphmine_tpu.io.edges import load_parquet_edges
    from tests.conftest import REFERENCE_PARQUET

    if not os.path.exists(REFERENCE_PARQUET):
        pytest.skip("bundled reference parquet not available")
    bulk = load_parquet_edges(REFERENCE_PARQUET)
    stream = load_parquet_edges(REFERENCE_PARQUET, batch_rows=1000)
    assert stream.num_rows_raw == bulk.num_rows_raw == 18399
    assert stream.num_edges == bulk.num_edges == 18398
    assert stream.num_vertices == bulk.num_vertices == 4613
    assert set(stream.names.tolist()) == set(bulk.names.tolist())
    bulk_edges = set(zip(bulk.names[bulk.src], bulk.names[bulk.dst]))
    stream_edges = set(zip(stream.names[stream.src], stream.names[stream.dst]))
    assert stream_edges == bulk_edges
    # duplicate multiplicity preserved too (multiset equality by name)
    import collections
    bc = collections.Counter(zip(bulk.names[bulk.src], bulk.names[bulk.dst]))
    sc = collections.Counter(zip(stream.names[stream.src], stream.names[stream.dst]))
    assert bc == sc

    import pytest
    with pytest.raises(ValueError, match="positive"):
        load_parquet_edges(REFERENCE_PARQUET, batch_rows=0)


def test_dictionary_fast_path_byte_identical_to_string_path():
    """r5 ingest fast path: parquet string columns are PLAIN_DICTIONARY
    on disk (the reference's own Spark output is), and interning the
    dictionary VALUES + remapping int32 indices replaced per-row Python
    strings (measured 84 s -> 14 s at 25M rows). Id assignment must be
    BYTE-identical to the per-row string path — LPA tie-breaks read the
    ids, so 'same names, different codes' would silently change pinned
    partitions."""
    import glob
    import os

    import pyarrow as pa
    import pytest
    import pyarrow.compute as pc
    import pyarrow.parquet as pq

    from graphmine_tpu.io.edges import load_parquet_edges
    from graphmine_tpu.io.factorize import factorize
    from tests.conftest import REFERENCE_PARQUET

    if not os.path.exists(REFERENCE_PARQUET):
        pytest.skip("bundled reference parquet not available")
    # the pre-r5 string path, reproduced verbatim
    paths = sorted(glob.glob(os.path.join(REFERENCE_PARQUET, "*.parquet")))
    table = pa.concat_tables(
        [pq.read_table(p, columns=["_c1", "_c2"]) for p in paths]
    )
    valid = pc.and_(
        pc.is_valid(table.column("_c1")), pc.is_valid(table.column("_c2"))
    )
    table = table.filter(valid)
    (src0, dst0), names0 = factorize(
        table.column("_c1").to_numpy(zero_copy_only=False),
        table.column("_c2").to_numpy(zero_copy_only=False),
    )
    et = load_parquet_edges(REFERENCE_PARQUET)
    np.testing.assert_array_equal(et.src, src0)
    np.testing.assert_array_equal(et.dst, dst0)
    np.testing.assert_array_equal(et.names.astype(str), names0.astype(str))


def test_parquet_plain_encoding_fallback(tmp_path):
    """Non-dictionary parquet storage takes the per-row string fallback in
    ``_column_codes`` — same table either way (with nulls filtered)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from graphmine_tpu.io.edges import load_parquet_edges

    rows_a = ["x.com", "y.com", None, "x.com", "z.com"]
    rows_b = ["y.com", "z.com", "x.com", None, "y.com"]
    p = tmp_path / "plain.parquet"
    pq.write_table(
        pa.table({"_c1": pa.array(rows_a), "_c2": pa.array(rows_b)}),
        p, use_dictionary=False,
    )
    et = load_parquet_edges(str(p))
    ets = load_parquet_edges(str(p), batch_rows=2)
    assert et.num_rows_raw == 5 and et.num_edges == 3  # two null rows drop
    pairs = sorted(zip(et.names[et.src], et.names[et.dst]))
    assert pairs == [("x.com", "y.com"), ("y.com", "z.com"), ("z.com", "y.com")]
    assert sorted(zip(ets.names[ets.src], ets.names[ets.dst])) == pairs

    # all rows null: filters to a 0-chunk column — must yield an EMPTY
    # table, not crash in np.concatenate (code-review r5)
    p2 = tmp_path / "allnull.parquet"
    pq.write_table(
        pa.table({"_c1": pa.array([None, None], pa.string()),
                  "_c2": pa.array(["a", "b"])}), p2,
    )
    empty = load_parquet_edges(str(p2))
    assert empty.num_rows_raw == 2 and empty.num_edges == 0
    assert empty.num_vertices == 0


def test_weighted_edge_list_loading(tmp_path):
    """r2: 3-column weighted edge lists (`src dst weight`) load via
    weight_col and feed weighted LPA end-to-end."""
    import pytest

    from graphmine_tpu.graph.container import graph_from_edge_table
    from graphmine_tpu.io.edges import load_edge_list
    from graphmine_tpu.ops.lpa import label_propagation

    p = tmp_path / "weighted.txt"
    # vertex c hears a (weight 1) and b (weight 8): b must win the mode
    p.write_text("# comment line\na c 1.0\nb c 8.0\na b 0.5\n")
    et = load_edge_list(str(p), weight_col=2)
    assert et.weights is not None and et.weights.dtype == np.float32
    np.testing.assert_allclose(et.weights, [1.0, 8.0, 0.5])

    g = graph_from_edge_table(et)
    assert g.msg_weight is not None
    labels = np.asarray(label_propagation(g, max_iter=1))
    b, c = [int(np.flatnonzero(et.names == n)[0]) for n in ("b", "c")]
    assert labels[c] == b  # weight 8 beats weight 1

    # unweighted parse of the same file ignores the column
    et_u = load_edge_list(str(p))
    assert et_u.weights is None and et_u.num_edges == 3

    with pytest.raises(ValueError, match="weight_col"):
        load_edge_list(str(p), weight_col=5)
    with pytest.raises(ValueError, match="weight_col"):
        load_edge_list(str(p), weight_col=1)


def _write_edgelist(tmp_path, name, lines):
    p = tmp_path / name
    p.write_bytes(b"\n".join(lines))
    return str(p)


def _assert_same_named_edges(got, want, weights=False):
    """Raw vertex ids legitimately differ across ingestion paths (interning
    order is row-major native vs column-major factorize — documented in
    load_parquet_edges); the invariant is the NAME-keyed edge sequence
    (with multiplicity and order) and the name set."""
    assert sorted(got.names) == sorted(want.names)
    g = list(zip(got.names[got.src], got.names[got.dst]))
    w = list(zip(want.names[want.src], want.names[want.dst]))
    assert g == w
    if weights:
        np.testing.assert_allclose(got.weights, want.weights)


def test_chunked_native_matches_bulk(tmp_path):
    """r3 streaming ingestion: the chunked native parse (tiny chunks, so
    boundaries land mid-line) produces identical ids/names/weights to the
    bulk NumPy path — unweighted and weighted."""
    import pytest

    from graphmine_tpu.io import native
    from graphmine_tpu.io.edges import load_edge_list

    if not native.chunked_parse_available():
        pytest.skip("native chunk parser not built")

    rng = np.random.default_rng(9)
    lines = [b"# header comment"]
    for i in range(500):
        a, b = rng.integers(0, 60, 2)
        lines.append(f"n{a} n{b} {rng.integers(1, 16) / 4.0}".encode())
    lines.append(b"")  # trailing newline
    p = _write_edgelist(tmp_path, "g.txt", lines)

    bulk = load_edge_list(p, use_native=False, weight_col=2)
    for chunk in (7, 64, 1 << 20):  # mid-line, few-line, single-chunk
        et = native.load_edge_list_chunked(p, weight_col=2, chunk_bytes=chunk)
        assert et is not None
        _assert_same_named_edges(et, bulk, weights=True)

    # unweighted: same endpoints, no weights array
    et_u = native.load_edge_list_chunked(p, chunk_bytes=13)
    _assert_same_named_edges(et_u, bulk, weights=False)
    assert et_u.weights is None


def test_chunked_numpy_fallback_matches_bulk(tmp_path):
    """The no-native chunked fallback (use_native=False + chunk_bytes)
    gives the same table under bounded memory."""
    from graphmine_tpu.io.edges import load_edge_list

    lines = [b"# c"] + [
        f"v{i % 37} v{(i * 7) % 41} {i % 5}.5".encode() for i in range(300)
    ]
    p = _write_edgelist(tmp_path, "g2.txt", lines)
    bulk = load_edge_list(p, use_native=False, weight_col=2)
    chunked = load_edge_list(p, use_native=False, weight_col=2, chunk_bytes=11)
    _assert_same_named_edges(chunked, bulk, weights=True)
    assert chunked.num_rows_raw == bulk.num_rows_raw


def test_chunked_edge_cases(tmp_path):
    """CRLF, blank lines, missing trailing newline, comment mid-file,
    malformed weight -> hard error on both streaming paths."""
    import pytest

    from graphmine_tpu.io import native
    from graphmine_tpu.io.edges import load_edge_list

    p = tmp_path / "edge.txt"
    p.write_bytes(b"a b 1.0\r\n\r\n# mid comment\nc d 2.0")  # no final \n
    for kw in (dict(use_native=False, chunk_bytes=5), dict()):
        et = load_edge_list(str(p), weight_col=2, **kw)
        assert et.num_edges == 2
        # interning ORDER differs across paths (row-major native vs
        # column-major factorize) — compare name-keyed structure
        assert sorted(et.names) == ["a", "b", "c", "d"]
        named = list(zip(et.names[et.src], et.names[et.dst]))
        assert named == [("a", "b"), ("c", "d")]
        np.testing.assert_allclose(et.weights, [1.0, 2.0])

    bad = tmp_path / "bad.txt"
    bad.write_bytes(b"a b 1.0\nc d notafloat\n")
    with pytest.raises(ValueError):
        load_edge_list(str(bad), weight_col=2)
    if native.chunked_parse_available():
        with pytest.raises(ValueError, match="weight_col"):
            native.load_edge_list_chunked(str(bad), weight_col=2)


def test_short_line_is_hard_error_on_every_path(tmp_path):
    """ADVICE r3: a non-comment data line with < 2 tokens must be a hard
    ValueError on EVERY ingestion path — which inputs parse must not
    depend on whether the .so is built (the native chunk parser used to
    silently drop such lines while the NumPy fallback raised)."""
    import pytest

    from graphmine_tpu.io import native
    from graphmine_tpu.io.edges import load_edge_list

    p = tmp_path / "short.txt"
    p.write_bytes(b"a b\nlonely\nc d\n")
    # NumPy bulk + NumPy chunked
    with pytest.raises(ValueError):
        load_edge_list(str(p), use_native=False)
    with pytest.raises(ValueError):
        load_edge_list(str(p), use_native=False, chunk_bytes=4)
    # native chunked + native whole-file
    if native.chunked_parse_available():
        with pytest.raises(ValueError, match=">= 2 columns"):
            native.load_edge_list_chunked(str(p))
        # chunk boundaries must not change the verdict
        with pytest.raises(ValueError, match=">= 2 columns"):
            native.load_edge_list_chunked(str(p), chunk_bytes=3)
    if native.available():
        with pytest.raises(ValueError, match=">= 2 columns"):
            native.load_edge_list_native(str(p))


def test_inline_comment_parity_across_paths(tmp_path):
    """np.loadtxt treats the comment char ANYWHERE in a line as starting a
    comment — the native parsers must too (code-review r4 finding:
    'a b # note' parsed to different graphs, and 'c # note' to different
    verdicts, depending on whether the .so was built)."""
    import pytest

    from graphmine_tpu.io import native
    from graphmine_tpu.io.edges import load_edge_list

    ok = tmp_path / "trail.txt"
    ok.write_bytes(b"a b # note\nc d\n")
    bulk = load_edge_list(str(ok), use_native=False)
    assert bulk.num_edges == 2 and sorted(bulk.names) == ["a", "b", "c", "d"]
    for kw in (dict(), dict(use_native=False, chunk_bytes=4)):
        et = load_edge_list(str(ok), **kw)
        named = sorted(zip(et.names[et.src], et.names[et.dst]))
        assert named == [("a", "b"), ("c", "d")], kw

    bad = tmp_path / "inline.txt"
    bad.write_bytes(b"a b\nc # note\n")  # strips to a 1-token line
    with pytest.raises(ValueError):
        load_edge_list(str(bad), use_native=False)
    with pytest.raises(ValueError):
        load_edge_list(str(bad), use_native=False, chunk_bytes=4)
    if native.chunked_parse_available():
        with pytest.raises(ValueError, match=">= 2 columns"):
            native.load_edge_list_chunked(str(bad))
    if native.available():
        with pytest.raises(ValueError, match=">= 2 columns"):
            native.load_edge_list_native(str(bad))


def test_empty_vocab_names_dtype_matches_across_paths(tmp_path):
    """ADVICE r3: a comment-only file yields the same (object-dtype) empty
    names array on every path — the native chunked path used to produce a
    float64 empty array."""
    from graphmine_tpu.io import native
    from graphmine_tpu.io.edges import load_edge_list

    p = tmp_path / "comments.txt"
    p.write_bytes(b"# only\n# comments\n")
    bulk = load_edge_list(str(p), use_native=False)
    assert bulk.num_edges == 0 and bulk.names.dtype == object
    chunked_np = load_edge_list(str(p), use_native=False, chunk_bytes=5)
    assert chunked_np.names.dtype == bulk.names.dtype
    if native.chunked_parse_available():
        et = native.load_edge_list_chunked(str(p))
        assert et.num_edges == 0
        assert et.names.dtype == bulk.names.dtype
    if native.available():
        # the whole-file native path (stale-.so fallback) too (review r4)
        et = native.load_edge_list_native(str(p))
        assert et.num_edges == 0
        assert et.names.dtype == bulk.names.dtype


def test_ragged_columns_rejected_on_every_path(tmp_path):
    """np.loadtxt rejects files whose data lines change column count; the
    native parsers and the NumPy chunked path (across chunk boundaries,
    where per-chunk loadtxt can't see the change) must give the same
    verdict (code-review r4 finding: 'a b c\\nd e # note' parsed natively
    but raised in every NumPy path)."""
    import pytest

    from graphmine_tpu.io import native
    from graphmine_tpu.io.edges import load_edge_list

    p = tmp_path / "ragged.txt"
    p.write_bytes(b"a b c\nd e # note\n")
    with pytest.raises(ValueError):
        load_edge_list(str(p), use_native=False)
    # chunk split isolates each line in its own (rectangular) chunk —
    # the cross-chunk ncols tracking must still reject
    with pytest.raises(ValueError, match="columns changed"):
        load_edge_list(str(p), use_native=False, chunk_bytes=6)
    if native.chunked_parse_available():
        for cb in (6, 1 << 20):
            with pytest.raises(ValueError, match="columns changed"):
                native.load_edge_list_chunked(str(p), chunk_bytes=cb)
    if native.available():
        with pytest.raises(ValueError, match="columns changed"):
            native.load_edge_list_native(str(p))

    # uniform extra columns stay accepted everywhere (loadtxt semantics:
    # rectangular 3-column unweighted files parse; col 2 is ignored)
    ok = tmp_path / "threecol.txt"
    ok.write_bytes(b"a b 9\nc d 8\n")
    for kw in (dict(), dict(use_native=False),
               dict(use_native=False, chunk_bytes=6)):
        et = load_edge_list(str(ok), **kw)
        named = sorted(zip(et.names[et.src], et.names[et.dst]))
        assert named == [("a", "b"), ("c", "d")], kw


def test_ingestion_paths_fuzz_agreement(tmp_path):
    """Property fuzz over the three edge-list ingestion paths (bulk NumPy,
    chunked NumPy, chunked native): random content — random whitespace
    runs, CRLF mixes, comments, blank lines, missing final newline,
    string and integer ids, weighted and not, including comment-only
    files (empty table on every path) — must produce the same name-keyed
    edge multiset in the same order, for adversarial chunk sizes that
    split lines anywhere."""
    from graphmine_tpu.io import native
    from graphmine_tpu.io.edges import load_edge_list

    rng = np.random.default_rng(123)
    for trial in range(8):
        weighted = bool(trial % 2)
        n = int(rng.integers(1, 120))
        lines = []
        for _ in range(n):
            if rng.random() < 0.1:
                lines.append(b"# comment " + str(rng.integers(99)).encode())
                continue
            if rng.random() < 0.1:
                lines.append(b"" if rng.random() < 0.5 else b"   \t ")
                continue
            a = (f"v{rng.integers(20)}" if rng.random() < 0.5
                 else str(rng.integers(50)))
            b = (f"n{rng.integers(20)}" if rng.random() < 0.5
                 else str(rng.integers(50)))
            sep = b" " if rng.random() < 0.5 else b"\t  "
            line = a.encode() + sep + b.encode()
            if weighted:
                line += sep + str(rng.integers(1, 32) / 4.0).encode()
            if rng.random() < 0.15:
                # trailing inline comment: loadtxt strips it; the native
                # parsers must too (code-review r4 finding)
                line += b" # trail " + str(rng.integers(99)).encode()
            lines.append(line)
        eol = b"\r\n" if rng.random() < 0.3 else b"\n"
        body = eol.join(lines)
        if rng.random() < 0.5:
            body += eol  # sometimes a final newline, sometimes not
        path = str(tmp_path / f"fuzz_{trial}.txt")
        with open(path, "wb") as f:
            f.write(body)

        wc = 2 if weighted else None
        # the generator emits only well-formed data lines, so every path
        # must accept (incl. comment-only files -> empty tables)
        bulk = load_edge_list(path, use_native=False, weight_col=wc)
        chunk = int(rng.integers(3, 40))
        np_chunked = load_edge_list(
            path, use_native=False, weight_col=wc, chunk_bytes=chunk
        )
        _assert_same_named_edges(np_chunked, bulk, weights=weighted)
        if native.chunked_parse_available():
            nat = native.load_edge_list_chunked(
                path, weight_col=wc, chunk_bytes=chunk
            )
            _assert_same_named_edges(nat, bulk, weights=weighted)

        # malformed twin (ADVICE r3): inject a 1-token line at a random
        # position — every path must now reject, at any chunk split
        import pytest

        bad_lines = list(lines)
        bad_lines.insert(int(rng.integers(0, len(bad_lines) + 1)), b"stray")
        bad_path = str(tmp_path / f"fuzz_{trial}_bad.txt")
        with open(bad_path, "wb") as f:
            f.write(eol.join(bad_lines) + eol)
        with pytest.raises(ValueError):
            load_edge_list(bad_path, use_native=False, weight_col=wc)
        with pytest.raises(ValueError):
            load_edge_list(
                bad_path, use_native=False, weight_col=wc, chunk_bytes=chunk
            )
        if native.chunked_parse_available():
            with pytest.raises(ValueError, match=">= 2 columns"):
                native.load_edge_list_chunked(
                    bad_path, weight_col=wc, chunk_bytes=chunk
                )


def test_column_codes_is_null_safe_standalone():
    """ADVICE r5: _column_codes must never intern None as a vertex id —
    nulls are dropped in BOTH the dictionary fast path and the per-row
    fallback, so a caller that forgot the row filter cannot poison the
    vocabulary (the loaders still pre-filter for row alignment)."""
    import pytest

    pa = pytest.importorskip("pyarrow")

    from graphmine_tpu.io.edges import _column_codes
    from graphmine_tpu.io.factorize import IncrementalFactorizer

    # per-row (non-dictionary) path with nulls
    interner = IncrementalFactorizer()
    codes = _column_codes(
        pa.chunked_array([pa.array(["a", None, "b", "a", None])]), interner
    )
    assert codes.tolist() == [0, 1, 0]  # 3 non-null rows, a -> 0, b -> 1
    assert all(isinstance(n, str) for n in interner.names())

    # dictionary-encoded path with nulls takes the fast path post-drop
    dcol = pa.array(["x", None, "y", "x"]).dictionary_encode()
    codes2 = _column_codes(dcol, interner)
    assert len(codes2) == 3
    names = list(interner.names())
    assert names == ["a", "b", "x", "y"] and None not in names
