"""NetworkX interop: conversions + cross-engine oracle checks."""

import numpy as np
import pytest

nx = pytest.importorskip("networkx")

from graphmine_tpu.graph.container import build_graph
from graphmine_tpu.interop import from_networkx, graph_from_networkx, to_networkx
from graphmine_tpu.io.edges import from_arrays
from graphmine_tpu.ops.cc import connected_components
from graphmine_tpu.ops.lpa import label_propagation


def test_roundtrip_preserves_structure():
    src = np.array([0, 1, 2, 0], np.int32)
    dst = np.array([1, 2, 0, 2], np.int32)
    et = from_arrays(src, dst, names=np.array(["a", "b", "c", "iso"]))
    g = to_networkx(et)
    assert g.number_of_nodes() == 4          # isolated vertex kept
    assert g.number_of_edges() == 4
    assert g.nodes[0]["name"] == "a"
    back = from_networkx(g)
    assert back.num_vertices == 4
    assert set(zip(back.src.tolist(), back.dst.tolist())) == set(
        zip(src.tolist(), dst.tolist())
    )
    assert back.names.tolist() == ["a", "b", "c", "iso"]  # names round-trip

    # duplicate edges: default collapses, multigraph preserves multiplicity
    et_dup = from_arrays(np.array([0, 0], np.int32), np.array([1, 1], np.int32))
    assert to_networkx(et_dup).number_of_edges() == 1
    assert to_networkx(et_dup, multigraph=True).number_of_edges() == 2
    assert from_networkx(to_networkx(et_dup, multigraph=True)).num_edges == 2


def test_labels_become_community_attribute():
    et = from_arrays(np.array([0, 1], np.int32), np.array([1, 0], np.int32))
    g = to_networkx(et, labels=np.array([7, 7]))
    assert g.nodes[0]["community"] == 7 and g.nodes[1]["community"] == 7


def test_graph_roundtrip_and_type_errors():
    g = build_graph([0, 1], [1, 2], num_vertices=3)
    nxg = to_networkx(g, directed=False)
    assert not nxg.is_directed() and nxg.number_of_edges() == 2
    with pytest.raises(TypeError, match="EdgeTable or Graph"):
        to_networkx([1, 2, 3])


def test_cc_matches_networkx_oracle(bundled_edges):
    """Weakly-connected components vs the NetworkX oracle on bundled data
    (SURVEY §4: 34 components, giant = 4,440)."""
    et = bundled_edges
    nxg = to_networkx(et)
    nx_comps = list(nx.weakly_connected_components(nxg))
    assert len(nx_comps) == 34
    g = graph_from_networkx(nxg)
    ours = np.asarray(connected_components(g))
    assert len(np.unique(ours)) == 34
    # identical partitions: every nx component maps to exactly one label
    for comp in nx_comps:
        assert len({int(ours[v]) for v in comp}) == 1


def test_lpa_partition_sanity_vs_networkx():
    """Two cliques + bridge: both engines split them identically."""
    nxg = nx.barbell_graph(5, 0)  # two 5-cliques joined by one edge
    g = graph_from_networkx(nxg)
    ours = np.asarray(label_propagation(g, max_iter=10))
    assert len({int(x) for x in ours[:5]}) == 1
    assert len({int(x) for x in ours[5:]}) == 1


def test_weighted_pagerank_matches_networkx():
    rng = np.random.default_rng(5)
    v, e = 60, 400
    src = rng.integers(0, v, e).astype(np.int32)
    dst = rng.integers(0, v, e).astype(np.int32)
    w = rng.uniform(0.1, 3.0, e).astype(np.float32)
    g = build_graph(src, dst, num_vertices=v, symmetric=False)

    from graphmine_tpu.ops.pagerank import pagerank

    ours_w = np.asarray(pagerank(g, max_iter=200, tol=1e-10, weights=w))
    ours_u = np.asarray(pagerank(g, max_iter=200, tol=1e-10))

    nxg = nx.MultiDiGraph()
    nxg.add_nodes_from(range(v))
    for s, d, wt in zip(src.tolist(), dst.tolist(), w.tolist()):
        nxg.add_edge(s, d, weight=wt)
    want_w = nx.pagerank(nxg, alpha=0.85, weight="weight", tol=1e-12, max_iter=500)
    want_u = nx.pagerank(nxg, alpha=0.85, weight=None, tol=1e-12, max_iter=500)
    np.testing.assert_allclose(ours_w, [want_w[i] for i in range(v)], atol=2e-5)
    np.testing.assert_allclose(ours_u, [want_u[i] for i in range(v)], atol=2e-5)
    assert not np.allclose(ours_w, ours_u)  # weights actually matter


def test_weighted_modularity_and_louvain_match_networkx():
    """Weighted graphs: our modularity agrees with the NetworkX oracle on
    arbitrary labels, and weighted Louvain recovers a weight-planted
    partition that unweighted Louvain cannot see."""
    from graphmine_tpu.ops.louvain import louvain
    from graphmine_tpu.ops.modularity import modularity

    rng = np.random.default_rng(11)
    v = 24
    # two halves; ALL pairs connected, but intra-half edges weigh 50x more
    src, dst, w = [], [], []
    for a in range(v):
        for b in range(a + 1, v):
            src.append(a); dst.append(b)
            same = (a < v // 2) == (b < v // 2)
            w.append(50.0 if same else 1.0)
    src = np.asarray(src, np.int32); dst = np.asarray(dst, np.int32)
    w = np.asarray(w, np.float32)
    g = build_graph(src, dst, num_vertices=v, edge_weights=w)

    labels = rng.integers(0, 3, v).astype(np.int32)
    ours = float(modularity(labels, g))
    nxg = nx.Graph()
    nxg.add_nodes_from(range(v))
    for s, d, wt in zip(src.tolist(), dst.tolist(), w.tolist()):
        nxg.add_edge(s, d, weight=wt)
    part = {}
    for i, l in enumerate(labels):
        part.setdefault(int(l), set()).add(i)
    want = nx.community.modularity(nxg, part.values(), weight="weight")
    np.testing.assert_allclose(ours, want, atol=1e-5)

    lab_w, q_w = louvain(g)
    lab_w = np.asarray(lab_w)
    # weighted louvain splits the halves along the planted weights
    assert len(set(lab_w[: v // 2].tolist())) == 1
    assert len(set(lab_w[v // 2:].tolist())) == 1
    assert lab_w[0] != lab_w[-1]
    # the unweighted graph is a uniform clique: no such structure exists
    g_u = build_graph(src, dst, num_vertices=v)
    _, q_u = louvain(g_u)
    assert float(q_w) > 0.3 > float(q_u) + 0.25
