"""HITS and closeness centrality vs the NetworkX oracle."""

import numpy as np
import pytest

from graphmine_tpu.graph.container import build_graph
from graphmine_tpu.ops.centrality import closeness_centrality, hits

nx = pytest.importorskip("networkx")


def random_digraph(v=40, e=160, seed=0):
    rng = np.random.default_rng(seed)
    src = rng.integers(0, v, e).astype(np.int32)
    dst = rng.integers(0, v, e).astype(np.int32)
    keep = src != dst
    return src[keep], dst[keep], v


def test_hits_matches_networkx():
    src, dst, v = random_digraph()
    # nx.DiGraph dedupes parallel edges; hits() honors multiplicity, so
    # feed it the deduped list for the oracle comparison
    pairs = np.unique(np.stack([src, dst], 1), axis=0)
    src, dst = pairs[:, 0], pairs[:, 1]
    g = build_graph(src, dst, num_vertices=v, symmetric=False)
    h, a = (np.asarray(x) for x in hits(g, max_iter=500, tol=1e-10))

    G = nx.DiGraph()
    G.add_nodes_from(range(v))
    G.add_edges_from(zip(src.tolist(), dst.tolist()))
    nh, na = nx.hits(G, max_iter=500, tol=1e-10)
    np.testing.assert_allclose(h, [nh[i] for i in range(v)], atol=2e-4)
    np.testing.assert_allclose(a, [na[i] for i in range(v)], atol=2e-4)


def test_hits_tiny_chain():
    # a -> b -> c: a is the only pure hub, c the only pure authority
    g = build_graph(np.array([0, 1], np.int32), np.array([1, 2], np.int32),
                    num_vertices=3, symmetric=False)
    h, a = (np.asarray(x) for x in hits(g))
    assert h[2] == 0 and a[0] == 0
    assert h.argmax() in (0, 1) and a.argmax() in (1, 2)


def test_closeness_matches_networkx():
    src, dst, v = random_digraph(seed=3)
    g = build_graph(src, dst, num_vertices=v, symmetric=True)
    c = np.asarray(closeness_centrality(g))

    G = nx.Graph()
    G.add_nodes_from(range(v))
    G.add_edges_from(zip(src.tolist(), dst.tolist()))
    expected = nx.closeness_centrality(G)
    np.testing.assert_allclose(c, [expected[i] for i in range(v)], rtol=1e-5)


def test_closeness_disconnected_and_subset():
    # path 0-1-2 plus isolated vertex 3
    g = build_graph(np.array([0, 1], np.int32), np.array([1, 2], np.int32),
                    num_vertices=4, symmetric=True)
    c = np.asarray(closeness_centrality(g))
    assert c[3] == 0.0
    assert c[1] > c[0] == c[2] > 0
    sub = np.asarray(closeness_centrality(g, vertices=[1, 3]))
    np.testing.assert_allclose(sub, c[[1, 3]])
    G = nx.Graph([(0, 1), (1, 2)])
    G.add_node(3)
    expected = nx.closeness_centrality(G)
    np.testing.assert_allclose(c, [expected[i] for i in range(4)], rtol=1e-6)


def test_directed_closeness_matches_networkx_digraph():
    src, dst, v = random_digraph(seed=5)
    pairs = np.unique(np.stack([src, dst], 1), axis=0)
    g = build_graph(pairs[:, 0], pairs[:, 1], num_vertices=v, symmetric=False)
    c = np.asarray(closeness_centrality(g))
    G = nx.DiGraph()
    G.add_nodes_from(range(v))
    G.add_edges_from(pairs.tolist())
    expected = nx.closeness_centrality(G)  # incoming-distance convention
    np.testing.assert_allclose(c, [expected[i] for i in range(v)], rtol=1e-5)


def test_shortest_paths_batched_tiles_match_per_landmark():
    from graphmine_tpu.ops.paths import shortest_paths

    src, dst, v = random_digraph(seed=7)
    g = build_graph(src, dst, num_vertices=v, symmetric=True)
    lms = np.array([3, 1, 17, 29, 5], np.int32)
    batched = np.asarray(shortest_paths(g, lms, landmark_batch=2))
    ones = np.column_stack(
        [np.asarray(shortest_paths(g, lms[j:j + 1], landmark_batch=1))[:, 0]
         for j in range(len(lms))]
    )
    np.testing.assert_array_equal(batched, ones)


def dedup(src, dst):
    pairs = np.unique(np.stack([src, dst], 1), axis=0)
    return pairs[:, 0], pairs[:, 1]


def test_betweenness_exact_matches_networkx_undirected():
    from graphmine_tpu.ops.centrality import betweenness_centrality

    src, dst, v = random_digraph(seed=13)
    # canonicalize to simple undirected pairs: reciprocal directed edges
    # would otherwise act as parallel edges and inflate path counts
    # (multigraph semantics — the engine's multiplicity convention)
    src, dst = dedup(np.minimum(src, dst), np.maximum(src, dst))
    g = build_graph(src, dst, num_vertices=v, symmetric=True)
    # v=40, batch 7 -> pad=2: exercises the padded-lane masking too
    bc = np.asarray(betweenness_centrality(g, source_batch=7))
    np.testing.assert_allclose(
        bc, np.asarray(betweenness_centrality(g, source_batch=8)), rtol=1e-5)
    G = nx.Graph()
    G.add_nodes_from(range(v))
    G.add_edges_from(zip(src.tolist(), dst.tolist()))
    expected = nx.betweenness_centrality(G, normalized=True)
    np.testing.assert_allclose(bc, [expected[i] for i in range(v)],
                               rtol=1e-4, atol=1e-6)


def test_betweenness_exact_matches_networkx_directed():
    from graphmine_tpu.ops.centrality import betweenness_centrality

    src, dst, v = random_digraph(seed=17, e=120)
    src, dst = dedup(src, dst)
    g = build_graph(src, dst, num_vertices=v, symmetric=False)
    bc = np.asarray(betweenness_centrality(g))
    G = nx.DiGraph()
    G.add_nodes_from(range(v))
    G.add_edges_from(zip(src.tolist(), dst.tolist()))
    expected = nx.betweenness_centrality(G, normalized=True)
    np.testing.assert_allclose(bc, [expected[i] for i in range(v)],
                               rtol=1e-4, atol=1e-6)
    # unnormalized too
    bc_raw = np.asarray(betweenness_centrality(g, normalized=False))
    raw = nx.betweenness_centrality(G, normalized=False)
    np.testing.assert_allclose(bc_raw, [raw[i] for i in range(v)],
                               rtol=1e-4, atol=1e-5)


def test_betweenness_path_graph_and_sampling():
    from graphmine_tpu.ops.centrality import betweenness_centrality

    # path 0-1-2-3-4: middle vertex carries the most pairs
    g = build_graph(np.arange(4, dtype=np.int32),
                    np.arange(1, 5, dtype=np.int32), num_vertices=5)
    bc = np.asarray(betweenness_centrality(g, normalized=False))
    assert list(bc) == [0.0, 3.0, 4.0, 3.0, 0.0]
    # sampled estimator: unbiased here because all sources are sampled
    bs = np.asarray(betweenness_centrality(
        g, sources=np.arange(5, dtype=np.int32), normalized=False))
    np.testing.assert_allclose(bs, bc)
    # a source sample is a noisy estimator: interior vertices score
    # positive, endpoints zero, scaled by V/k
    half = np.asarray(betweenness_centrality(
        g, sources=np.array([0, 2, 4], np.int32), normalized=False))
    assert half[0] == half[4] == 0.0
    assert (half[1:4] > 0).all()


def test_betweenness_mesh_source_sharding_matches_single_device():
    from graphmine_tpu.ops.centrality import betweenness_centrality
    from graphmine_tpu.parallel.mesh import make_mesh

    src, dst, v = random_digraph(seed=23)
    src, dst = dedup(np.minimum(src, dst), np.maximum(src, dst))
    g = build_graph(src, dst, num_vertices=v)
    single = np.asarray(betweenness_centrality(g, source_batch=4))
    mesh = make_mesh(8)  # conftest provides 8 virtual devices
    sharded = np.asarray(betweenness_centrality(g, source_batch=4, mesh=mesh))
    np.testing.assert_allclose(sharded, single, rtol=1e-5, atol=1e-7)
    # sampled + mesh, k not divisible by devices*batch
    srcs = np.arange(13, dtype=np.int32)
    a = np.asarray(betweenness_centrality(g, sources=srcs, source_batch=4))
    m = np.asarray(betweenness_centrality(g, sources=srcs, source_batch=4,
                                          mesh=mesh))
    np.testing.assert_allclose(m, a, rtol=1e-5, atol=1e-7)


def test_eigenvector_and_katz_match_networkx():
    from graphmine_tpu.ops.centrality import (
        eigenvector_centrality,
        katz_centrality,
    )

    src, dst, v = random_digraph(seed=21)
    src, dst = dedup(np.minimum(src, dst), np.maximum(src, dst))
    g = build_graph(src, dst, num_vertices=v)
    G = nx.Graph()
    G.add_nodes_from(range(v))
    G.add_edges_from(zip(src.tolist(), dst.tolist()))

    ev = np.asarray(eigenvector_centrality(g, max_iter=500, tol=1e-8))
    ref = nx.eigenvector_centrality(G, max_iter=1000, tol=1e-10)
    np.testing.assert_allclose(ev, [ref[i] for i in range(v)], atol=1e-5)

    kz = np.asarray(katz_centrality(g, alpha=0.05))
    refk = nx.katz_centrality(G, alpha=0.05, max_iter=2000, tol=1e-10)
    np.testing.assert_allclose(kz, [refk[i] for i in range(v)], atol=1e-5)

    # directed Katz follows edge direction
    gd = build_graph(src, dst, num_vertices=v, symmetric=False)
    kzd = np.asarray(katz_centrality(gd, alpha=0.05))
    GD = nx.DiGraph()
    GD.add_nodes_from(range(v))
    GD.add_edges_from(zip(src.tolist(), dst.tolist()))
    refd = nx.katz_centrality(GD, alpha=0.05, max_iter=2000, tol=1e-10)
    np.testing.assert_allclose(kzd, [refd[i] for i in range(v)], atol=1e-5)


def test_frame_methods():
    from graphmine_tpu.frames import GraphFrame

    gf = GraphFrame((np.array([0, 1], np.int32), np.array([1, 2], np.int32)))
    h, a = gf.hits()
    assert np.asarray(h).shape == (3,)
    assert np.asarray(gf.closeness_centrality()).shape == (3,)
