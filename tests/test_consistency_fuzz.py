"""Cross-implementation consistency fuzz: every LPA/CC path, one answer.

The framework has four LPA execution paths (sort-based superstep, fused
bucketed kernel, vertex-range-sharded shard_map — sort and bucketed
bodies — and the ppermute ring schedule) — each in unweighted AND
weighted (r2) form — and three CC paths. Synchronous semantics are
deterministic, so on ANY graph they must agree bit-for-bit (weighted:
with exactly-representable weights, so summation order can't round).
This sweep hammers that invariant across random graph shapes: sparse,
dense, star-heavy (histogram hubs), self-loops, duplicates, isolates.
"""

import numpy as np
import pytest

from graphmine_tpu.graph.container import build_graph
from graphmine_tpu.ops.cc import connected_components
from graphmine_tpu.ops.lpa import label_propagation


@pytest.fixture(scope="module")
def mesh8():
    import jax

    from graphmine_tpu.parallel.mesh import make_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return make_mesh(8)


def _graphs():
    rng = np.random.default_rng(123)
    cases = []
    for v, e in [(17, 10), (64, 800), (200, 300), (333, 3000)]:
        cases.append((rng.integers(0, v, e).astype(np.int32),
                      rng.integers(0, v, e).astype(np.int32), v))
    # star-heavy: one hub with most edges (exercises wide/hist buckets)
    v = 120
    hub_dst = rng.integers(0, v, 90).astype(np.int32)
    extra = rng.integers(0, v, (2, 60)).astype(np.int32)
    cases.append((np.concatenate([np.zeros(90, np.int32), extra[0]]),
                  np.concatenate([hub_dst, extra[1]]), v))
    # self-loops + exact duplicates + isolates
    cases.append((np.array([1, 1, 1, 2, 5, 5], np.int32),
                  np.array([1, 2, 2, 3, 6, 6], np.int32), 9))
    # regression (consistency_sweep seed 34): 3 edges over 51 vertices —
    # most shards hold only bucket-plan padding rows, and the shard-body
    # scatter of those rows must not disturb the isolated vertices at the
    # ends of the chunks (an OOB drop-scatter under shard_map was observed
    # corrupting them with shifted reads on XLA:CPU)
    cases.append((np.array([44, 5, 12], np.int32),
                  np.array([0, 33, 5], np.int32), 51))
    return cases


@pytest.mark.parametrize("case", range(7))
def test_all_lpa_paths_agree(case, mesh8):
    from graphmine_tpu.ops.bucketed_mode import build_graph_and_plan, lpa_superstep_bucketed
    from graphmine_tpu.parallel.ring import ring_label_propagation
    from graphmine_tpu.parallel.sharded import (
        partition_graph,
        shard_graph_arrays,
        sharded_label_propagation,
    )
    import jax
    import jax.numpy as jnp

    src, dst, v = _graphs()[case]
    g = build_graph(src, dst, num_vertices=v)
    want = np.asarray(label_propagation(g, max_iter=4, plan=None))

    g2, plan = build_graph_and_plan(src, dst, num_vertices=v)
    lbl = jnp.arange(v, dtype=jnp.int32)
    step = jax.jit(lpa_superstep_bucketed)
    for _ in range(4):
        lbl = step(lbl, g2, plan)
    np.testing.assert_array_equal(want, np.asarray(lbl), err_msg="fused bucketed")

    sg_fast = shard_graph_arrays(
        partition_graph(g, mesh=mesh8, build_bucket_plan=True), mesh8
    )
    np.testing.assert_array_equal(
        want,
        np.asarray(sharded_label_propagation(sg_fast, mesh8, max_iter=4)),
        err_msg="sharded bucketed",
    )
    sg = shard_graph_arrays(partition_graph(g, mesh=mesh8), mesh8)
    np.testing.assert_array_equal(
        want,
        np.asarray(sharded_label_propagation(sg, mesh8, max_iter=4)),
        err_msg="sharded sort",
    )
    np.testing.assert_array_equal(
        want,
        np.asarray(ring_label_propagation(sg, mesh8, max_iter=4)),
        err_msg="ring",
    )


@pytest.mark.parametrize("case", range(7))
def test_all_weighted_lpa_paths_agree(case, mesh8):
    """r2: weighted LPA has the same four execution paths; same one-answer
    invariant. Weights are multiples of 1/4 so per-label sums are exact in
    float32 under every path's summation order."""
    import jax
    import jax.numpy as jnp

    from graphmine_tpu.ops.bucketed_mode import (
        build_graph_and_plan,
        lpa_superstep_bucketed,
    )
    from graphmine_tpu.parallel.ring import ring_label_propagation
    from graphmine_tpu.parallel.sharded import (
        partition_graph,
        shard_graph_arrays,
        sharded_label_propagation,
    )

    src, dst, v = _graphs()[case]
    rng = np.random.default_rng(1000 + case)
    w = (rng.integers(1, 16, len(src)) / 4.0).astype(np.float32)
    g = build_graph(src, dst, num_vertices=v, edge_weights=w)
    want = np.asarray(label_propagation(g, max_iter=4, plan=None))

    g2, plan = build_graph_and_plan(src, dst, num_vertices=v, edge_weights=w)
    lbl = jnp.arange(v, dtype=jnp.int32)
    step = jax.jit(lpa_superstep_bucketed)
    for _ in range(4):
        lbl = step(lbl, g2, plan)
    np.testing.assert_array_equal(want, np.asarray(lbl), err_msg="fused bucketed")

    sg_fast = shard_graph_arrays(
        partition_graph(g, mesh=mesh8, build_bucket_plan=True), mesh8
    )
    np.testing.assert_array_equal(
        want,
        np.asarray(sharded_label_propagation(sg_fast, mesh8, max_iter=4)),
        err_msg="sharded bucketed",
    )
    sg = shard_graph_arrays(partition_graph(g, mesh=mesh8), mesh8)
    np.testing.assert_array_equal(
        want,
        np.asarray(sharded_label_propagation(sg, mesh8, max_iter=4)),
        err_msg="sharded sort",
    )
    np.testing.assert_array_equal(
        want,
        np.asarray(ring_label_propagation(sg, mesh8, max_iter=4)),
        err_msg="ring",
    )


@pytest.mark.parametrize("case", range(7))
def test_cc_paths_agree_with_union_find(case, mesh8):
    from graphmine_tpu.parallel.ring import ring_connected_components
    from graphmine_tpu.parallel.sharded import (
        partition_graph,
        shard_graph_arrays,
        sharded_connected_components,
    )

    src, dst, v = _graphs()[case]
    g = build_graph(src, dst, num_vertices=v)
    ours = np.asarray(connected_components(g))

    # union-find oracle
    parent = list(range(v))

    def find(x):
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    for a, b in zip(src.tolist(), dst.tolist()):
        ra, rb = find(a), find(b)
        if ra != rb:
            parent[max(ra, rb)] = min(ra, rb)
    oracle = np.asarray([find(i) for i in range(v)])
    # same partition (labels are min-vertex per component in both)
    np.testing.assert_array_equal(ours, oracle)

    sg = shard_graph_arrays(partition_graph(g, mesh=mesh8), mesh8)
    np.testing.assert_array_equal(
        ours, np.asarray(sharded_connected_components(sg, mesh8)))
    np.testing.assert_array_equal(
        ours, np.asarray(ring_connected_components(sg, mesh8)))
