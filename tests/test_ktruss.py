"""k-truss vs the NetworkX oracle."""

import numpy as np
import pytest

from graphmine_tpu.graph.container import build_graph
from graphmine_tpu.ops.ktruss import k_truss

nx = pytest.importorskip("networkx")


def edge_set(a, b):
    return set(zip(a.tolist(), b.tolist()))


def oracle_edges(G, k):
    T = nx.k_truss(G, k)
    return {(min(u, v), max(u, v)) for u, v in T.edges()}


@pytest.mark.parametrize("seed", [0, 1])
@pytest.mark.parametrize("k", [2, 3, 4, 5])
def test_k_truss_matches_networkx(seed, k):
    rng = np.random.default_rng(seed)
    v, e = 60, 420
    src = rng.integers(0, v, e).astype(np.int32)
    dst = rng.integers(0, v, e).astype(np.int32)
    g = build_graph(src, dst, num_vertices=v)
    a, b = k_truss(g, k)
    G = nx.Graph()
    G.add_nodes_from(range(v))
    G.add_edges_from((int(x), int(y)) for x, y in zip(src, dst) if x != y)
    assert edge_set(a, b) == oracle_edges(G, k)


def test_k_truss_hand_built():
    # K4 plus a dangling path: the 4-truss is exactly the K4
    k4 = [(0, 1), (0, 2), (0, 3), (1, 2), (1, 3), (2, 3)]
    path = [(3, 4), (4, 5)]
    src, dst = map(np.array, zip(*(k4 + path)))
    g = build_graph(src.astype(np.int32), dst.astype(np.int32), num_vertices=6)
    a, b = k_truss(g, 4)
    assert edge_set(a, b) == set(k4)
    a2, b2 = k_truss(g, 2)  # 2-truss keeps every edge
    assert len(a2) == 8
    a5, b5 = k_truss(g, 5)  # nothing is 5-truss here
    assert len(a5) == 0


def test_k_truss_validation_and_triangle_free():
    g = build_graph(np.array([0, 1], np.int32), np.array([1, 2], np.int32),
                    num_vertices=3)
    with pytest.raises(ValueError, match="k must be"):
        k_truss(g, 1)
    a, b = k_truss(g, 2)  # triangle-free: 2-truss is the whole graph
    assert len(a) == 2
    a3, _ = k_truss(g, 3)
    assert len(a3) == 0
