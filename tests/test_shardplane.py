"""Sharded-write-plane suite (marker ``shardplane``): the ISSUE 17
no-single-point-of-failure contract — tools/run_tier1.sh
--shardplane-only.

The acceptance pins:
- ``ShardPlan`` cuts the id space into k contiguous vertex ranges (the
  last shard owns growth ids) and ownership is deterministic;
- the delta splitter routes every insert AND delete to its dst owner,
  ``merge_splits`` is a bit-exact inverse, and split-then-apply equals
  sequential whole-batch apply — labels, LOF and weights bit-identical,
  cross-range deletes included;
- publishes are epoch-coordinated two-phase commits: the durable
  ``publish_epoch`` record is THE commit point, a torn publish (crash
  between stage and commit) leaves the previous epoch served and is
  finished or swept by ``recover()``;
- shard death flips ONLY its range read-only (untouched ranges keep
  accepting), restart/standby-promotion replays the acked tail with
  zero acked-delta loss, and a 3-shard/2-tenant plane survives a
  mid-burst shard kill with zero mixed-epoch reads;
- ``GRAPHMINE_WRITER_SHARDS=1`` (the default) is the exact pre-shard
  path — the plane is never constructed, published bytes identical.
"""

import json
import os
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from graphmine_tpu.graph.container import build_graph
from graphmine_tpu.obs.schema import validate_records
from graphmine_tpu.obs.spans import Tracer
from graphmine_tpu.pipeline.checkpoint import graph_fingerprint
from graphmine_tpu.pipeline.metrics import MetricsSink
from graphmine_tpu.serve import SnapshotStore
from graphmine_tpu.serve.admission import AdmissionBounds, AdmissionController
from graphmine_tpu.serve.delta import EdgeDelta, cold_recompute, splice_edges
from graphmine_tpu.serve.server import SnapshotServer
from graphmine_tpu.serve.shardplane import (
    EpochCoordinator,
    ShardPlan,
    ShardRangeUnavailableError,
    ShardedWritePlane,
    emit_shard_record,
    merge_splits,
    split_delta,
    writer_shards_from_env,
)
from graphmine_tpu.testing import faults

pytestmark = pytest.mark.shardplane


# ---- fixtures -------------------------------------------------------------


def _clique(lo, hi):
    ids = np.arange(lo, hi)
    s, d = np.meshgrid(ids, ids)
    m = s.ravel() < d.ravel()
    return s.ravel()[m], d.ravel()[m]


def _cliques(spans):
    parts = [_clique(lo, hi) for lo, hi in spans]
    src = np.concatenate([p[0] for p in parts]).astype(np.int32)
    dst = np.concatenate([p[1] for p in parts]).astype(np.int32)
    return src, dst, max(hi for _, hi in spans)


def _sink():
    return MetricsSink(tracer=Tracer())


def _publish(store, src, dst, v, weights=None, sink=None):
    g = build_graph(src, dst, num_vertices=v)
    labels, cc, _ = cold_recompute(g)
    arrays = {
        "src": src, "dst": dst, "labels": labels, "cc_labels": cc,
        "lof": np.linspace(0.5, 1.2, v).astype(np.float32),
    }
    if weights is not None:
        arrays["weights"] = np.asarray(weights, np.float32)
    store.publish(
        arrays, fingerprint=graph_fingerprint(src, dst), sink=sink,
    )
    return store


def _generous():
    return AdmissionController(bounds=AdmissionBounds(
        max_pending_rows=100_000, max_queue_depth=64, deadline_s=300.0,
    ))


def _get(host, port, path, headers=None):
    req = urllib.request.Request(
        f"http://{host}:{port}{path}", headers=headers or {}
    )
    with urllib.request.urlopen(req, timeout=30) as r:
        return json.loads(r.read())


def _post(host, port, path, payload, headers=None):
    req = urllib.request.Request(
        f"http://{host}:{port}{path}", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    with urllib.request.urlopen(req, timeout=120) as r:
        return json.loads(r.read())


# ---- range plan ------------------------------------------------------------


def test_shard_plan_ownership_properties(monkeypatch):
    """Contiguous cover, ceil-width chunks, growth ids to the LAST
    shard, scalar/vector ownership agreement, env parsing."""
    plan = ShardPlan.build(3, 100)
    assert plan.boundaries == (0, 34, 68, 100)
    assert [r["shard"] for r in plan.ranges()] == [0, 1, 2]
    assert plan.ranges()[-1]["owns_growth"] is True
    # every id in [0, v) owned by exactly the range that contains it
    ids = np.arange(120)  # includes growth ids >= 100
    owners = plan.owners(ids)
    for i in (0, 33, 34, 67, 68, 99):
        lo, hi = plan.range_of(plan.owner_of(i))
        assert lo <= i < hi
        assert owners[i] == plan.owner_of(i)
    # growth: ids beyond num_vertices belong to the last shard
    assert plan.owner_of(100) == 2
    assert (owners[100:] == 2).all()

    one = ShardPlan.build(1, 100)
    assert one.boundaries == (0, 100)
    assert (one.owners(ids) == 0).all()

    with pytest.raises(ValueError):
        ShardPlan.build(0, 100)

    monkeypatch.delenv("GRAPHMINE_WRITER_SHARDS", raising=False)
    assert writer_shards_from_env() == 1
    monkeypatch.setenv("GRAPHMINE_WRITER_SHARDS", "4")
    assert writer_shards_from_env() == 4
    assert ShardPlan.from_env(100).num_shards == 4
    for bad in ("0", "-2", "three", "1.5", ""):
        monkeypatch.setenv("GRAPHMINE_WRITER_SHARDS", bad)
        with pytest.raises(ValueError):
            writer_shards_from_env()


def test_emit_shard_record_is_the_single_builder():
    """Unknown phases are refused at the builder (the schema_lint twin:
    no other call site may emit these phases at all) and a ``None``
    sink is a no-op."""
    sink = _sink()
    emit_shard_record(sink, "shard_publish", epoch=1, shard=0, version=1,
                      arrays=["labels"])
    emit_shard_record(None, "epoch_commit", epoch=1)  # no-op, no raise
    assert sink.records[-1]["phase"] == "shard_publish"
    with pytest.raises(ValueError):
        emit_shard_record(sink, "shard_published", epoch=1)
    with pytest.raises(ValueError):
        emit_shard_record(sink, "delta_apply")  # registered, not ours


# ---- deterministic splitter ------------------------------------------------


def test_split_merge_bit_identity_randomized():
    """N random batches (weighted and not, growth inserts, cross-range
    and unmatched deletes) split and scatter back bit-identically, and
    every sub-batch's rows all belong to its shard's dst range."""
    rng = np.random.default_rng(17)
    for trial in range(20):
        k = int(rng.integers(1, 6))
        v = int(rng.integers(k, 60))
        plan = ShardPlan.build(k, v)
        n_ins = int(rng.integers(0, 30))
        n_del = int(rng.integers(0, 20))
        d = EdgeDelta(
            rng.integers(0, v, n_ins),
            # some inserts hit growth ids beyond v
            rng.integers(0, v + 10, n_ins),
            rng.integers(0, v, n_del),
            rng.integers(0, v, n_del),
            insert_weight=(
                rng.random(n_ins).astype(np.float32)
                if trial % 2 else None
            ),
        )
        splits = split_delta(d, plan)
        # partition: every original row appears in exactly one split
        all_ins = np.concatenate(
            [sp.insert_index for sp in splits]
        ) if splits else np.empty(0)
        all_del = np.concatenate([sp.delete_index for sp in splits])
        assert sorted(all_ins) == list(range(n_ins))
        assert sorted(all_del) == list(range(n_del))
        for sp in splits:
            lo, hi = plan.range_of(sp.shard)
            owns_growth = sp.shard == plan.num_shards - 1
            for dst in sp.delta.insert_dst:
                assert lo <= dst < hi or (owns_growth and dst >= v)
            for dst in sp.delta.delete_dst:
                assert lo <= dst < hi or (owns_growth and dst >= v)
        m = merge_splits(splits)
        np.testing.assert_array_equal(m.insert_src, d.insert_src)
        np.testing.assert_array_equal(m.insert_dst, d.insert_dst)
        np.testing.assert_array_equal(m.delete_src, d.delete_src)
        np.testing.assert_array_equal(m.delete_dst, d.delete_dst)
        if d.insert_weight is None:
            assert m.insert_weight is None or n_ins == 0
        else:
            np.testing.assert_array_equal(m.insert_weight, d.insert_weight)


def test_split_then_splice_parity_randomized():
    """Applying a batch's splits one-by-one produces the same edge
    multiset, vertex count, delete accounting and (recomputed) labels
    as one whole-batch splice — cross-range deletes included. Unique
    edge keys per trial so multiset comparison is exact."""
    rng = np.random.default_rng(29)
    for trial in range(8):
        v = int(rng.integers(12, 40))
        k = int(rng.integers(2, 5))
        src, dst, _ = _cliques([(0, v // 2), (v // 2, v)])
        w = (rng.integers(1, 16, len(src)) / 4.0).astype(np.float32)
        plan = ShardPlan.build(k, v)
        # inserts: fresh unique pairs (some growth); deletes: a sample
        # of existing edges — src and dst often land in DIFFERENT
        # ranges, the cross-range rule under test
        n_ins = int(rng.integers(1, 12))
        ins = rng.choice(v * (v + 8), size=n_ins, replace=False)
        isrc, idst = ins % v, ins // v
        del_idx = rng.choice(
            len(src), size=int(rng.integers(1, 6)), replace=False
        )
        d = EdgeDelta(
            isrc, idst, src[del_idx], dst[del_idx],
            insert_weight=(
                (rng.integers(1, 8, n_ins) / 4.0).astype(np.float32)
                if trial % 2 else None
            ),
        )
        weighted = d.insert_weight is not None

        def run(parts):
            s, dd, ww, vv = src, dst, w, v
            stats_sum = {"inserted": 0, "deleted": 0, "unmatched_deletes": 0}
            for p in parts:
                s, dd, ww, vv, st = splice_edges(s, dd, vv, p, weights=ww)
                for key in stats_sum:
                    stats_sum[key] += st[key]
            return s, dd, ww, vv, stats_sum

        whole = run([d])
        parts = run([sp.delta for sp in split_delta(d, plan)])
        assert whole[3] == parts[3]  # num_vertices
        assert whole[4] == parts[4]  # inserted/deleted/unmatched sums
        # edge MULTISET identical (order differs by construction: the
        # split path appends per-shard); weights ride their edges
        def canon(s, dd, ww):
            order = np.lexsort((ww, dd, s))
            return s[order], dd[order], ww[order]
        for a, b in zip(canon(*whole[:3]), canon(*parts[:3])):
            np.testing.assert_array_equal(a, b)
        # recomputed labels/cc bit-identical over the identical multiset
        ga = build_graph(whole[0], whole[1], num_vertices=whole[3])
        gb = build_graph(parts[0], parts[1], num_vertices=parts[3])
        la, ca, _ = cold_recompute(ga)
        lb, cb, _ = cold_recompute(gb)
        np.testing.assert_array_equal(la, lb)
        np.testing.assert_array_equal(ca, cb)
        assert weighted == (d.insert_weight is not None)


# ---- epoch-coordinated publish ---------------------------------------------


def _coordinator(tmp_path, k=3, v=30, sink=None):
    src, dst, v = _cliques([(0, v // 2), (v // 2, v)])
    store = SnapshotStore(str(tmp_path / "snap"))
    _publish(store, src, dst, v)
    plan = ShardPlan.build(k, v)
    return EpochCoordinator(store, plan, sink=sink), plan, v


def _shard_arrays(plan, v, fill=0):
    out = {}
    for s in range(plan.num_shards):
        lo, hi = plan.range_of(s)
        out[s] = {"labels": np.arange(lo, hi, dtype=np.int32) + fill}
    return out


def test_epoch_stage_commit_read_roundtrip(tmp_path):
    """stage → commit → read: the record is the commit point, arrays
    verify against their manifests, the version vector round-trips, and
    only RETAIN_EPOCHS generations survive."""
    sink = _sink()
    coord, plan, v = _coordinator(tmp_path, sink=sink)
    assert coord.committed_epoch() == 0
    assert coord.read_epoch() is None

    coord.stage(1, _shard_arrays(plan, v), versions={0: 2, 1: 2, 2: 2})
    # staged but uncommitted: nothing served
    assert coord.committed_epoch() == 0
    coord.commit(1, {0: 2, 1: 2, 2: 2})
    assert coord.committed_epoch() == 1
    got = coord.read_epoch()
    assert got["epoch"] == 1
    assert got["version_vector"] == {0: 2, 1: 2, 2: 2}
    lo, hi = plan.range_of(1)
    np.testing.assert_array_equal(
        got["shards"][1]["arrays"]["labels"], np.arange(lo, hi)
    )

    for e, ver in ((2, 3), (3, 4), (4, 5)):
        coord.stage(e, _shard_arrays(plan, v, fill=e),
                    versions={s: ver for s in range(3)})
        coord.commit(e, {s: ver for s in range(3)})
    assert coord.committed_epoch() == 4
    assert coord.committed_epochs() == [3, 4]  # RETAIN_EPOCHS = 2
    assert coord.version_vector() == {0: 5, 1: 5, 2: 5}

    phases = [r["phase"] for r in sink.records]
    assert phases.count("shard_publish") == 12  # 4 epochs x 3 shards
    assert phases.count("epoch_commit") == 4
    assert validate_records(sink.records) == []


def test_torn_publish_serves_previous_epoch_and_recovers(tmp_path):
    """THE torn-publish drill: a crash injected at the
    ``shard_publish_commit`` seam (everything staged, nothing
    committed) leaves the previous epoch served in full; ``recover()``
    finishes the complete generation. An INCOMPLETE stage (a shard's
    array file lost) is swept instead — never half-committed."""
    sink = _sink()
    coord, plan, v = _coordinator(tmp_path, sink=sink)
    coord.stage(1, _shard_arrays(plan, v), versions={s: 2 for s in range(3)})
    coord.commit(1, {s: 2 for s in range(3)})

    coord.stage(2, _shard_arrays(plan, v, fill=9),
                versions={s: 3 for s in range(3)})
    inj = faults.shard_publish_torn()
    with inj.installed():
        with pytest.raises(Exception):
            coord.commit(2, {s: 3 for s in range(3)})
    # the coordinator "crashed" between stage and commit: epoch 1 is
    # still served, whole and verifiable
    assert coord.committed_epoch() == 1
    assert coord.read_epoch()["version_vector"] == {0: 2, 1: 2, 2: 2}

    rec = coord.recover()
    assert coord.committed_epoch() == 2
    assert coord.version_vector() == {0: 3, 1: 3, 2: 3}
    assert any(r["phase"] == "epoch_commit" and r.get("recovered")
               for r in sink.records)

    # incomplete stage: lose one shard's array file → recover sweeps
    coord.stage(3, _shard_arrays(plan, v, fill=4),
                versions={s: 4 for s in range(3)})
    stage = coord._stage_dir(3)
    os.remove(os.path.join(stage, "shard-001", "labels.npy"))
    coord.recover()
    assert coord.committed_epoch() == 2
    assert not os.path.exists(stage)
    assert rec is not None
    assert validate_records(sink.records) == []


# ---- the plane: submit / dedupe / per-range failover -----------------------


def test_plane_submit_dedupe_shed_and_range_refusal(tmp_path):
    """Direct plane contract: accepted batches return per-shard seqs,
    a retried id every touched shard holds is a duplicate, one
    saturated range sheds the WHOLE batch, and a dead range raises the
    structured 503 while untouched ranges keep accepting."""
    src, dst, v = _cliques([(0, 15), (15, 30)])
    store = SnapshotStore(str(tmp_path / "snap"))
    _publish(store, src, dst, v)
    plan = ShardPlan.build(3, v)  # ranges [0,10) [10,20) [20,30)
    plane = ShardedWritePlane(
        store, plan, sink=_sink(),
        admission_bounds=AdmissionBounds(
            max_pending_rows=100, max_queue_depth=8, deadline_s=300.0,
        ),
    )
    try:
        cross = EdgeDelta.from_pairs(insert=[[1, 2], [1, 12], [1, 25]])
        sub = plane.submit(cross, delta_id="d1")
        assert sub["verdict"] == "accepted"
        assert sorted(sub["shard_seqs"]) == [0, 1, 2]

        # clean retry: every touched shard already holds d1
        again = plane.submit(cross, delta_id="d1")
        assert again["verdict"] == "duplicate"
        assert again["shard_seqs"] == sub["shard_seqs"]

        # watermarks advance per shard; the version vector follows
        plane.commit_applied(sub["shard_seqs"], version=2)
        assert plane.version_vector() == {0: 2, 1: 2, 2: 2}

        # all-or-nothing: saturate shard 1's ladder → whole batch sheds,
        # nothing appended anywhere
        before = {
            ws.shard: ws.wal.last_seq for ws in plane.shards
        }
        plane.shards[1].debt.submitted(10_000)
        shed = plane.submit(
            EdgeDelta.from_pairs(insert=[[0, 1], [0, 15]]), delta_id="d2",
        )
        assert shed["verdict"] == "shed"
        assert "shard 1" in shed["reason"]
        assert {
            ws.shard: ws.wal.last_seq for ws in plane.shards
        } == before
        from graphmine_tpu.serve.delta import RepairDebt

        plane.shards[1].debt = RepairDebt()  # drop the synthetic backlog

        # dead range: only batches TOUCHING it are refused
        plane.kill_shard(1, reason="writer_shard_kill")
        with pytest.raises(ShardRangeUnavailableError) as e:
            plane.submit(EdgeDelta.from_pairs(insert=[[0, 12]]))
        assert e.value.shards == (1,)
        assert "degraded vertex range" in str(e.value)
        ok = plane.submit(
            EdgeDelta.from_pairs(insert=[[0, 1]]), delta_id="d3",
        )
        assert ok["verdict"] == "accepted"
        assert list(ok["shard_seqs"]) == [0]

        # restart: the acked-but-unapplied tail comes back for replay
        pending = plane.restart_shard(1)
        assert [p["id"] for p in pending] == ["d1"] or pending == []
        after = plane.submit(EdgeDelta.from_pairs(insert=[[0, 13]]))
        assert after["verdict"] == "accepted"
    finally:
        plane.close()


def test_plane_standby_ship_promote_is_fenced(tmp_path):
    """Per-range standby: ship copies the WAL verbatim, promotion mints
    a store epoch (the fence) and reopens the range with zero acked
    loss — the §"Replicated writers" dance, per range."""
    src, dst, v = _cliques([(0, 15), (15, 30)])
    store = SnapshotStore(str(tmp_path / "snap"))
    _publish(store, src, dst, v)
    plane = ShardedWritePlane(store, ShardPlan.build(2, v), sink=_sink())
    try:
        plane.attach_standby(1)
        s1 = plane.submit(
            EdgeDelta.from_pairs(insert=[[0, 20], [1, 21]]), delta_id="a",
        )
        assert plane.ship_shard(1) == 1  # one entry copied verbatim
        epoch_before = store.current_epoch()

        plane.kill_shard(1)
        out = plane.promote_shard(1)
        assert out["epoch"] == epoch_before + 1
        assert [p["id"] for p in out["pending"]] == ["a"]
        # the promoted WAL holds the acked seq and the range is live
        assert not plane.shards[1].read_only
        assert plane.shards[1].wal.last_seq == s1["shard_seqs"][1]
        ok = plane.submit(EdgeDelta.from_pairs(insert=[[2, 22]]))
        assert ok["verdict"] == "accepted"
        # no standby anymore: a second promote demands a fresh attach
        with pytest.raises(ValueError):
            plane.promote_shard(1)
    finally:
        plane.close()


# ---- server integration ----------------------------------------------------


def test_writer_shards_one_is_exact_preshard_path(tmp_path, monkeypatch):
    """The default (1 shard) never builds a plane, composes with
    ``wal=`` exactly as before, and publishes byte-identical arrays to
    a pre-shard server fed the same deltas. Plane mode refuses
    ``wal=``/``standby_of=`` loudly."""
    monkeypatch.setenv("GRAPHMINE_QUALITY", "0")
    src, dst, v = _cliques([(0, 12), (12, 26)])
    deltas = [
        {"insert": [[0, 14], [3, 20]], "delete": []},
        {"insert": [[5, 30]], "delete": [[0, 14]]},
    ]

    def run(root, **kw):
        store = SnapshotStore(str(tmp_path / root))
        _publish(store, src, dst, v)
        server = SnapshotServer(store, admission=_generous(), **kw)
        try:
            for p in deltas:
                out = server.apply_delta(dict(p))
                assert out.get("verdict") in (None, "accepted")
                server.wait_applied(timeout=120.0)
        finally:
            server.stop()
        return store.load()

    base = run("a")
    explicit = run("b", writer_shards=1)
    assert explicit.version == base.version
    for name in ("src", "dst", "labels", "cc_labels", "lof"):
        np.testing.assert_array_equal(explicit[name], base[name])
    # 1-shard servers have no plane and no epochs directory
    assert not os.path.exists(str(tmp_path / "b" / "epochs"))

    monkeypatch.setenv("GRAPHMINE_WRITER_SHARDS", "1")
    store = SnapshotStore(str(tmp_path / "c"))
    _publish(store, src, dst, v)
    s = SnapshotServer(store, admission=_generous())
    try:
        assert s.writer_shards == 1
        assert s._tenants["default"].plane is None
    finally:
        s.stop()

    monkeypatch.setenv("GRAPHMINE_WRITER_SHARDS", "3")
    with pytest.raises(ValueError):
        SnapshotServer(store, wal=str(tmp_path / "w"))


def test_sharded_apply_bit_identical_to_single_writer(tmp_path, monkeypatch):
    """THE randomized parity satellite at the system level: N random
    batches (weighted inserts, growth vertices, cross-range deletes)
    through a 3-shard plane and through the classic single-WAL writer —
    every published array (labels, LOF, weights, edges) bit-identical."""
    monkeypatch.setenv("GRAPHMINE_QUALITY", "0")
    rng = np.random.default_rng(23)
    src, dst, v = _cliques([(0, 12), (12, 26), (26, 40)])
    w = (rng.integers(1, 16, len(src)) / 4.0).astype(np.float32)

    batches = []
    cur_edges = list(zip(src.tolist(), dst.tolist()))
    for _ in range(6):
        ins = [
            [int(rng.integers(0, v)), int(rng.integers(0, v + 6)),
             float(rng.integers(1, 8)) / 4.0]
            for _ in range(int(rng.integers(1, 6)))
        ]
        # cross-range deletes: sample real edges (src/dst often owned
        # by different shards)
        k = int(rng.integers(0, 3))
        dels = [list(cur_edges[i]) for i in
                rng.choice(len(cur_edges), size=k, replace=False)]
        for e in dels:
            cur_edges.remove((e[0], e[1]))
        cur_edges.extend((r[0], r[1]) for r in ins)
        batches.append({"insert": ins, "delete": dels})

    def run(root, shards):
        store = SnapshotStore(str(tmp_path / root))
        _publish(store, src, dst, v, weights=w)
        server = SnapshotServer(
            store, admission=_generous(),
            wal=str(tmp_path / root / "wal") if shards == 1 else None,
            writer_shards=shards,
        )
        try:
            for i, p in enumerate(batches):
                out = server.apply_delta(dict(p), delta_id=f"b{i}")
                assert out.get("verdict") in (None, "accepted"), out
                server.wait_applied(timeout=120.0)
        finally:
            server.stop()
        return store.load()

    one = run("one", 1)
    three = run("three", 3)
    assert three.version == one.version
    for name in ("src", "dst", "weights", "labels", "cc_labels", "lof"):
        np.testing.assert_array_equal(three[name], one[name])


def test_plane_server_surfaces_and_gauges(tmp_path, monkeypatch):
    """A 3-shard server's live surfaces: /healthz epoch + per-range
    version vector, /statusz shardplane range table, per-shard-labeled
    WAL gauges on /metrics (the unlabeled pre-shard series absent),
    and the obs_report writer-shards timeline over the stream."""
    monkeypatch.setenv("GRAPHMINE_QUALITY", "0")
    sink = _sink()
    src, dst, v = _cliques([(0, 15), (15, 30)])
    store = SnapshotStore(str(tmp_path / "snap"))
    _publish(store, src, dst, v, sink=sink)
    server = SnapshotServer(
        store, sink=sink, admission=_generous(), writer_shards=3,
    )
    host, port = server.start()
    try:
        out = _post(host, port, "/delta",
                    {"insert": [[0, 5], [0, 16], [0, 25]], "delete": []})
        assert out["version"] == 2
        hz = _get(host, port, "/healthz")
        assert hz["writer_shards"] == 3
        assert hz["epoch"] == 1
        assert hz["shard_versions"] == {"0": 2, "1": 2, "2": 2}
        assert "degraded_shards" not in hz

        sz = _get(host, port, "/statusz")
        table = sz["shardplane"]
        assert table["num_shards"] == 3
        assert [s["shard"] for s in table["shards"]] == [0, 1, 2]
        assert all(s["wal"]["last_seq"] == 1 for s in table["shards"])

        req = urllib.request.Request(f"http://{host}:{port}/metrics")
        with urllib.request.urlopen(req, timeout=30) as r:
            metrics = r.read().decode()
        seq_lines = [
            ln for ln in metrics.splitlines()
            if ln.startswith("graphmine_serve_wal_last_seq{")
        ]
        for s in range(3):
            assert any(f'shard="{s}"' in ln for ln in seq_lines), seq_lines
        # the unlabeled pre-shard series must NOT exist in plane mode
        assert "\ngraphmine_serve_wal_last_seq " not in metrics

        faults.writer_shard_kill(server, 1)
        hz = _get(host, port, "/healthz")
        assert hz["degraded_shards"] == [1]
    finally:
        server.stop()

    from tools.obs_report import build_report

    report = build_report(sink.records)
    assert "writer shards" in report
    assert "EPOCH COMMIT" in report
    assert "SHARD READ_ONLY" in report
    assert validate_records(sink.records) == []


def test_serve_cli_info_reads_shardplane_offline(tmp_path, monkeypatch):
    """``serve_cli info`` reports the committed epoch, version vector
    and per-shard WAL watermarks straight from the store — the RUNBOOKS
    §17 offline triage path (no server process required)."""
    monkeypatch.setenv("GRAPHMINE_QUALITY", "0")
    src, dst, v = _cliques([(0, 15), (15, 30)])
    store = SnapshotStore(str(tmp_path / "snap"))
    _publish(store, src, dst, v)
    server = SnapshotServer(store, admission=_generous(), writer_shards=2)
    try:
        server.apply_delta({"insert": [[0, 5], [0, 20]], "delete": []})
        server.wait_applied(timeout=120.0)
    finally:
        server.stop()

    from tools.serve_cli import _shardplane_info

    info = _shardplane_info(store, store.load())
    assert info["committed_epoch"] == 1
    assert info["num_shards"] == 2
    assert info["version_vector"] == {"0": 2, "1": 2}
    wals = info["shard_wals"]
    assert set(wals) == {"shard-000", "shard-001"}
    assert all(w["last_seq"] == 1 for w in wals.values())


# ---- THE chaos acceptance --------------------------------------------------


def test_shard_kill_chaos_acceptance(tmp_path, monkeypatch):
    """THE ISSUE 17 acceptance: a live 3-shard / 2-tenant server under
    concurrent cross-range bursts loses writer shard 1 mid-burst.

    Pinned from live surfaces: batches touching the dead range 503 with
    the structured range reason while shard-0/2-confined batches AND the
    second tenant keep publishing; /healthz epochs only ever advance and
    every version vector is internally consistent (no mixed-epoch
    reads); a server restart replays the acked tail so ZERO
    acknowledged deltas are lost; the record stream validates clean."""
    monkeypatch.setenv("GRAPHMINE_QUALITY", "0")
    sink = _sink()
    src, dst, v = _cliques([(0, 14), (14, 28), (28, 42)])
    store = SnapshotStore(str(tmp_path / "snap"))
    _publish(store, src, dst, v, sink=sink)
    sb, db, vb = _cliques([(0, 10), (10, 20)])
    _publish(store.for_tenant("tb"), sb, db, vb, sink=sink)

    server = SnapshotServer(
        store, sink=sink, admission=_generous(), writer_shards=3,
    )
    host, port = server.start()
    # ranges: [0,14) [14,28) [28,42)+growth
    acked = []        # (tenant, insert pairs) whose accept we saw
    acked_lock = threading.Lock()
    errors = []
    refused_dead = [0]
    epochs_seen = []
    next_edge = [10_000]

    def fresh_pairs(lo, hi, n=2):
        """Unique never-before-inserted pairs with dst in [lo, hi)."""
        with acked_lock:
            base = next_edge[0]
            next_edge[0] += n
        return [[(base + i) % 14, lo + ((base + i) % (hi - lo))]
                for i in range(n)]

    stop = threading.Event()
    killed = threading.Event()

    def writer(tenant, lo, hi, ack_wal=False):
        i = 0
        while not stop.is_set():
            pairs = fresh_pairs(lo, hi)
            headers = {} if tenant == "default" else {"X-Tenant-Id": tenant}
            if ack_wal:
                # 202 at the durability point: these acks may still be
                # queued when the shard dies — the replay-path half of
                # the zero-acked-loss pin
                headers["X-Delta-Ack"] = "wal"
                headers["X-Delta-Id"] = f"{tenant}-{lo}-{i}"
                i += 1
            try:
                out = _post(
                    host, port, "/delta",
                    {"insert": pairs, "delete": []},
                    headers=headers,
                )
                if out.get("verdict") in (None, "accepted"):
                    with acked_lock:
                        acked.append((tenant, pairs))
            except urllib.error.HTTPError as e:
                body = e.read().decode()
                if e.code == 503 and "degraded vertex range" in body:
                    refused_dead[0] += 1
                elif e.code != 503:
                    errors.append((tenant, e.code, body))
                    return
            except Exception as exc:  # noqa: BLE001 — assert later
                errors.append((tenant, exc))
                return

    threads = [
        threading.Thread(target=writer, args=("default", 0, 14)),
        threading.Thread(target=writer, args=("default", 14, 28, True)),
        threading.Thread(target=writer, args=("default", 28, 42)),
        threading.Thread(target=writer, args=("tb", 0, 20)),
    ]
    for t in threads:
        t.start()
    try:
        import time as _time

        t0 = _time.monotonic()
        while _time.monotonic() - t0 < 8.0:
            hz = _get(host, port, "/healthz")
            if "epoch" in hz:
                epochs_seen.append(hz["epoch"])
                vv = hz["shard_versions"]
                # no mixed-epoch read: one vector, all three ranges
                # present, from ONE committed record
                assert sorted(vv) == ["0", "1", "2"]
            if (not killed.is_set()
                    and _time.monotonic() - t0 > 2.0
                    and len(acked) >= 6):
                faults.writer_shard_kill(server, 1)
                killed.set()
            if killed.is_set() and refused_dead[0] > 0 and \
                    _time.monotonic() - t0 > 5.0:
                break
            _time.sleep(0.05)
        stop.set()
        for t in threads:
            t.join(timeout=60)

        assert killed.is_set(), "storm never reached the kill point"
        assert errors == [], errors
        assert refused_dead[0] > 0, "dead range never refused a batch"
        # epochs only ever advanced — a torn or reverted epoch would
        # show up as a non-monotonic step
        assert epochs_seen == sorted(epochs_seen)

        # untouched ranges (and the OTHER TENANT) still accept, live
        ok = _post(host, port, "/delta",
                   {"insert": [[0, 1]], "delete": []})
        assert ok.get("verdict") in (None, "accepted")
        okb = _post(host, port, "/delta",
                    {"insert": [[0, 1]], "delete": []},
                    headers={"X-Tenant-Id": "tb"})
        assert okb.get("verdict") in (None, "accepted")
        with acked_lock:
            acked.append(("default", [[0, 1]]))
            acked.append(("tb", [[0, 1]]))
        server.wait_applied(timeout=120.0)
    finally:
        stop.set()
        server.stop()

    # zero acked-delta loss: a fresh server over the same store replays
    # every shard's acked-but-unapplied tail (shard 1's closed WAL
    # included) and every acknowledged insert is in the published edges
    server2 = SnapshotServer(
        store, sink=sink, admission=_generous(), writer_shards=3,
    )
    try:
        assert server2.wait_applied(timeout=120.0)
        for tenant in ("default", "tb"):
            snap = (store if tenant == "default"
                    else store.for_tenant("tb")).load()
            have = set(zip(snap["src"].tolist(), snap["dst"].tolist()))
            for t, pairs in acked:
                if t != tenant:
                    continue
                for s, d in pairs:
                    assert (s, d) in have, (
                        f"acked insert ({s},{d}) for {tenant} lost"
                    )
        # the epoch chain converged with the WAL watermarks
        ts = server2._tenants["default"]
        assert ts.plane.coordinator.committed_epoch() >= max(
            epochs_seen or [0]
        )
    finally:
        server2.stop()
    assert validate_records(sink.records) == []
