"""Memory planner + automatic schedule selection (VERDICT r2 item 3).

Pins the replicated↔ring crossover, the loud pre-allocation reject path,
and the driver wiring (--schedule auto default; explicit schedules still
planner-checked; checkpoint cadence rides the same loop).
"""

import os

import numpy as np
import pytest

from graphmine_tpu.pipeline.planner import (
    PlanError,
    estimate_bytes_per_device,
    plan_run,
)

GIB = 1 << 30


def test_plan_lof_applies_measured_crossover():
    """r6: the planner's LOF plan is the ops-layer policy (one owner)
    with the ladder direction derived from it — IVF primary degrades to
    exact, exact primary degrades to IVF."""
    from graphmine_tpu.ops.lof import LOF_IVF_MIN_POINTS
    from graphmine_tpu.pipeline.planner import plan_lof

    small = plan_lof(10_000, 128)
    assert small.impl == "exact" and small.degrade_to == "ivf"
    big = plan_lof(LOF_IVF_MIN_POINTS, 128)
    assert big.impl == "ivf" and big.degrade_to == "exact"
    assert "3.1x" in big.reason  # measured provenance rides the plan
    forced = plan_lof(10**8, 128, requested="xla")
    assert forced.impl == "exact"
    assert plan_lof(100, 16, requested="ivf").impl == "ivf"
    assert plan_lof(10_000, 128, ivf_min_points=1000).impl == "ivf"


def test_single_device_selects_fused_kernel():
    p = plan_run(1 << 20, 1 << 23, num_devices=1)
    assert p.schedule == "single" and not p.lpa_only
    # DESIGN.md: the north-star config (~100M edges) uses ~3.6 GB
    ns = plan_run(1 << 24, 100_000_000, num_devices=1)
    assert ns.schedule == "single"
    assert 3.3 * GIB < ns.bytes_per_device < 4.2 * GIB


def test_small_multi_device_selects_replicated():
    p = plan_run(1 << 20, 1 << 23, num_devices=8)
    assert p.schedule == "replicated" and p.lpa_only
    # speed-preference order: replicated wins when it fits, even though
    # ring models *smaller* here (no replicated V-term)
    assert p.estimates["ring"] < p.estimates["replicated"]
    assert "fastest" in p.reason


def test_crossover_300m_vertices_selects_ring():
    """The VERDICT scenario: 300M vertices (with a natural ~2.5B-edge
    graph) on 8 devices must route to ring without user knowledge —
    replicated's V-terms don't fit next to the sharded edge arrays."""
    v, e, d = 300_000_000, 2_500_000_000, 8
    assert estimate_bytes_per_device("replicated", v, e, d) > 0.9 * 16 * GIB
    p = plan_run(v, e, num_devices=d)
    assert p.schedule == "ring" and not p.lpa_only
    assert "sharded" in p.reason
    assert p.bytes_per_device <= p.hbm_bytes


def test_reject_path_is_loud_and_numeric():
    with pytest.raises(PlanError) as ei:
        plan_run(2_000_000_000, 40_000_000_000, num_devices=2)
    msg = str(ei.value)
    assert "no LPA schedule fits" in msg
    assert "GiB" in msg and "Add devices" in msg
    # numbers for every candidate schedule appear
    assert "replicated=" in msg and "ring=" in msg


def test_int32_message_overflow_rejected_at_plan_time(monkeypatch):
    """VERDICT r4 item 6: a per-device message count past 2^31-1 must fail
    LOUDLY at plan time — not rely on HBM byte budgets coincidentally
    rejecting it first, and never wrap silently at gather time."""
    # Single device, E such that M = 2E > int32 range, with an HBM
    # override huge enough that bytes alone would accept the config —
    # isolating the index bound as the thing that rejects it.
    monkeypatch.setenv("GRAPHMINE_HBM_BYTES", str(1 << 46))  # 64 TiB part
    e = 1_200_000_000  # M = 2.4B messages
    with pytest.raises(PlanError) as ei:
        plan_run(1 << 26, e, num_devices=1)
    msg = str(ei.value)
    assert "int32" in msg and "2,147,483,647" in msg
    assert "SILENTLY" in msg and "devices" in msg

    # explicit request for an overflowing sharded schedule: same wall
    with pytest.raises(PlanError, match="int32"):
        plan_run(1 << 26, 4_000_000_000, num_devices=2, requested="replicated")

    # enough devices: the same edge count plans fine (auto path)
    p = plan_run(1 << 26, e, num_devices=4)
    assert p.schedule in ("replicated", "ring")

    # the error's minimum-device hint is itself sufficient
    from graphmine_tpu.pipeline.planner import (
        _INT32_MAX,
        messages_per_device,
    )

    for s in ("replicated", "ring"):
        assert messages_per_device(s, e, 4) <= _INT32_MAX


def test_host_graph_int64_ptr_and_device_guard():
    """Companion container guards: a host CSR past int32 keeps an int64
    ptr (it exists to be partitioned), while DEVICE assembly of such a
    CSR raises with the remedy. Exercised with a fabricated ptr — 2^31
    real messages would need ~16 GB of host RAM in a unit test."""
    from graphmine_tpu.graph.container import _graph_from_csr

    ptr = np.array([0, (1 << 31) + 5], dtype=np.int64)
    tiny = np.zeros(4, np.int32)
    with pytest.raises(ValueError, match="int32 gather-index"):
        _graph_from_csr(tiny, tiny, ptr, tiny, tiny, 1, True)


def test_explicit_schedule_that_cannot_fit_names_the_one_that_would():
    v, e, d = 300_000_000, 2_500_000_000, 8
    with pytest.raises(PlanError, match="'ring' would fit"):
        plan_run(v, e, num_devices=d, requested="replicated")


def test_explicit_ring_on_one_device_maps_to_single():
    p = plan_run(1 << 16, 1 << 18, num_devices=1, requested="ring")
    assert p.schedule == "single"


def test_weighted_raises_estimates():
    kw = dict(num_vertices=1 << 20, num_edges=1 << 24, num_devices=4)
    for s in ("replicated", "ring"):
        assert estimate_bytes_per_device(s, weighted=True, **kw) > \
            estimate_bytes_per_device(s, weighted=False, **kw)


def test_hbm_env_override(monkeypatch):
    """A tiny budget forces ring early; a huge one keeps replicated."""
    v, e, d = 100_000_000, 200_000_000, 8
    monkeypatch.setenv("GRAPHMINE_HBM_BYTES", str(2 * GIB))
    assert plan_run(v, e, num_devices=d).schedule == "ring"
    monkeypatch.setenv("GRAPHMINE_HBM_BYTES", str(64 * GIB))
    assert plan_run(v, e, num_devices=d).schedule == "replicated"


def test_hbm_precedence_env_device_default(monkeypatch):
    """VERDICT r3 item 3: env var → device-reported bytes → 16 GiB."""
    from graphmine_tpu.pipeline.planner import hbm_bytes_per_device

    monkeypatch.delenv("GRAPHMINE_HBM_BYTES", raising=False)
    assert hbm_bytes_per_device() == 16 * GIB
    # device-reported value (a v4 part) wins over the default
    assert hbm_bytes_per_device(device_bytes=32 * GIB) == 32 * GIB
    # env var wins over both
    monkeypatch.setenv("GRAPHMINE_HBM_BYTES", str(2 * GIB))
    assert hbm_bytes_per_device(device_bytes=32 * GIB) == 2 * GIB
    # a zero/None device report falls through to the default
    monkeypatch.delenv("GRAPHMINE_HBM_BYTES")
    assert hbm_bytes_per_device(device_bytes=0) == 16 * GIB
    assert hbm_bytes_per_device(device_bytes=None) == 16 * GIB
    # lazy callable form: evaluated when env did not win...
    assert hbm_bytes_per_device(device_bytes=lambda: 32 * GIB) == 32 * GIB
    # ...and NEVER evaluated when it did (an env-pinned budget must not
    # touch a flaky runtime's memory query — code-review r4)
    monkeypatch.setenv("GRAPHMINE_HBM_BYTES", str(2 * GIB))

    def boom():
        raise AssertionError("device queried despite env override")

    assert hbm_bytes_per_device(device_bytes=boom) == 2 * GIB


def test_device_hbm_bytes_memory_stats_chain(monkeypatch):
    """The driver's device query: bytes_limit when reported — the MIN
    across all local devices since ISSUE 14 — None on CPU
    (memory_stats() -> None), None when the runtime raises."""
    import jax

    from graphmine_tpu.pipeline import driver

    class _Dev:
        def __init__(self, stats=None, raise_=False):
            self._stats, self._raise = stats, raise_

        def memory_stats(self):
            if self._raise:
                raise RuntimeError("tunneled runtime")
            return self._stats

    def fake_devices(*devs):
        return lambda *a, **k: list(devs)

    # a v5p part reporting ~95 GiB
    monkeypatch.setattr(
        jax, "local_devices", fake_devices(_Dev({"bytes_limit": 95 * GIB}))
    )
    assert driver.device_hbm_bytes() == 95 * GIB
    # heterogeneous mesh: the smallest chip governs the budget
    monkeypatch.setattr(
        jax, "local_devices",
        fake_devices(_Dev({"bytes_limit": 95 * GIB}),
                     _Dev({"bytes_limit": 16 * GIB})),
    )
    assert driver.device_hbm_bytes() == 16 * GIB
    # CPU backend: memory_stats() is None (measured on this jax build)
    monkeypatch.setattr(jax, "local_devices", fake_devices(_Dev(None)))
    assert driver.device_hbm_bytes() is None
    # stats dict without the key, or a raising runtime -> None
    monkeypatch.setattr(
        jax, "local_devices", fake_devices(_Dev({"other": 1}))
    )
    assert driver.device_hbm_bytes() is None
    monkeypatch.setattr(
        jax, "local_devices", fake_devices(_Dev(raise_=True))
    )
    assert driver.device_hbm_bytes() is None


def test_pipeline_plan_uses_device_reported_hbm(monkeypatch, tmp_path):
    """End-to-end chain: with no env override, the driver budgets against
    what the device reports — a mocked 1 MiB part forces the planner to
    reject a graph the 16 GiB default would happily accept."""
    import jax

    from graphmine_tpu.pipeline import driver

    rng = np.random.default_rng(0)
    path = tmp_path / "edges.txt"
    src = rng.integers(0, 2000, 30000)
    dst = rng.integers(0, 2000, 30000)
    path.write_text(
        "\n".join(f"a{a} b{b}" for a, b in zip(src, dst)) + "\n"
    )
    monkeypatch.delenv("GRAPHMINE_HBM_BYTES", raising=False)

    class _Tiny:
        def memory_stats(self):
            return {"bytes_limit": 1 << 20}

    monkeypatch.setattr(jax, "local_devices", lambda *a, **k: [_Tiny()])
    with pytest.raises(PlanError, match="no LPA schedule fits"):
        driver.run_pipeline(_tiny_config(
            data_path=str(path), data_format="edgelist", num_devices=1,
        ))


# ---------------------------------------------------------------------------
# driver wiring
# ---------------------------------------------------------------------------


_SYNTH = {}


def _synthetic_edgelist() -> str:
    """Deterministic stand-in for the bundled reference parquet (absent in
    some containers): same V/E scale (V=4613, E=18399), so every byte
    threshold in these tests — the 300 KB scale-out budget, the wedge
    budget, the replicated-fits/single-doesn't split — models identically.
    A chain over all V vertices guarantees full id coverage; the remaining
    edges are uniform random."""
    if "path" not in _SYNTH:
        from conftest import cached_edgelist

        v, e = 4613, 18399
        rng = np.random.default_rng(20260802)
        chain = np.arange(v, dtype=np.int64)
        src = np.concatenate([chain, rng.integers(0, v, e - v)])
        dst = np.concatenate([(chain + 1) % v, rng.integers(0, v, e - v)])
        text = "".join(f"{s} {t}\n" for s, t in zip(src, dst))
        _SYNTH["path"] = cached_edgelist("graphmine_synth", text)
    return _SYNTH["path"]


def _tiny_config(**kw):
    from graphmine_tpu.pipeline.config import PipelineConfig

    defaults = dict(
        outlier_method="none", max_iter=3,
        data_path=_synthetic_edgelist(), data_format="edgelist",
    )
    defaults.update(kw)
    return PipelineConfig(**defaults)


def test_pipeline_auto_schedule_emits_plan_and_runs(tmp_path):
    """Default --schedule auto: the plan event lands in metrics and the
    run completes; on 8 virtual devices with a small graph the planner
    picks replicated."""
    from graphmine_tpu.pipeline.driver import run_pipeline

    res = run_pipeline(_tiny_config(num_devices=8))
    plans = [r for r in res.metrics.records if r.get("phase") == "plan"]
    assert plans and plans[0]["schedule"] == "replicated"
    assert plans[0]["bytes_per_device"] > 0
    assert res.num_communities > 0


def test_pipeline_auto_schedule_single_device():
    from graphmine_tpu.pipeline.driver import run_pipeline

    res = run_pipeline(_tiny_config(num_devices=1))
    plans = [r for r in res.metrics.records if r.get("phase") == "plan"]
    assert plans and plans[0]["schedule"] == "single"
    assert res.num_communities > 0


def test_pipeline_wedge_budget_reroutes_lof_features(monkeypatch):
    """r5 OOM fix: past GRAPHMINE_WEDGE_BUDGET the LOF phase must use the
    wedge-sampled clustering column instead of the exact expansion (the
    exact pipeline materializes ~28 B/wedge on the host and was OOM-
    killed at 130 GB on the first e2e capture). A budget of 1 forces the
    reroute on the bundled data; the phase event and warning say so."""
    from graphmine_tpu.pipeline.driver import run_pipeline

    monkeypatch.setenv("GRAPHMINE_WEDGE_BUDGET", "1")
    res = run_pipeline(
        _tiny_config(num_devices=1, outlier_method="lof", lof_k=16)
    )
    lof_events = [r for r in res.metrics.records
                  if r.get("phase") == "outliers_lof"]
    assert lof_events and lof_events[0]["features"] == "device-8-sampled"
    warns = [r for r in res.metrics.records if r.get("phase") == "warning"]
    assert any("wedge" in w["message"].lower() for w in warns)
    assert res.lof is not None and len(res.lof) == res.graph.num_vertices

    # default budget: bundled data is far below it -> exact features
    monkeypatch.delenv("GRAPHMINE_WEDGE_BUDGET")
    res2 = run_pipeline(
        _tiny_config(num_devices=1, outlier_method="lof", lof_k=16)
    )
    lof_events = [r for r in res2.metrics.records
                  if r.get("phase") == "outliers_lof"]
    assert lof_events and lof_events[0]["features"] == "device-8"


def test_pipeline_impossible_config_fails_before_allocation(monkeypatch):
    """The loud plan-time error: a budget no schedule fits under raises
    PlanError during run_pipeline, before any partition/device work."""
    from graphmine_tpu.pipeline.driver import run_pipeline

    monkeypatch.setenv("GRAPHMINE_HBM_BYTES", "1000")  # ~1 KB budget
    with pytest.raises(PlanError, match="no LPA schedule fits"):
        run_pipeline(_tiny_config(num_devices=8))


def test_checkpoint_cadence(tmp_path, monkeypatch):
    """checkpoint_every=2 with max_iter=5 saves supersteps 2, 4 and the
    final 5 (never stale at completion); default 1 saves every step."""
    from graphmine_tpu.pipeline import driver as drv

    saved = []
    real = drv.ckpt.save_labels

    def spy(d, labels, iteration, **kw):
        saved.append(iteration)
        return real(d, labels, iteration, **kw)

    monkeypatch.setattr(drv.ckpt, "save_labels", spy)
    drv.run_pipeline(_tiny_config(
        num_devices=1, max_iter=5,
        checkpoint_dir=str(tmp_path), checkpoint_every=2,
    ))
    assert saved == [2, 4, 5]

    saved.clear()
    drv.run_pipeline(_tiny_config(
        num_devices=1, max_iter=3,
        checkpoint_dir=str(tmp_path / "b"), checkpoint_every=1,
    ))
    assert saved == [1, 2, 3]


def test_checkpoint_every_validation():
    with pytest.raises(ValueError, match="checkpoint_every"):
        _tiny_config(checkpoint_every=0).validate()


def test_scale_out_mode_host_graph_pipeline(monkeypatch):
    """r3 scale-out: when the planner picks a distributed schedule AND the
    full graph cannot also fit one device, the pipeline keeps the graph
    host-side (census/modularity via NumPy twins) and produces identical
    labels/census to the device path. r4 (VERDICT r3 item 2): the
    recursive-LPA outlier pass now RUNS in scale-out mode — distributed
    over the planner-resolved schedule — and must match the single-device
    masked pass exactly, as must the sharded LOF scores."""
    import numpy as np

    from graphmine_tpu.pipeline.driver import run_pipeline

    # reference run: plenty of budget, device graph, same 8-device mesh
    ref = run_pipeline(_tiny_config(
        num_devices=8, max_iter=3, outlier_method="both",
    ))
    assert ref.outliers is not None

    # bundled graph models: single ~699 KB, replicated ~157 KB/device,
    # ring ~97 KB/device. 0.9 * 300000 = 270 KB -> replicated fits,
    # single does not => scale-out with the replicated schedule.
    monkeypatch.setenv("GRAPHMINE_HBM_BYTES", "300000")
    res = run_pipeline(_tiny_config(
        num_devices=8, max_iter=3, outlier_method="both",
    ))
    plans = [r for r in res.metrics.records if r.get("phase") == "plan"]
    assert plans[0]["schedule"] == "replicated"
    assert any(r.get("phase") == "scale_out" for r in res.metrics.records)
    np.testing.assert_array_equal(res.labels, ref.labels)
    p0, s0, e0 = ref.community_table
    p1, s1, e1 = res.community_table
    np.testing.assert_array_equal(p0, p1)
    np.testing.assert_array_equal(s0, s1)
    np.testing.assert_array_equal(e0, e1)
    # host graph really is host-resident numpy
    assert isinstance(res.graph.src, np.ndarray)
    # recursive-LPA outliers run distributed and match the single-device
    # masked pass bit-for-bit (VERDICT r3 item 2)
    assert res.outliers is not None
    np.testing.assert_array_equal(
        res.outliers.sub_labels, ref.outliers.sub_labels
    )
    np.testing.assert_array_equal(
        res.outliers.outlier_vertices, ref.outliers.outlier_vertices
    )
    np.testing.assert_array_equal(res.outliers.sub_sizes, ref.outliers.sub_sizes)
    assert res.outliers.thresholds == ref.outliers.thresholds
    out_rec = [r for r in res.metrics.records
               if r.get("phase") == "outliers_recursive_lpa"]
    assert out_rec and out_rec[0]["schedule"] == "replicated"
    # LOF still runs via the host feature twin + sharded scorer
    assert res.lof is not None and res.lof.shape == (res.graph.num_vertices,)
    lof_rec = [r for r in res.metrics.records if r.get("phase") == "outliers_lof"]
    assert lof_rec and lof_rec[0]["features"] == "host-8-sampled"
    # modularity host twin agrees with the device value
    comm = [r for r in res.metrics.records if r.get("phase") == "communities"][0]
    ref_comm = [r for r in ref.metrics.records if r.get("phase") == "communities"][0]
    assert abs(comm["modularity"] - ref_comm["modularity"]) < 1e-4

    # 0.9 * 120000 = 108 KB -> only ring fits; same labels, and the
    # outlier pass rides the ring schedule with the same result
    monkeypatch.setenv("GRAPHMINE_HBM_BYTES", "120000")
    res_ring = run_pipeline(_tiny_config(
        num_devices=8, max_iter=3, outlier_method="recursive_lpa",
    ))
    plans = [r for r in res_ring.metrics.records if r.get("phase") == "plan"]
    assert plans[0]["schedule"] == "ring"
    np.testing.assert_array_equal(res_ring.labels, ref.labels)
    assert res_ring.outliers is not None
    np.testing.assert_array_equal(
        res_ring.outliers.outlier_vertices, ref.outliers.outlier_vertices
    )
    out_rec = [r for r in res_ring.metrics.records
               if r.get("phase") == "outliers_recursive_lpa"]
    assert out_rec and out_rec[0]["schedule"] == "ring"


def test_vertex_features_host_parity(bundled_graph):
    """The NumPy feature twin matches the device feature matrix within
    float32 rounding when the clustering column is included."""
    import numpy as np

    from graphmine_tpu.graph.container import build_graph
    from graphmine_tpu.ops.features import vertex_features, vertex_features_host
    from graphmine_tpu.ops.lpa import label_propagation

    g = bundled_graph
    labels = np.asarray(label_propagation(g, max_iter=3))
    want = np.asarray(vertex_features(g, labels))
    host_g = build_graph(
        np.asarray(g.src), np.asarray(g.dst),
        num_vertices=g.num_vertices, to_device=False,
    )
    got = vertex_features_host(host_g, labels, include_clustering=True)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6)

    # clustering omitted -> same first 7 columns, zero last column
    got7 = vertex_features_host(host_g, labels, include_clustering=False)
    np.testing.assert_allclose(got7[:, :7], want[:, :7], rtol=2e-5, atol=2e-6)
    assert not got7[:, 7].any()

    # sampled clustering (the r4 scale-out default): same first 7 columns,
    # last column tracks the exact coefficient within the binomial bound
    gots = vertex_features_host(
        host_g, labels, include_clustering="sampled", clustering_samples=256
    )
    np.testing.assert_allclose(gots[:, :7], want[:, :7], rtol=2e-5, atol=2e-6)
    err = np.abs(gots[:, 7] - want[:, 7])
    assert err.max() <= 4.5 * 0.5 / np.sqrt(256) + 1e-6
    assert err.mean() <= 1.5 * 0.5 / np.sqrt(256)
