"""Propagation-blocking superstep engine (ops/blocking.py, ISSUE 7).

Parity suite pinning blocked supersteps bit-identical to the sort-based
``segment_mode`` oracle across power-law / ring / self-loop /
isolated-vertex / duplicate-edge graphs, for LPA / CC / PageRank, fused
and sharded; plus the crossover policy owner, the planner family seam,
the ``plan_build`` observability records, the weighted-payload contract,
and the ``blocking`` bench-tier body smoke.

Marker: ``blocking`` (``tools/run_tier1.sh --blocking-only``).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from graphmine_tpu.graph.container import build_graph
from graphmine_tpu.ops.blocking import (
    BLOCKED_MIN_MESSAGES,
    BLOCKED_MIN_VERTICES,
    BUCKETED_MIN_MESSAGES,
    BlockedPlan,
    blocked_inflow,
    build_graph_and_blocked_plan,
    cc_superstep_blocked,
    lpa_superstep_blocked,
    plan_build_stats,
    select_superstep_family,
)
from graphmine_tpu.ops.cc import connected_components
from graphmine_tpu.ops.lpa import label_propagation
from graphmine_tpu.ops.pagerank import pagerank

pytestmark = pytest.mark.blocking

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _power_law(rng):
    v, e = 600, 4000
    raw = rng.pareto(1.2, size=2 * e)
    ids = np.minimum((raw * v / 50).astype(np.int64), v - 1).astype(np.int32)
    return ids[:e], ids[e:], v


def _ring(rng):
    v = 257
    src = np.arange(v, dtype=np.int32)
    return src, np.roll(src, -1).astype(np.int32), v


def _self_loops(rng):
    v, e = 300, 1500
    src = rng.integers(0, v, e).astype(np.int32)
    dst = rng.integers(0, v, e).astype(np.int32)
    dst[::7] = src[::7]
    return src, dst, v


def _isolated(rng):
    # vertices [200, 300) never appear in any edge
    v, e = 300, 1200
    src = rng.integers(0, 200, e).astype(np.int32)
    dst = rng.integers(0, 200, e).astype(np.int32)
    return src, dst, v


def _dup_edges(rng):
    v, e = 250, 900
    src = rng.integers(0, v, e).astype(np.int32)
    dst = rng.integers(0, v, e).astype(np.int32)
    # duplicate one hot edge many times (multiplicity must count)
    src[: e // 3] = src[0]
    dst[: e // 3] = dst[0]
    return src, dst, v


GRAPHS = {
    "power_law": _power_law,
    "ring": _ring,
    "self_loops": _self_loops,
    "isolated": _isolated,
    "dup_edges": _dup_edges,
}


@pytest.fixture(params=sorted(GRAPHS), ids=sorted(GRAPHS))
def edges(request):
    return GRAPHS[request.param](np.random.default_rng(3))


# ---- fused parity ----------------------------------------------------------


def test_lpa_blocked_bit_identical(edges):
    src, dst, v = edges
    g = build_graph(src, dst, num_vertices=v)
    plan = BlockedPlan.from_graph(g, tile_slots=193)  # force several bins
    ref = np.asarray(label_propagation(g, 5, plan=None))
    got = np.asarray(label_propagation(g, 5, plan=plan))
    np.testing.assert_array_equal(ref, got)


def test_lpa_blocked_per_superstep(edges):
    """Step-for-step identity against the sort superstep, not just the
    final labels (catches off-by-one-superstep compensation)."""
    import jax.numpy as jnp

    from graphmine_tpu.ops.lpa import lpa_superstep

    src, dst, v = edges
    g = build_graph(src, dst, num_vertices=v)
    plan = BlockedPlan.from_graph(g, tile_slots=100)
    lbl = jnp.arange(v, dtype=jnp.int32)
    for _ in range(4):
        ref = lpa_superstep(lbl, g)
        got = lpa_superstep_blocked(lbl, g, plan)
        np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))
        lbl = ref


def test_cc_blocked_bit_identical(edges):
    src, dst, v = edges
    g = build_graph(src, dst, num_vertices=v)
    plan = BlockedPlan.from_graph(g, tile_slots=151)
    ref = np.asarray(connected_components(g, plan=None))
    got = np.asarray(connected_components(g, plan=plan))
    np.testing.assert_array_equal(ref, got)


def test_cc_superstep_blocked_matches_oracle_step(edges):
    import jax.numpy as jnp

    from graphmine_tpu.ops.cc import cc_superstep

    src, dst, v = edges
    g = build_graph(src, dst, num_vertices=v)
    plan = BlockedPlan.from_graph(g, tile_slots=96)
    lbl = jnp.arange(v, dtype=jnp.int32)
    for _ in range(3):
        ref = cc_superstep(lbl, g)
        got = cc_superstep_blocked(lbl, plan)
        np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))
        lbl = ref


def test_pagerank_blocked_matches(edges):
    src, dst, v = edges
    g = build_graph(src, dst, num_vertices=v, symmetric=False)
    plan = BlockedPlan.from_graph(g, tile_slots=128)
    ref = np.asarray(pagerank(g, plan=None))
    got = np.asarray(pagerank(g, plan=plan))
    # float sums reassociate across the row layout: tolerance, not bits
    np.testing.assert_allclose(ref, got, rtol=2e-5, atol=1e-8)
    assert abs(float(got.sum()) - 1.0) < 1e-4


def test_blocked_inflow_matches_segment_sum():
    import jax

    rng = np.random.default_rng(9)
    src, dst, v = _power_law(rng)
    g = build_graph(src, dst, num_vertices=v, symmetric=False)
    plan = BlockedPlan.from_graph(g, tile_slots=200)
    contrib = rng.random(v).astype(np.float32)
    ref = jax.ops.segment_sum(
        contrib[np.asarray(g.src)], np.asarray(g.dst), num_segments=v
    )
    got = blocked_inflow(plan, contrib)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(got), rtol=2e-5)


def test_multi_bin_layout_and_stats():
    rng = np.random.default_rng(4)
    src, dst, v = _power_law(rng)
    g, plan = build_graph_and_blocked_plan(
        src, dst, num_vertices=v, tile_slots=64
    )
    assert plan.num_bins > 1
    assert plan.tile_slots >= 64 or plan.num_bins == 1
    stats = plan_build_stats(plan, g.num_edges)
    assert stats["family"] == "blocked"
    assert stats["bins"] == plan.num_bins
    assert stats["padded_slots_per_edge"] > 0
    ref = np.asarray(label_propagation(g, 5, plan=None))
    got = np.asarray(label_propagation(g, 5, plan=plan))
    np.testing.assert_array_equal(ref, got)


def test_plan_graph_mismatch_refuses():
    """A same-V plan from a DIFFERENT graph must refuse on every explicit
    plan seam (LPA, CC, PageRank) — it would silently mis-reduce."""
    rng = np.random.default_rng(5)
    src, dst, v = _self_loops(rng)
    g = build_graph(src, dst, num_vertices=v)
    other = build_graph(src[: len(src) // 2], dst[: len(dst) // 2],
                        num_vertices=v)
    plan = BlockedPlan.from_graph(other)
    with pytest.raises(ValueError, match="mismatch"):
        label_propagation(g, 2, plan=plan)
    with pytest.raises(ValueError, match="mismatch"):
        connected_components(g, plan=plan)
    g_dir = build_graph(src, dst, num_vertices=v, symmetric=False)
    other_dir = build_graph(src[: len(src) // 2], dst[: len(dst) // 2],
                            num_vertices=v, symmetric=False)
    with pytest.raises(ValueError, match="mismatch"):
        pagerank(g_dir, plan=BlockedPlan.from_graph(other_dir))


# ---- weighted contract -----------------------------------------------------


def test_weighted_lpa_blocked_bit_identical(edges):
    src, dst, v = edges
    w = np.random.default_rng(6).random(len(src)).astype(np.float32)
    g = build_graph(src, dst, num_vertices=v, edge_weights=w)
    plan = BlockedPlan.from_graph(g, tile_slots=160)
    assert plan.weight_mat is not None
    ref = np.asarray(label_propagation(g, 5, plan=None))
    got = np.asarray(label_propagation(g, 5, plan=plan))
    np.testing.assert_array_equal(ref, got)


def test_weighted_graph_weightless_plan_refuses():
    """The serving layer's weighted contract (serve/delta.py): weights
    are never silently dropped — a blocked plan without the slot-aligned
    payload refuses loudly on a weighted graph."""
    rng = np.random.default_rng(7)
    src, dst, v = _self_loops(rng)
    w = rng.random(len(src)).astype(np.float32)
    g_unw = build_graph(src, dst, num_vertices=v)
    g_w = build_graph(src, dst, num_vertices=v, edge_weights=w)
    weightless = BlockedPlan.from_graph(g_unw)
    with pytest.raises(ValueError, match="weight"):
        lpa_superstep_blocked(
            np.arange(v, dtype=np.int32), g_w, weightless
        )


def test_pagerank_blocked_refusals():
    rng = np.random.default_rng(8)
    src, dst, v = _self_loops(rng)
    g_sym = build_graph(src, dst, num_vertices=v)
    plan_sym = BlockedPlan.from_graph(g_sym)
    with pytest.raises(ValueError, match="directed"):
        pagerank(g_sym, plan=plan_sym)
    g_dir = build_graph(src, dst, num_vertices=v, symmetric=False)
    plan_dir = BlockedPlan.from_graph(g_dir)
    w = rng.random(len(src)).astype(np.float32)
    with pytest.raises(ValueError, match="weight"):
        pagerank(g_dir, weights=w, plan=plan_dir)


# ---- sharded parity --------------------------------------------------------


def _mesh8():
    import jax

    from graphmine_tpu.parallel.mesh import make_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    return make_mesh(8)


def test_sharded_lpa_blocked_bit_identical(edges):
    from graphmine_tpu.parallel.sharded import (
        partition_graph,
        shard_graph_arrays,
        sharded_label_propagation,
    )

    src, dst, v = edges
    g = build_graph(src, dst, num_vertices=v)
    mesh = _mesh8()
    sg = shard_graph_arrays(
        partition_graph(
            g, mesh=mesh, build_blocked_plan=True, blocked_tile_slots=48
        ),
        mesh,
    )
    assert sg.blk_src is not None
    ref = np.asarray(label_propagation(g, 5, plan=None))
    got = np.asarray(sharded_label_propagation(sg, mesh, max_iter=5))
    np.testing.assert_array_equal(ref, got)


def test_sharded_cc_blocked_bit_identical(edges):
    from graphmine_tpu.parallel.sharded import (
        partition_graph,
        shard_graph_arrays,
        sharded_connected_components,
    )

    src, dst, v = edges
    g = build_graph(src, dst, num_vertices=v)
    mesh = _mesh8()
    sg = shard_graph_arrays(
        partition_graph(
            g, mesh=mesh, build_blocked_plan=True, blocked_tile_slots=48
        ),
        mesh,
    )
    ref = np.asarray(connected_components(g, plan=None))
    got = np.asarray(sharded_connected_components(sg, mesh))
    np.testing.assert_array_equal(ref, got)


def test_sharded_weighted_lpa_blocked_bit_identical():
    from graphmine_tpu.parallel.sharded import (
        partition_graph,
        shard_graph_arrays,
        sharded_label_propagation,
    )

    rng = np.random.default_rng(10)
    src, dst, v = _power_law(rng)
    w = rng.random(len(src)).astype(np.float32)
    g = build_graph(src, dst, num_vertices=v, edge_weights=w)
    mesh = _mesh8()
    sg = shard_graph_arrays(
        partition_graph(
            g, mesh=mesh, build_blocked_plan=True, blocked_tile_slots=48
        ),
        mesh,
    )
    assert sg.blk_row_weight
    ref = np.asarray(label_propagation(g, 5, plan=None))
    got = np.asarray(sharded_label_propagation(sg, mesh, max_iter=5))
    np.testing.assert_array_equal(ref, got)


def test_sharded_blocked_lpa_only_trimming():
    from graphmine_tpu.parallel.sharded import (
        partition_graph,
        shard_graph_arrays,
        sharded_label_propagation,
    )

    rng = np.random.default_rng(11)
    src, dst, v = _self_loops(rng)
    g = build_graph(src, dst, num_vertices=v)
    mesh = _mesh8()
    sg = shard_graph_arrays(
        partition_graph(g, mesh=mesh, build_blocked_plan=True), mesh,
        lpa_only=True,
    )
    assert sg.msg_send is None  # sort-body arrays dropped
    ref = np.asarray(label_propagation(g, 5, plan=None))
    got = np.asarray(sharded_label_propagation(sg, mesh, max_iter=5))
    np.testing.assert_array_equal(ref, got)


def test_partition_plan_flags_mutually_exclusive():
    from graphmine_tpu.parallel.sharded import partition_graph

    rng = np.random.default_rng(12)
    src, dst, v = _self_loops(rng)
    with pytest.raises(ValueError, match="mutually exclusive"):
        partition_graph(
            src, dst, num_vertices=v, num_shards=4,
            build_bucket_plan=True, build_blocked_plan=True,
        )


# ---- crossover policy + planner seam ---------------------------------------


def test_family_policy_thresholds():
    fam, reason = select_superstep_family(10, 100)
    assert fam == "sort" and "65536" in reason
    fam, _ = select_superstep_family(1000, BUCKETED_MIN_MESSAGES)
    assert fam == "bucketed"
    # message count alone is not enough: the value table must also be
    # past on-chip capacity for blocked to win
    fam, _ = select_superstep_family(1000, BLOCKED_MIN_MESSAGES)
    assert fam == "bucketed"
    fam, reason = select_superstep_family(
        BLOCKED_MIN_VERTICES, BLOCKED_MIN_MESSAGES
    )
    assert fam == "blocked" and "blocking" in reason


def test_family_policy_env_overrides(monkeypatch):
    monkeypatch.setenv("GRAPHMINE_BLOCKED_MIN_MESSAGES", "1")
    monkeypatch.setenv("GRAPHMINE_BLOCKED_MIN_VERTICES", "1")
    fam, _ = select_superstep_family(100, BUCKETED_MIN_MESSAGES)
    assert fam == "blocked"
    monkeypatch.setenv("GRAPHMINE_SUPERSTEP_FAMILY", "sort")
    fam, reason = select_superstep_family(1 << 24, 1 << 24)
    assert fam == "sort" and "env override" in reason
    monkeypatch.setenv("GRAPHMINE_SUPERSTEP_FAMILY", "nope")
    with pytest.raises(ValueError, match="GRAPHMINE_SUPERSTEP_FAMILY"):
        select_superstep_family(1 << 24, 1 << 24)


def test_family_policy_requested_validation():
    fam, reason = select_superstep_family(10, 10, requested="blocked")
    assert fam == "blocked" and "requested" in reason
    with pytest.raises(ValueError, match="unknown superstep family"):
        select_superstep_family(10, 10, requested="warp")


def test_planner_superstep_plan_and_ladder():
    from graphmine_tpu.pipeline.planner import (
        degradation_ladder,
        plan_superstep,
    )

    p = plan_superstep(BLOCKED_MIN_VERTICES, BLOCKED_MIN_MESSAGES)
    assert p.family == "blocked" and p.degrade_to == "bucketed"
    p2 = plan_superstep(1000, BUCKETED_MIN_MESSAGES)
    assert p2.family == "bucketed" and p2.degrade_to == "sort"
    # the blocked→bucketed degradation rung shows up in the ladder
    assert degradation_ladder("single", 1, family="blocked") == [
        "single_bucketed", "single_sort",
    ]
    assert degradation_ladder("single", 1) == ["single_sort"]
    assert degradation_ladder("replicated", 8, family="blocked") == ["ring"]


# ---- auto seam + plan_build observability ----------------------------------


def test_auto_seam_resolves_blocked_with_parity(monkeypatch):
    """With the crossover forced down, plan='auto' flips LPA and CC to
    the blocked family end-to-end — identical labels, and the
    impl_selected + plan_build provenance pair lands in the sink,
    schema-valid."""
    from graphmine_tpu.obs.schema import validate_records
    from graphmine_tpu.pipeline.metrics import MetricsSink

    rng = np.random.default_rng(13)
    src, dst, v = _power_law(rng)
    g = build_graph(src, dst, num_vertices=v)
    ref_l = np.asarray(label_propagation(g, 5, plan=None))
    ref_c = np.asarray(connected_components(g, plan=None))

    monkeypatch.setenv("GRAPHMINE_SUPERSTEP_FAMILY", "blocked")
    sink = MetricsSink()
    got_l = np.asarray(label_propagation(g, 5, plan="auto", sink=sink))
    got_c = np.asarray(connected_components(g, plan="auto", sink=sink))
    np.testing.assert_array_equal(ref_l, got_l)
    np.testing.assert_array_equal(ref_c, got_c)

    sel = sink.of_phase("impl_selected")
    assert [r["op"] for r in sel] == ["lpa_superstep", "cc_superstep"]
    assert all(r["impl"] == "blocked" for r in sel)
    builds = sink.of_phase("plan_build")
    assert len(builds) == 2 and builds[0]["family"] == "blocked"
    assert builds[0]["cached"] is False and builds[0]["seconds"] >= 0
    # the CC resolution reuses LPA's cached plan: zero build seconds
    assert builds[1]["cached"] is True and builds[1]["seconds"] == 0.0
    assert builds[0]["padded_slots_per_edge"] > 0
    assert not validate_records(sink.records)


def test_auto_seam_sort_family_emits_selection_only():
    from graphmine_tpu.pipeline.metrics import MetricsSink

    rng = np.random.default_rng(14)
    src, dst, v = _self_loops(rng)  # tiny: M < 2^16 -> sort
    g = build_graph(src, dst, num_vertices=v)
    sink = MetricsSink()
    label_propagation(g, 2, plan="auto", sink=sink)
    sel = sink.of_phase("impl_selected")
    assert len(sel) == 1 and sel[0]["impl"] == "sort"
    assert not sink.of_phase("plan_build")


def test_driver_runs_blocked_family(tmp_path, monkeypatch):
    """Driver e2e: the planner resolves the blocked family, the
    single-device LPA runs it, and labels match the default (bucketed)
    run bit-for-bit, with the provenance records in the stream."""
    from graphmine_tpu.pipeline.config import PipelineConfig
    from graphmine_tpu.pipeline.driver import run_pipeline

    rng = np.random.default_rng(15)
    src, dst, v = _power_law(rng)
    lines = "\n".join(f"{s} {d}" for s, d in zip(src, dst))
    p = tmp_path / "edges.txt"
    p.write_text(lines + "\n")

    cfg = dict(
        data_path=str(p), data_format="edgelist", outlier_method="none",
        num_devices=1, max_iter=3,
    )
    base = run_pipeline(PipelineConfig(**cfg))
    monkeypatch.setenv("GRAPHMINE_SUPERSTEP_FAMILY", "blocked")
    blocked = run_pipeline(PipelineConfig(**cfg))
    np.testing.assert_array_equal(
        np.asarray(base.labels), np.asarray(blocked.labels)
    )
    sel = [
        r for r in blocked.metrics.of_phase("impl_selected")
        if r["op"] == "lpa_superstep"
    ]
    assert sel and sel[0]["impl"] == "blocked"
    builds = blocked.metrics.of_phase("plan_build")
    assert builds and builds[0]["family"] == "blocked"


def test_driver_honors_forced_sort_family(tmp_path, monkeypatch):
    """An explicit GRAPHMINE_SUPERSTEP_FAMILY=sort force is honored by
    the driver: the sort superstep actually runs (no plan built, no
    plan_build record) and the provenance record says so — the
    tiny-scale sort→bucketed coercion applies to AUTO resolutions only."""
    from graphmine_tpu.pipeline.config import PipelineConfig
    from graphmine_tpu.pipeline.driver import run_pipeline

    rng = np.random.default_rng(16)
    src, dst, v = _power_law(rng)
    p = tmp_path / "edges.txt"
    p.write_text("\n".join(f"{s} {d}" for s, d in zip(src, dst)) + "\n")
    cfg = dict(
        data_path=str(p), data_format="edgelist", outlier_method="none",
        num_devices=1, max_iter=3,
    )
    base = run_pipeline(PipelineConfig(**cfg))
    monkeypatch.setenv("GRAPHMINE_SUPERSTEP_FAMILY", "sort")
    res = run_pipeline(PipelineConfig(**cfg))
    np.testing.assert_array_equal(
        np.asarray(base.labels), np.asarray(res.labels)
    )
    sel = [
        r for r in res.metrics.of_phase("impl_selected")
        if r["op"] == "lpa_superstep"
    ]
    assert sel and sel[0]["impl"] == "sort"
    assert not res.metrics.of_phase("plan_build")


def test_top_level_exports_match_api_docs():
    import graphmine_tpu as gm

    for name in (
        "BlockedPlan", "build_graph_and_blocked_plan",
        "lpa_superstep_blocked", "cc_superstep_blocked", "blocked_inflow",
        "select_superstep_family", "plan_superstep", "SuperstepPlan",
    ):
        assert hasattr(gm, name), name


# ---- bench tier ------------------------------------------------------------


def test_blocking_tier_body_cpu_smoke():
    """Run ``main_blocking``'s ACTUAL measurement body end-to-end on CPU
    at env-capped tiny scale (the roofline tier's convention) so the tier
    cannot fail its first-ever execution inside a real-TPU window."""
    env = dict(
        os.environ,
        GRAPHMINE_BENCH_CPU_FALLBACK="1",
        _GRAPHMINE_BENCH_CHILD="1",
        GRAPHMINE_BLOCKING_VERTICES=str(1 << 12),
        GRAPHMINE_BLOCKING_EDGES=str(1 << 13),
        GRAPHMINE_BLOCKING_ITERS="2",
        JAX_PLATFORMS="cpu",
    )
    out = subprocess.run(
        [sys.executable, os.path.join(_REPO, "bench.py"), "--tier", "blocking"],
        capture_output=True, text=True, timeout=300, env=env, cwd=_REPO,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(
        [l for l in out.stdout.splitlines() if l.startswith("{")][-1]
    )
    assert rec["metric"] == "blocking_binned_slots_per_sec_cpu_fallback"
    assert rec["value"] > 0
    assert rec["vs_baseline"] == 0.0  # CPU rates: no TPU-model ratio
    d = rec["detail"]
    for k in (
        "random_gather_slots_per_sec", "monotone_gather_slots_per_sec",
        "binned_pass_slots_per_sec", "binned_vs_random_gather",
    ):
        assert d[k] > 0, k
    assert d["messages"] == 2 * d["num_edges"]
    assert d["num_bins"] >= 1 and d["plan_build_seconds"] >= 0
