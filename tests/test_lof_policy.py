"""LOF auto-policy (r6): the measured IVF crossover as deployed code.

VERDICT r5 weak-item 3: a measured 3.1x sat undeployed because
``lof_scores(impl="auto")`` was scale-blind. These tests pin the policy —
small-N auto stays exact, large-N auto deploys the IVF index, a
pathology-guard fallback stays loud AND exact — and gate the index's
quality against the exact oracle (recall >= 0.999, |AUROC delta| <=
0.005 on a fixed-seed cloud: the acceptance numbers, with the measured
values 0.9999 / 0.001 well inside them).
"""

import numpy as np
import pytest

from graphmine_tpu.ops.lof import (
    LOF_IVF_MIN_POINTS,
    auroc,
    lof_scores,
    select_lof_impl,
)
from graphmine_tpu.pipeline.metrics import MetricsSink

pytestmark = pytest.mark.ann  # the --ann-only tier-1 lane


@pytest.fixture(scope="module")
def blob_cloud():
    """Fixed-seed clustered cloud with planted shell outliers — IVF's
    design case (inverted lists exploit cluster structure), sized well
    under the real crossover so tests force the dispatch explicitly."""
    rng = np.random.default_rng(42)
    n, f = 20000, 8
    centers = rng.normal(size=(16, f)).astype(np.float32) * 4
    assign = rng.integers(0, 16, n)
    pts = centers[assign] + rng.normal(size=(n, f)).astype(np.float32)
    is_out = rng.random(n) < 0.01
    n_out = int(is_out.sum())
    d = rng.normal(size=(n_out, f)).astype(np.float32)
    d /= np.linalg.norm(d, axis=1, keepdims=True)
    pts[is_out] = centers[assign[is_out]] + d * rng.uniform(
        4.0, 6.0, (n_out, 1)
    ).astype(np.float32)
    return pts, is_out


def test_select_lof_impl_crossover():
    # the deployed default crossover is the provenance table's value
    assert LOF_IVF_MIN_POINTS == 1 << 17
    fam, reason = select_lof_impl(LOF_IVF_MIN_POINTS - 1, 128)
    assert fam == "exact" and "crossover" in reason
    fam, reason = select_lof_impl(LOF_IVF_MIN_POINTS, 128)
    assert fam == "ivf" and "3.1x" in reason
    # explicit requests bypass the policy
    assert select_lof_impl(10**9, 128, impl="xla")[0] == "exact"
    assert select_lof_impl(100, 16, impl="ivf")[0] == "ivf"
    # overrides: argument beats the default; env beats the default
    assert select_lof_impl(1000, 16, ivf_min_points=500)[0] == "ivf"
    # unknown impls are rejected, not silently coerced to a family
    with pytest.raises(ValueError, match="unknown LOF impl"):
        select_lof_impl(1000, 16, impl="IVF")


def test_select_lof_impl_env_override(monkeypatch):
    monkeypatch.setenv("GRAPHMINE_LOF_IVF_MIN_N", "300")
    assert select_lof_impl(1000, 16)[0] == "ivf"
    monkeypatch.setenv("GRAPHMINE_LOF_IVF_MIN_N", "5000")
    assert select_lof_impl(1000, 16)[0] == "exact"


def test_auto_small_n_runs_exact_and_records(blob_cloud):
    pts, _ = blob_cloud
    m = MetricsSink()
    auto = np.asarray(lof_scores(pts[:4000], k=32, sink=m))
    rec = m.of_phase("impl_selected")
    assert len(rec) == 1 and rec[0]["impl"] == "exact"
    assert rec[0]["op"] == "lof_knn" and rec[0]["n"] == 4000
    assert rec[0]["requested"] == "auto"
    exact = np.asarray(lof_scores(pts[:4000], k=32, impl="xla"))
    np.testing.assert_allclose(auto, exact, rtol=1e-5, atol=1e-6)


def test_auto_large_n_deploys_ivf_and_records(blob_cloud):
    """The crossover dispatch itself, with the threshold lowered so the
    'large-N' branch runs at test scale (the same policy function with
    the same inputs; only the constant moves)."""
    pts, _ = blob_cloud
    m = MetricsSink()
    auto = np.asarray(
        lof_scores(pts, k=32, sink=m, ivf_min_points=10000)
    )
    rec = m.of_phase("impl_selected")
    assert len(rec) == 1 and rec[0]["impl"] == "ivf"
    assert not m.of_phase("ivf_fallback")  # really rode the index
    ivf = np.asarray(lof_scores(pts, k=32, impl="ivf"))
    np.testing.assert_array_equal(auto, ivf)  # same deterministic index
    # and the approximate scores track the exact oracle
    exact = np.asarray(lof_scores(pts, k=32, impl="xla"))
    frac_close = np.mean(np.abs(auto - exact) < 0.05 * np.abs(exact) + 0.01)
    assert frac_close > 0.95, frac_close


def test_forced_fallback_is_exact_and_loud():
    """Auto selects IVF (lowered threshold) on a cloud whose clusters
    cannot fill the requested top-k: the pathology guard must route to
    the exact result AND leave an ivf_fallback record + warning (ADVICE
    r5) — with the impl_selected record still saying what the policy
    chose, so the triage trail shows both the decision and the bailout."""
    rng = np.random.default_rng(4)
    n, f, k = 64, 4, 40  # k above any cluster's size: "k_unfillable"
    pts = rng.normal(size=(n, f)).astype(np.float32)
    m = MetricsSink()
    with pytest.warns(UserWarning, match="ivf_knn guard"):
        scores = np.asarray(
            lof_scores(pts, k=k, sink=m, ivf_min_points=50)
        )
    sel = m.of_phase("impl_selected")
    assert sel and sel[0]["impl"] == "ivf"
    fb = m.of_phase("ivf_fallback")
    assert fb and fb[0]["guard"]
    exact = np.asarray(lof_scores(pts, k=k, impl="xla"))
    np.testing.assert_allclose(scores, exact, rtol=1e-5, atol=1e-5)


def test_ivf_recall_and_auroc_regression_gates(blob_cloud):
    """The acceptance gates as a pinned regression test: on the
    fixed-seed clustered cloud the index must hold recall >= 0.999
    against the exact kNN oracle and |AUROC delta| <= 0.005 on the
    planted outliers (measured: 0.9999 recall / 0.001 delta at 262K on
    silicon; this cloud measures ~1.0 / ~0.000 at CI scale)."""
    from graphmine_tpu.ops.ann import ivf_knn
    from graphmine_tpu.ops.knn import knn

    pts, is_out = blob_cloud
    k = 32
    exact_d2, exact_i = knn(pts, k=k, impl="xla")
    ivf_d2, ivf_i = ivf_knn(pts, k=k)
    exact_i, ivf_i = np.asarray(exact_i), np.asarray(ivf_i)
    recall = np.mean([
        len(set(exact_i[i]) & set(ivf_i[i])) / k for i in range(len(pts))
    ])
    assert recall >= 0.999, recall

    from graphmine_tpu.ops.lof import lof_from_knn

    a_exact = auroc(np.asarray(lof_from_knn(exact_d2, exact_i, k)), is_out)
    a_ivf = auroc(np.asarray(lof_from_knn(ivf_d2, ivf_i, k)), is_out)
    assert abs(a_exact - a_ivf) <= 0.005, (a_exact, a_ivf)
    assert a_ivf > 0.95  # the harness detects, not just agrees
