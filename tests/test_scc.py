"""SCC vs the scipy.sparse.csgraph oracle (SURVEY §4: oracle-backed tests)."""

import numpy as np
import pytest

from graphmine_tpu.graph.container import build_graph
from graphmine_tpu.ops.scc import strongly_connected_components


def _canon(labels):
    """Map labels to dense ids by first occurrence — partition comparison."""
    labels = np.asarray(labels)
    first = {}
    out = np.empty_like(labels)
    nxt = 0
    for i, l in enumerate(labels):
        if l not in first:
            first[l] = nxt
            nxt += 1
        out[i] = first[l]
    return out


def _oracle(src, dst, v):
    from scipy.sparse import coo_matrix
    from scipy.sparse.csgraph import connected_components as cc

    m = coo_matrix((np.ones(len(src)), (src, dst)), shape=(v, v))
    _, labels = cc(m, directed=True, connection="strong")
    return labels


def _check(src, dst, v):
    g = build_graph(np.asarray(src, np.int32), np.asarray(dst, np.int32), num_vertices=v)
    got = np.asarray(strongly_connected_components(g))
    want = _oracle(np.asarray(src), np.asarray(dst), v)
    np.testing.assert_array_equal(_canon(got), _canon(want))
    # labels are member vertex ids
    assert np.all((got >= 0) & (got < v))


def test_two_cycles_with_bridge():
    # cycle {0,1,2} -> bridge -> cycle {3,4}; 5 isolated
    _check([0, 1, 2, 2, 3, 4], [1, 2, 0, 3, 4, 3], 6)


def test_dag_is_all_singletons():
    _check([0, 0, 1, 2], [1, 2, 3, 3], 4)


def test_full_cycle():
    v = 7
    src = list(range(v))
    dst = [(i + 1) % v for i in range(v)]
    _check(src, dst, v)


def test_nested_reach_order():
    # 0 reaches SCC {1,2} but is its own SCC — exercises the peel ordering
    _check([0, 1, 2], [1, 2, 1], 3)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_random_vs_oracle(seed):
    rng = np.random.default_rng(seed)
    v, e = 60, 180
    src = rng.integers(0, v, e).astype(np.int32)
    dst = rng.integers(0, v, e).astype(np.int32)
    _check(src, dst, v)
