"""BFS path-finding tests (GraphFrames .bfs semantics)."""

import numpy as np

from graphmine_tpu.graph.container import build_graph
from graphmine_tpu.ops.paths import UNREACHABLE, bfs, bfs_parents


def _chain_graph():
    # 0->1->2->3->4 chain plus shortcut 0->3, and 5 isolated
    src = np.array([0, 1, 2, 3, 0], np.int32)
    dst = np.array([1, 2, 3, 4, 3], np.int32)
    return build_graph(src, dst, num_vertices=6)


def test_parents_give_shortest_tree():
    g = _chain_graph()
    dist, parent = bfs_parents(g, np.array([0]), direction="out")
    assert np.asarray(dist)[:5].tolist() == [0, 1, 2, 1, 2]
    p = np.asarray(parent)
    assert p[0] == -1 and p[5] == -1
    assert p[3] == 0  # via the shortcut, not the chain
    assert p[4] == 3


def test_bfs_path_reconstruction():
    g = _chain_graph()
    (path,) = bfs(g, [0], [4])
    assert path.tolist() == [0, 3, 4]


def test_bfs_stops_at_first_hit_level():
    g = _chain_graph()
    # targets at different depths: 3 (depth 1) and 4 (depth 2) -> only depth-1 path
    paths = bfs(g, [0], [3, 4])
    assert [p.tolist() for p in paths] == [[0, 3]]


def test_bfs_unreachable_and_max_len():
    g = _chain_graph()
    assert bfs(g, [0], [5]) == []
    assert bfs(g, [1], [4], max_path_length=2) == []
    (p,) = bfs(g, [1], [4], max_path_length=3)
    assert p.tolist() == [1, 2, 3, 4]


def test_bfs_source_is_target():
    g = _chain_graph()
    (p,) = bfs(g, [2, 0], [2])
    assert p.tolist() == [2]


def test_bfs_both_direction():
    g = _chain_graph()
    (p,) = bfs(g, [4], [0], direction="both")
    assert p.tolist() == [4, 3, 0]


def test_unreachable_sentinel():
    g = _chain_graph()
    dist, _ = bfs_parents(g, np.array([4]), direction="out")
    assert int(np.asarray(dist)[0]) == int(UNREACHABLE)
