"""Multi-tenant serving suite (marker ``tenancy``): the ISSUE 16
isolation contract — tools/run_tier1.sh --tenancy-only.

The acceptance pins:
- the snapshot store namespaces tenants under ``<root>/tenants/<id>/``
  with the default tenant on the bare root (full back-compat), hostile
  ids refused before any path exists;
- each tenant gets its own admission ladder (``GRAPHMINE_TENANT_BOUNDS``
  / ``set_overrides``) and the apply worker dequeues weighted-fair by
  deficit round-robin — one tenant's backlog cannot starve another's;
- WAL frames carry the tenant id durably: replay and the idempotency
  dedupe are tenant-scoped (the same ``delta_id`` under two tenants is
  two applies);
- every read/alert endpoint routes by ``X-Tenant-Id`` / ``?tenant=``; a
  valid vertex under the wrong tenant 404s exactly like an unknown
  tenant (no namespace-existence oracle);
- the noisy-neighbor chaos tier: with tenant A abusing a live 3-tenant
  server (``faults.noisy_neighbor_burst``), B's and C's reads hold p99,
  their deltas keep flowing with zero sheds, zero cross-tenant reads
  leak, and only A's alert plane fires.
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from graphmine_tpu.graph.container import build_graph
from graphmine_tpu.obs.schema import validate_records
from graphmine_tpu.obs.spans import Tracer
from graphmine_tpu.pipeline.checkpoint import graph_fingerprint
from graphmine_tpu.pipeline.metrics import MetricsSink
from graphmine_tpu.serve import SnapshotStore
from graphmine_tpu.serve.delta import EdgeDelta, cold_recompute
from graphmine_tpu.serve.server import SnapshotServer, _PendingDelta
from graphmine_tpu.serve.tenancy import (
    DEFAULT_TENANT,
    TenantRegistry,
    UnknownTenantError,
    validate_tenant_id,
)
from graphmine_tpu.testing import faults

pytestmark = pytest.mark.tenancy


# ---- fixtures -------------------------------------------------------------


def _clique(lo, hi):
    ids = np.arange(lo, hi)
    s, d = np.meshgrid(ids, ids)
    m = s.ravel() < d.ravel()
    return s.ravel()[m], d.ravel()[m]


def _cliques(spans):
    """Disjoint cliques over ``spans`` — per-tenant graphs of different
    shapes, so a cross-namespace read is detectable (degree and vertex
    range differ, not just labels)."""
    parts = [_clique(lo, hi) for lo, hi in spans]
    src = np.concatenate([p[0] for p in parts]).astype(np.int32)
    dst = np.concatenate([p[1] for p in parts]).astype(np.int32)
    return src, dst, max(hi for _, hi in spans)


def _sink():
    return MetricsSink(tracer=Tracer())


def _publish(store, src, dst, v, sink=None):
    g = build_graph(src, dst, num_vertices=v)
    labels, cc, _ = cold_recompute(g)
    store.publish(
        {
            "src": src, "dst": dst, "labels": labels, "cc_labels": cc,
            # all below the 1.5 anomaly threshold: a healthy tenant's
            # quality rules must stay quiet unless a test trips them
            "lof": np.linspace(0.5, 1.2, v).astype(np.float32),
        },
        fingerprint=graph_fingerprint(src, dst),
        sink=sink,
    )
    return store


def _three_tenant_root(tmp_path, sink=None):
    """Bare-root default plus tenants ``ta`` (30 vertices, two cliques
    of 15) and ``tb`` (20 vertices, two cliques of 10)."""
    src, dst, v = _cliques([(0, 12), (12, 26), (26, 40)])
    store = SnapshotStore(str(tmp_path / "snap"))
    _publish(store, src, dst, v, sink=sink)
    sa, da, va = _cliques([(0, 15), (15, 30)])
    _publish(store.for_tenant("ta"), sa, da, va, sink=sink)
    sb, db, vb = _cliques([(0, 10), (10, 20)])
    _publish(store.for_tenant("tb"), sb, db, vb, sink=sink)
    return store


def _get(host, port, path, headers=None):
    req = urllib.request.Request(
        f"http://{host}:{port}{path}", headers=headers or {}
    )
    with urllib.request.urlopen(req, timeout=30) as r:
        return json.loads(r.read())


def _post(host, port, path, payload, headers=None):
    req = urllib.request.Request(
        f"http://{host}:{port}{path}", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    with urllib.request.urlopen(req, timeout=120) as r:
        return json.loads(r.read())


# ---- namespaced snapshot store --------------------------------------------


def test_store_namespace_roundtrip(tmp_path):
    """Per-tenant stores live under ``<root>/tenants/<id>/``, the
    default tenant on the bare root; version chains are independent."""
    src, dst, v = _cliques([(0, 12), (12, 26), (26, 40)])
    store = SnapshotStore(str(tmp_path / "snap"))
    _publish(store, src, dst, v)

    ta = store.for_tenant("ta")
    assert ta.root == os.path.join(store.base_root, "tenants", "ta")
    assert ta.base_root == store.base_root
    assert ta.for_tenant("ta") is ta
    assert store.for_tenant(DEFAULT_TENANT) is store

    sa, da, va = _cliques([(0, 15), (15, 30)])
    _publish(ta, sa, da, va)
    tb = store.for_tenant("tb")
    sb, db, vb = _cliques([(0, 10), (10, 20)])
    _publish(tb, sb, db, vb)
    _publish(tb, sb, db, vb)  # second publish: tb's own chain advances

    assert store.list_tenants() == [DEFAULT_TENANT, "ta", "tb"]
    assert store.load().version == 1
    assert ta.load().version == 1
    assert tb.load().version == 2
    # namespaces hold different graphs, not views of one
    assert store.load()["src"].size != ta.load()["src"].size
    assert ta.load()["src"].size != tb.load()["src"].size


def test_hostile_tenant_ids_refused(tmp_path):
    """A hostile id raises ``ValueError`` before any filesystem path is
    built — no directory appears, nothing escapes the root."""
    src, dst, v = _cliques([(0, 12), (12, 26), (26, 40)])
    store = SnapshotStore(str(tmp_path / "snap"))
    _publish(store, src, dst, v)
    before = sorted(os.listdir(store.base_root))

    for bad in (
        "", "A", "Ta", "a/b", "../evil", "a b", "a.b", "ü",
        "x" * 65, "tenants/../../evil",
    ):
        with pytest.raises(ValueError):
            validate_tenant_id(bad)
        with pytest.raises(ValueError):
            store.for_tenant(bad)

    assert sorted(os.listdir(store.base_root)) == before
    assert not (tmp_path / "evil").exists()

    for good in ("a", "0", "a-b_c9", "x" * 64, DEFAULT_TENANT):
        assert validate_tenant_id(good) == good


def test_tenant_registry_bounds_and_memory(monkeypatch):
    """``GRAPHMINE_TENANT_BOUNDS`` seeds per-tenant admission overrides,
    ``set_overrides`` layers on top, and the packing oracle sums
    per-tenant snapshot bytes against the serve budget."""
    monkeypatch.setenv(
        "GRAPHMINE_TENANT_BOUNDS",
        json.dumps({"ta": {"max_pending_rows": 7, "deadline_s": 3.5}}),
    )
    reg = TenantRegistry()
    assert reg.bounds_for("ta").max_pending_rows == 7
    assert reg.bounds_for("ta").deadline_s == 3.5
    baseline = reg.bounds_for("tb")
    assert baseline.max_pending_rows != 7

    reg.set_overrides("tb", max_queue_depth=2)
    assert reg.bounds_for("tb").max_queue_depth == 2
    # overrides never bleed across tenants
    assert reg.bounds_for("ta").max_queue_depth == baseline.max_queue_depth
    assert set(reg.snapshot()["overrides"]) >= {"ta", "tb"}

    reg.note_bytes("ta", 100)
    reg.note_bytes("tb", 60)
    mp = reg.memory_payload(200)
    assert mp["total_snapshot_bytes"] == 160
    assert mp["headroom_bytes"] == 40
    assert mp["fits"] is True
    assert reg.memory_payload(100)["fits"] is False
    assert "budget_bytes" not in reg.memory_payload(None)  # unknown budget

    monkeypatch.setenv("GRAPHMINE_TENANT_BOUNDS", "{not json")
    with pytest.raises(ValueError):
        TenantRegistry()


# ---- HTTP routing + read-plane blast radius -------------------------------


def test_http_tenant_routing_and_wrong_tenant_404(tmp_path):
    """``X-Tenant-Id`` and ``?tenant=`` route every endpoint to that
    tenant's engine; a valid vertex under the wrong tenant 404s exactly
    like an unknown tenant; malformed ids 400."""
    store = _three_tenant_root(tmp_path)
    server = SnapshotServer(store)
    host, port = server.start()
    try:
        # same vertex, three namespaces, three different degrees
        # (default: clique of 12 -> 11; ta: 15 -> 14; tb: 10 -> 9)
        deg = lambda hdr=None, qs="": len(_get(  # noqa: E731
            host, port, f"/neighbors?v=5{qs}", headers=hdr
        )["neighbors"])
        assert deg() == 11
        assert deg(hdr={"X-Tenant-Id": "ta"}) == 14
        assert deg(qs="&tenant=ta") == 14
        assert deg(hdr={"X-Tenant-Id": "tb"}) == 9

        # vertex 25 exists under default and ta, not under tb (v=20):
        # wrong tenant answers 404 "not found", same as an unknown
        # tenant — a prober can't learn which tenants exist
        assert _get(host, port, "/vertex?v=25&tenant=ta")["vertex"] == 25
        bodies = []
        for path in ("/vertex?v=25&tenant=tb", "/vertex?v=25&tenant=ghost"):
            with pytest.raises(urllib.error.HTTPError) as e:
                _get(host, port, path)
            assert e.value.code == 404
            bodies.append(json.loads(e.value.read())["error"])
        assert bodies[0] == bodies[1]

        for hdr in ("../evil", "TA", "a b"):
            with pytest.raises(urllib.error.HTTPError) as e:
                _get(host, port, "/vertex?v=5",
                     headers={"X-Tenant-Id": hdr})
            assert e.value.code == 400

        hz = _get(host, port, "/healthz")
        assert hz["tenants"] == 3
        assert set(hz["tenant_versions"]) == {DEFAULT_TENANT, "ta", "tb"}
        assert set(hz["tenant_snapshot_age_s"]) == set(hz["tenant_versions"])
        assert all(a >= 0 for a in hz["tenant_snapshot_age_s"].values())

        st = _get(host, port, "/statusz")["tenancy"]
        assert set(st["per_tenant"]) == {DEFAULT_TENANT, "ta", "tb"}
        assert st["per_tenant"]["ta"]["version"] == 1
        assert {"ta", "tb"} <= set(st["memory"]["tenants"])
    finally:
        server.stop()


# ---- tenant-scoped durability ---------------------------------------------


def test_wal_dedupe_and_replay_are_tenant_scoped(tmp_path):
    """The same ``delta_id`` under two tenants is two distinct applies;
    the retry under the original tenant dedupes; a restart preserves
    each tenant's version chain and the dedupe table."""
    store = _three_tenant_root(tmp_path)
    server = SnapshotServer(store, wal=True)
    payload = {"insert": [[1, 16]], "delete": []}
    try:
        r1 = server.apply_delta(payload, delta_id="d1", tenant="ta")
        assert r1["version"] == 2
        r2 = server.apply_delta(payload, delta_id="d1", tenant="tb")
        assert r2.get("verdict") != "duplicate"
        assert r2["version"] == 2
        r3 = server.apply_delta(payload, delta_id="d1", tenant="ta")
        assert r3["verdict"] == "duplicate" and r3["applied"] is True
        assert server.engine_for("ta").version == 2
        assert server.engine_for("tb").version == 2
        assert server.engine_for(DEFAULT_TENANT).version == 1
    finally:
        server.stop()

    server2 = SnapshotServer(store, wal=True)
    try:
        assert server2.engine_for("ta").version == 2
        assert server2.engine_for("tb").version == 2
        r4 = server2.apply_delta(payload, delta_id="d1", tenant="ta")
        assert r4["verdict"] == "duplicate"
        r5 = server2.apply_delta(payload, delta_id="d2", tenant="ta")
        assert r5["version"] == 3
    finally:
        server2.stop()


def test_unknown_tenant_rejected_before_side_effects(tmp_path):
    """A write naming an unknown (or malformed) tenant fails before any
    admission/WAL side effect — nothing lands in anyone's ledger."""
    store = _three_tenant_root(tmp_path)
    server = SnapshotServer(store, wal=True)
    try:
        wal_before = server.wal.snapshot()["last_seq"]
        with pytest.raises(UnknownTenantError):
            server.apply_delta({"insert": [[0, 1]]}, tenant="ghost")
        with pytest.raises(ValueError):
            server.apply_delta({"insert": [[0, 1]]}, tenant="../evil")
        assert server.wal.snapshot()["last_seq"] == wal_before
        assert server.engine_for(DEFAULT_TENANT).version == 1
    finally:
        server.stop()


# ---- weighted-fair dequeue ------------------------------------------------


def _enqueue(server, tenant, rows, n=1, deadline_s=300.0):
    ts = server._tenant_state(tenant)
    for _ in range(n):
        pd = _PendingDelta(
            EdgeDelta(), rows, time.monotonic() + deadline_s, deadline_s
        )
        pd.tenant = tenant
        ts.queue.append(pd)
    if tenant not in server._rr:
        server._rr.append(tenant)


def test_deficit_round_robin_interleaves_tenants(tmp_path):
    """With two tenants backed up, the worker's dequeue alternates by
    row quantum — the abuser's queue depth never buys it consecutive
    turns. (No delta ever enters through apply_delta here, so the lazy
    apply worker never starts and popping by hand is race-free.)"""
    server = SnapshotServer(_three_tenant_root(tmp_path))
    server._fair_quantum_rows = 4
    _enqueue(server, "ta", rows=4, n=3)
    _enqueue(server, "tb", rows=4, n=3)

    pops = [server._pop_group() for _ in range(6)]
    assert [t for t, _, _ in pops] == ["ta", "tb", "ta", "tb", "ta", "tb"]
    assert all(len(g) == 1 and e == [] for _, g, e in pops)

    # a batch larger than the quantum still makes progress (>=1 per turn)
    _enqueue(server, "ta", rows=1000)
    _enqueue(server, "tb", rows=4)
    t1, g1, _ = server._pop_group()
    assert (t1, g1[0].rows) == ("ta", 1000)
    assert server._pop_group()[0] == "tb"

    # one active tenant = infinite quantum: the pre-tenancy
    # pop-everything (and coalesce-everything) behavior
    _enqueue(server, "ta", rows=4, n=3)
    t2, g2, _ = server._pop_group()
    assert t2 == "ta" and len(g2) == 3

    # expired deadlines are split out for shedding whoever's turn it is
    _enqueue(server, "ta", rows=4, deadline_s=300.0)
    _enqueue(server, "tb", rows=4)
    ts = server._tenant_state("ta")
    ts.queue[0].deadline = time.monotonic() - 1.0
    _, _, expired = server._pop_group()
    assert [p.tenant for p in expired] == ["ta"]


# ---- per-tenant alert planes ----------------------------------------------


def test_alert_planes_are_tenant_scoped(tmp_path):
    """Tenant A's canary page fires naming A — records tenant-stamped,
    ``/alertz?tenant=A`` firing — while B's rule set stays clean."""
    sink = _sink()
    server = SnapshotServer(_three_tenant_root(tmp_path), sink=sink)
    ts_a = server._tenant_state("ta")
    server._tenant_state("tb")

    # drive A's canary rule directly through its own manager (for_s
    # honored by spacing the evaluations far apart)
    ts_a.alerts.evaluate({"canary_recall": 0.1}, now=1000.0)
    ts_a.alerts.evaluate({"canary_recall": 0.1}, now=2000.0)

    page_a = server.alertz("ta")
    assert page_a["tenant"] == "ta"
    assert page_a["firing"] >= 1
    rule = next(
        r for r in page_a["rules"] if r["name"] == "canary_recall_low"
    )
    assert rule["state"] == "firing"

    page_b = server.alertz("tb")
    assert page_b["tenant"] == "tb" and page_b["firing"] == 0
    assert server.alertz()["firing"] == 0  # default untouched too

    alert_recs = [r for r in sink.records if r.get("phase") == "alert"]
    assert any(
        r.get("tenant") == "ta" and r["name"] == "canary_recall_low"
        and r["state"] == "firing"
        for r in alert_recs
    )
    assert not any(r.get("tenant") == "tb" for r in alert_recs)
    assert validate_records(sink.records) == []


# ---- the noisy-neighbor chaos acceptance ----------------------------------


def test_noisy_neighbor_isolation_acceptance(tmp_path, monkeypatch):
    """THE ISSUE 16 acceptance: a live 3-tenant server with tenant
    ``noisy`` abusing the write path (volume + stalled repairs via
    ``faults.noisy_neighbor_burst``) while ``vb``/``vc`` keep working.

    Pinned from live endpoints: victims' reads stay fast and answer
    ONLY from their own namespace; their mid-storm deltas publish with
    zero sheds; the abuser sheds and its ingest-lag page fires; the
    victims' alert planes never fire."""
    # Alert thresholds: resolved at each tenant's first touch, so set
    # BEFORE the server exists. for_s outlasts any victim's worst-case
    # queue wait (<= ~2 abuser publishes) but not the abuser's
    # storm-long backlog.
    monkeypatch.setenv("GRAPHMINE_ALERT_INGEST_LAG_S", "0.5")
    monkeypatch.setenv("GRAPHMINE_ALERT_INGEST_LAG_FOR_S", "6.0")
    # Quality plane off: the synthetic lof arrays drift wildly once a
    # real repair rescores them, and those warn-rules would drown the
    # signal under test — WRITE-path isolation via the ingest-lag page.
    # Per-tenant quality/canary scoping is pinned separately above.
    monkeypatch.setenv("GRAPHMINE_QUALITY", "0")

    sink = _sink()
    src, dst, v = _cliques([(0, 12), (12, 26), (26, 40)])
    store = SnapshotStore(str(tmp_path / "snap"))
    _publish(store, src, dst, v, sink=sink)
    _publish(store.for_tenant("noisy"), src, dst, v, sink=sink)
    sb, db, vvb = _cliques([(0, 15), (15, 30)])
    _publish(store.for_tenant("vb"), sb, db, vvb, sink=sink)
    sc, dc, vvc = _cliques([(0, 10), (10, 20)])
    _publish(store.for_tenant("vc"), sc, dc, vvc, sink=sink)

    server = SnapshotServer(store, sink=sink)
    # Tight envelope for the abuser only: ~2 groups of pending rows,
    # then ITS OWN ladder sheds it. Victims keep the generous defaults.
    server.tenancy.set_overrides(
        "noisy", max_pending_rows=24, max_queue_depth=2, deadline_s=120.0,
    )
    payloads, staller = faults.noisy_neighbor_burst(
        "noisy", v, batches=6, rows_per_batch=8, seed=7, stall_s=1.2,
    )
    inj = faults.FaultInjector()
    inj.add("delta_repair", staller, at=1, repeat=10**6)

    host, port = server.start()
    abuser_sheds = [0]
    abuser_errors = []
    stop = threading.Event()

    def abuse():
        i = 0
        while not stop.is_set():
            try:
                _post(host, port, "/delta", payloads[i % len(payloads)],
                      headers={"X-Tenant-Id": "noisy"})
            except urllib.error.HTTPError as e:
                e.read()
                if e.code == 503:
                    abuser_sheds[0] += 1
                    time.sleep(0.05)
                else:
                    abuser_errors.append(e)
                    return
            except Exception as e:  # noqa: BLE001 — collect, assert later
                abuser_errors.append(e)
                return
            i += 1

    victim_delta = {
        "vb": {"insert": [[2, 16]], "delete": []},
        "vc": {"insert": [[2, 11]], "delete": []},
    }
    try:
        # phase A — quiet baseline: victims write and read cleanly
        for t in ("vb", "vc"):
            out = _post(host, port, "/delta", victim_delta[t],
                        headers={"X-Tenant-Id": t})
            assert out["version"] == 2
            assert _get(host, port, f"/alertz?tenant={t}")["firing"] == 0

        # phase B — the storm
        read_lat = []
        victim_versions = {"vb": set(), "vc": set()}
        noisy_fired = False
        posted_mid = False
        with inj.installed():
            threads = [
                threading.Thread(target=abuse, daemon=True)
                for _ in range(3)
            ]
            for th in threads:
                th.start()
            t0 = time.monotonic()
            while time.monotonic() - t0 < 25.0:
                for t in ("vb", "vc"):
                    q0 = time.perf_counter()
                    out = _post(host, port, "/query", {"vertices": [3, 7]},
                                headers={"X-Tenant-Id": t})
                    read_lat.append(time.perf_counter() - q0)
                    assert len(out["label"]) == 2
                    victim_versions[t].add(out["version"])
                elapsed = time.monotonic() - t0
                if elapsed > 3.0 and not posted_mid:
                    posted_mid = True
                    for t in ("vb", "vc"):
                        out = _post(host, port, "/delta", victim_delta[t],
                                    headers={"X-Tenant-Id": t})
                        # flowing, not shed: a real publish came back
                        assert out["version"] == 3
                    # zero cross-tenant reads: vb's vertex 25 does not
                    # exist in vc's 20-vertex namespace, storm or not
                    with pytest.raises(urllib.error.HTTPError) as e:
                        _get(host, port, "/vertex?v=25&tenant=vc")
                    assert e.value.code == 404
                if elapsed > 8.0:
                    page = _get(host, port, "/alertz?tenant=noisy")
                    firing = [
                        r["name"] for r in page["rules"]
                        if r["state"] == "firing"
                    ]
                    if "ingest_lag_high" in firing:
                        noisy_fired = True
                        break
                time.sleep(0.02)
            stop.set()
            for th in threads:
                th.join(timeout=30)
        server.wait_applied(timeout=120.0)

        assert abuser_errors == []
        assert noisy_fired, "abuser ingest-lag page never fired"
        assert posted_mid and abuser_sheds[0] > 0

        # victims' reads: bounded p99, and every answer came from the
        # victim's OWN version chain (1 publish + 2 deltas), never the
        # abuser's racing chain
        read_lat.sort()
        assert read_lat[int(0.99 * (len(read_lat) - 1))] < 1.0
        for t in ("vb", "vc"):
            assert victim_versions[t] <= {2, 3}
            assert server.engine_for(t).version == 3
            assert _get(host, port, f"/alertz?tenant={t}")["firing"] == 0

        st = _get(host, port, "/statusz")["tenancy"]["per_tenant"]
        assert st["noisy"]["verdicts"].get("shed", 0) >= 1
        assert st["vb"]["verdicts"].get("shed", 0) == 0
        assert st["vc"]["verdicts"].get("shed", 0) == 0
        assert st["noisy"]["version"] > 3

        # the victims' alert planes never transitioned, storm-long
        assert not any(
            r.get("phase") == "alert" and r.get("tenant") in ("vb", "vc")
            for r in sink.records
        )
        assert validate_records(sink.records) == []

        # the per-tenant obs rollup renders the storm
        from tools.obs_report import _tenant_section

        lines = _tenant_section(sink.records, 0.0)
        assert any("noisy" in ln for ln in lines)
    finally:
        stop.set()
        server.stop()
