"""LPA semantics tests: hand-built graphs with unambiguous modes, invariants,
and the bundled-data distinct-label trajectory (anchor ~927→650, BASELINE.md).
"""

import numpy as np
import jax.numpy as jnp

from graphmine_tpu.graph.container import build_graph, graph_from_edge_table
from graphmine_tpu.ops.lpa import label_propagation, lpa_superstep, num_communities, canonicalize
from graphmine_tpu.ops.segment import segment_mode


def test_segment_mode_basic():
    seg = jnp.array([0, 0, 0, 1, 1, 2], jnp.int32)
    val = jnp.array([5, 7, 5, 3, 3, 9], jnp.int32)
    mode, count = segment_mode(seg, val, num_segments=4)
    assert mode.tolist()[:3] == [5, 3, 9]
    assert count.tolist() == [2, 2, 1, 0]  # empty segment -> count 0


def test_segment_mode_tie_breaks_smallest():
    seg = jnp.array([0, 0, 0, 0], jnp.int32)
    val = jnp.array([4, 2, 4, 2], jnp.int32)
    mode, count = segment_mode(seg, val, num_segments=1)
    assert mode.tolist() == [2] and count.tolist() == [2]


def test_segment_mode_drops_out_of_range():
    seg = jnp.array([0, 1, 2, 2], jnp.int32)  # 2 == num_segments: padding sentinel
    val = jnp.array([7, 8, 9, 9], jnp.int32)
    mode, count = segment_mode(seg, val, num_segments=2)
    assert mode.tolist() == [7, 8] and count.tolist() == [1, 1]


def test_two_triangles_bridge():
    # Two triangles joined by one bridge edge: LPA must find 2 communities.
    src = np.array([0, 1, 2, 3, 4, 5, 0])
    dst = np.array([1, 2, 0, 4, 5, 3, 3])
    g = build_graph(src, dst)
    labels = label_propagation(g, max_iter=10)
    labels = np.asarray(canonicalize(labels))
    assert labels[0] == labels[1] == labels[2]
    assert labels[3] == labels[4] == labels[5]


def test_isolated_vertex_keeps_label():
    g = build_graph([0, 1], [1, 0], num_vertices=3)
    labels = np.asarray(label_propagation(g, max_iter=3))
    assert labels[2] == 2


def test_duplicate_edge_multiplicity_matters():
    # v2 has neighbors {0 (x2 via duplicate edge), 1}. With multiplicity the
    # mode is 0; without it would tie and pick the smaller anyway, so also
    # test the reverse: duplicates on the larger label flip the outcome.
    g = build_graph([0, 0, 1], [2, 2, 2], num_vertices=3)
    l1 = np.asarray(lpa_superstep(jnp.arange(3, dtype=jnp.int32), g))
    assert l1[2] == 0
    g2 = build_graph([1, 1, 0], [2, 2, 2], num_vertices=3)
    l2 = np.asarray(lpa_superstep(jnp.arange(3, dtype=jnp.int32), g2))
    assert l2[2] == 1  # multiplicity beats the smaller-label tie-break


def test_labels_drawn_from_initial_set():
    rng = np.random.default_rng(1)
    src = rng.integers(0, 50, 200)
    dst = rng.integers(0, 50, 200)
    g = build_graph(src, dst)
    labels = np.asarray(label_propagation(g, max_iter=4))
    assert set(labels.tolist()) <= set(range(50))


def test_bundled_trajectory(bundled_graph):
    labels, changed = label_propagation(bundled_graph, max_iter=5, return_history=True)
    n = int(num_communities(labels))
    # BASELINE.md anchor: 927 -> 765 -> 716 -> 682 -> 650 (tie-break dependent).
    assert 550 <= n <= 750, n
    assert int(changed[0]) > int(changed[-1])  # propagation settles


def test_permutation_invariance_of_partition(bundled_edges):
    # Relabeling vertices must permute the partition, not change its shape.
    et = bundled_edges
    rng = np.random.default_rng(7)
    perm = rng.permutation(et.num_vertices).astype(np.int32)
    g1 = graph_from_edge_table(et)
    g2 = build_graph(perm[et.src], perm[et.dst], num_vertices=et.num_vertices)
    l1 = np.asarray(label_propagation(g1, max_iter=3))
    l2 = np.asarray(label_propagation(g2, max_iter=3))
    sizes1 = np.sort(np.unique(l1, return_counts=True)[1])
    sizes2 = np.sort(np.unique(l2, return_counts=True)[1])
    # Tie-breaks depend on ids, so exact partition equality isn't guaranteed;
    # the community-size histogram must be statistically stable.
    assert abs(len(sizes1) - len(sizes2)) <= len(sizes1) // 10


def test_bucketed_superstep_matches_sort_based(rng):
    import jax
    import jax.numpy as jnp

    from graphmine_tpu.ops.bucketed_mode import (
        BucketedModePlan,
        lpa_superstep_bucketed,
    )

    for v, e in ((40, 160), (500, 3000)):
        src = rng.integers(0, v, e).astype(np.int32)
        dst = rng.integers(0, v, e).astype(np.int32)
        g = build_graph(src, dst, num_vertices=v)
        plan = BucketedModePlan.from_graph(g)
        plan_h = BucketedModePlan.from_edges(src, dst, v)
        labels = jnp.asarray(rng.integers(0, v, v).astype(np.int32))
        want = np.asarray(jax.jit(lpa_superstep)(labels, g))
        got = np.asarray(jax.jit(lpa_superstep_bucketed)(labels, g, plan))
        got_h = np.asarray(jax.jit(lpa_superstep_bucketed)(labels, g, plan_h))
        np.testing.assert_array_equal(want, got)
        np.testing.assert_array_equal(want, got_h)
    # full run through label_propagation(plan=...)
    full = np.asarray(label_propagation(g, max_iter=5))
    fast = np.asarray(label_propagation(g, max_iter=5, plan=plan))
    np.testing.assert_array_equal(full, fast)


def test_bucketed_plan_padding_stays_tight():
    """Gathered-slots regression guard: the 1.10x width ladder (r4) holds
    plan padding <= 10% on a power-law graph — the gather-bound superstep
    pays wall-clock for every padded slot (the ladder refinement moved
    the chip tier 54.2 -> 62.6M edges/s/chip, docs/DESIGN.md), so a
    ladder change that quietly re-widens rows must fail here."""
    from graphmine_tpu.ops.bucketed_mode import build_graph_and_plan

    rng = np.random.default_rng(99)
    v, e = 20_000, 200_000
    raw = rng.pareto(1.2, size=2 * e)
    ids = np.minimum((raw * v / 30).astype(np.int64), v - 1).astype(np.int32)
    g, plan = build_graph_and_plan(ids[:e], ids[e:], num_vertices=v)
    slots = sum(int(np.prod(m.shape)) for m in plan.send_idx)
    if plan.hist_send is not None:
        slots += int(plan.hist_send.shape[0])
    messages = g.num_messages
    assert slots >= messages  # padding can't be negative
    assert slots <= 1.10 * messages, (slots, messages)


def test_bucketed_plan_graph_mismatch_raises(rng):
    import jax.numpy as jnp
    import pytest

    from graphmine_tpu.ops.bucketed_mode import (
        BucketedModePlan,
        lpa_superstep_bucketed,
    )

    g1 = build_graph(np.array([0, 1], np.int32), np.array([1, 2], np.int32),
                     num_vertices=3)
    g2 = build_graph(np.array([0, 1, 2], np.int32), np.array([1, 2, 0], np.int32),
                     num_vertices=3)
    plan = BucketedModePlan.from_graph(g1)
    with pytest.raises(ValueError, match="mismatch"):
        lpa_superstep_bucketed(jnp.arange(3, dtype=jnp.int32), g2, plan)


def test_fused_plan_mismatch_and_bad_edges_raise():
    import jax.numpy as jnp
    import pytest

    from graphmine_tpu.ops.bucketed_mode import (
        BucketedModePlan,
        bucketed_mode,
        lpa_superstep_bucketed,
    )

    g1e = (np.array([0, 1], np.int32), np.array([1, 2], np.int32))
    g2 = build_graph(np.array([0, 1, 2], np.int32), np.array([1, 2, 0], np.int32),
                     num_vertices=3)
    fused = BucketedModePlan.from_edges(*g1e, num_vertices=3)
    assert fused.send_idx is not None and fused.msg_idx is None
    with pytest.raises(ValueError, match="mismatch"):
        lpa_superstep_bucketed(jnp.arange(3, dtype=jnp.int32), g2, fused)
    with pytest.raises(ValueError, match="fused"):
        bucketed_mode(fused, jnp.zeros(4, jnp.int32), jnp.zeros(3, jnp.int32))
    with pytest.raises(ValueError, match="equal-length"):
        BucketedModePlan.from_edges(np.array([0]), np.array([1, 2]), num_vertices=3)


def test_bucketed_hist_path_matches_sort_based(rng, monkeypatch):
    """Mega-hub histogram mode (fused plans, degree > _HIST_MIN_DEG) agrees
    with the reference superstep — threshold lowered so small graphs hit it,
    including the budget cap that spills overflow hubs back to sort rows."""
    import importlib

    import jax
    import jax.numpy as jnp

    bm = importlib.import_module("graphmine_tpu.ops.bucketed_mode")

    monkeypatch.setattr(bm, "_HIST_MIN_DEG", 8)
    v, e = 200, 4000  # several vertices with degree > 8
    src = rng.integers(0, v, e).astype(np.int32)
    dst = rng.integers(0, v, e).astype(np.int32)
    g = build_graph(src, dst, num_vertices=v)
    plan = bm.BucketedModePlan.from_edges(src, dst, v)
    assert plan.hist_vertex_ids is not None and plan.hist_vertex_ids.size > 0
    labels = jnp.asarray(rng.integers(0, v, v).astype(np.int32))
    want = np.asarray(jax.jit(lpa_superstep)(labels, g))
    got = np.asarray(jax.jit(bm.lpa_superstep_bucketed)(labels, g, plan))
    np.testing.assert_array_equal(want, got)

    # budget cap: allow only 2 hub histograms; rest must spill to buckets
    monkeypatch.setattr(bm, "_HIST_BUDGET", 2 * v)
    plan2 = bm.BucketedModePlan.from_edges(src, dst, v)
    assert plan2.hist_vertex_ids is not None and plan2.hist_vertex_ids.size == 2
    got2 = np.asarray(jax.jit(bm.lpa_superstep_bucketed)(labels, g, plan2))
    np.testing.assert_array_equal(want, got2)


def test_auto_plan_path_matches_sort_path(rng):
    """plan='auto' (the default) engages the fused+histogram kernel above
    the message threshold and must match plan=None exactly — including a
    >2048-degree hub (histogram path) and the plan cache."""
    from graphmine_tpu.ops import lpa as lpa_mod

    v = 40_000
    hub_e = 3_000
    src = np.concatenate([
        np.zeros(hub_e, np.int32),                       # hub 0, degree 3000
        rng.integers(1, v, 31_000).astype(np.int32),
    ])
    dst = np.concatenate([
        rng.integers(1, v, hub_e).astype(np.int32),
        rng.integers(1, v, 31_000).astype(np.int32),
    ])
    g = build_graph(src, dst, num_vertices=v)
    assert g.num_messages >= (1 << 16)

    lpa_mod._auto_plan_cache.clear()
    auto = np.asarray(label_propagation(g, max_iter=3))          # builds plan
    assert len(lpa_mod._auto_plan_cache) == 1
    auto2 = np.asarray(label_propagation(g, max_iter=3))         # cache hit
    assert len(lpa_mod._auto_plan_cache) == 1
    none = np.asarray(label_propagation(g, max_iter=3, plan=None))
    np.testing.assert_array_equal(auto, none)
    np.testing.assert_array_equal(auto, auto2)

    # custom init_labels (possibly outside [0, V)) must stay on the sort
    # path — the fused histogram assumes labels in [0, V)
    init = jnp.arange(v, dtype=jnp.int32) + jnp.int32(1_000_000)
    got = np.asarray(label_propagation(g, max_iter=2, init_labels=init))
    want = np.asarray(label_propagation(g, max_iter=2, init_labels=init, plan=None))
    np.testing.assert_array_equal(got, want)
    assert got.max() >= v  # out-of-range labels survived untouched

    import pytest
    with pytest.raises(ValueError, match="plan must be"):
        label_propagation(g, plan="none")



def test_weighted_lpa_matches_bruteforce(rng):
    """Weighted LPA (argmax of incoming weight sums, ties -> smallest
    label) vs a numpy brute-force oracle; all-ones weights reproduce the
    unweighted kernel exactly."""
    v, e = 40, 200
    src = rng.integers(0, v, e).astype(np.int32)
    dst = rng.integers(0, v, e).astype(np.int32)
    w = rng.uniform(0.5, 2.0, e).astype(np.float32)

    g_w = build_graph(src, dst, num_vertices=v, edge_weights=w)
    g_1 = build_graph(src, dst, num_vertices=v, edge_weights=np.ones(e, np.float32))
    g_u = build_graph(src, dst, num_vertices=v)

    labels0 = np.arange(v, dtype=np.int32)

    def brute_step(lab, weights):
        out = lab.copy()
        for u in range(v):
            sums = {}
            for s, d, wt in zip(src, dst, weights):
                if d == u:
                    sums[lab[s]] = sums.get(lab[s], 0.0) + wt
                if s == u:
                    sums[lab[d]] = sums.get(lab[d], 0.0) + wt
            if sums:
                best = max(sums.values())
                out[u] = min(l for l, x in sums.items() if np.isclose(x, best))
        return out

    want = labels0.copy()
    got = jnp.asarray(labels0)
    for _ in range(3):
        want = brute_step(want, w.astype(np.float64))
        got = lpa_superstep(got, g_w)
    np.testing.assert_array_equal(want, np.asarray(got))

    # ones-weighted == unweighted, full run
    np.testing.assert_array_equal(
        np.asarray(label_propagation(g_1, max_iter=5)),
        np.asarray(label_propagation(g_u, max_iter=5, plan=None)),
    )

    # guard: a weighted graph needs a plan that carries the weight payload
    import pytest

    from graphmine_tpu.ops.bucketed_mode import BucketedModePlan, lpa_superstep_bucketed
    plan = BucketedModePlan.from_graph(g_u)
    with pytest.raises(ValueError, match="weight payload"):
        lpa_superstep_bucketed(jnp.asarray(labels0), g_w, plan)
    from graphmine_tpu.parallel.sharded import partition_graph
    assert partition_graph(g_w, num_shards=2).msg_weight is not None
    # r2: the sharded bucket plan carries weights too
    assert partition_graph(g_w, num_shards=2, build_bucket_plan=True).bucket_weight


def test_segmented_row_cumsum_matches_sequential():
    """The unrolled Hillis-Steele segmented scan (r4 replacement for
    lax.associative_scan, whose per-width-class Mosaic compile blew the
    weighted chip tier's 900s timeout on real TPU) must match a
    sequential reference at every width class shape — including w=1,
    odd widths, and rows whose first flag is not set (the scan's
    identity padding must behave as 'run continues from nothing')."""
    import jax.numpy as jnp

    from graphmine_tpu.ops.bucketed_mode import _segmented_row_cumsum

    # own-seed rng: inputs must not depend on the session fixture's
    # stream position (selection/order reproducibility)
    rng = np.random.default_rng(1234)
    for w in (1, 2, 3, 5, 8, 17, 33, 100, 128):
        n = 7
        flags = rng.random((n, w)) < 0.3
        vals = rng.uniform(0.0, 10.0, (n, w)).astype(np.float32)
        want = np.zeros_like(vals)
        for i in range(n):
            acc = 0.0
            for j in range(w):
                acc = float(vals[i, j]) if flags[i, j] else acc + float(vals[i, j])
                want[i, j] = acc
        got = np.asarray(_segmented_row_cumsum(
            jnp.asarray(flags), jnp.asarray(vals)
        ))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_rowwise_wmode_precision_at_large_prefixes(rng):
    """Regression: per-run weight totals must not be computed as
    differences of a row-wide float32 cumsum — at ~2e7 prefix magnitude
    the ulp is 2.0 and close rivals misrank. The segmented-scan
    implementation keeps error bounded by within-run accumulation, so it
    must match a float64 brute force whenever the float64 top-2 margin
    exceeds 1.0 (old implementation: fails this fuzz)."""
    import jax.numpy as jnp

    from graphmine_tpu.ops.bucketed_mode import _SENTINEL, _rowwise_wmode

    checked = 0
    for trial in range(200):
        r = np.random.default_rng(trial)
        w_row = 64
        lbl = np.sort(r.integers(0, 20, w_row)).astype(np.int32)
        wgt = r.uniform(1e5, 4e5, w_row).astype(np.float32)
        sums = {}
        for l, x in zip(lbl, wgt):
            sums[int(l)] = sums.get(int(l), 0.0) + float(x)  # float64
        top = sorted(sums.items(), key=lambda kv: (-kv[1], kv[0]))
        if len(top) > 1 and top[0][1] - top[1][1] <= 1.0:
            continue  # genuine near-tie: either winner is legitimate
        got = int(_rowwise_wmode(jnp.asarray(lbl)[None, :],
                                 jnp.asarray(wgt)[None, :])[0])
        assert got == top[0][0], (trial, got, top[:2])
        checked += 1
    assert checked > 150  # the margin guard must not eat the fuzz

    # sentinel slots are excluded even at big magnitudes
    lbl = np.array([[3, 3, 7, _SENTINEL]], np.int32)
    wgt = np.array([[1e7, 1e7, 5.0, 9e9]], np.float32)
    assert int(_rowwise_wmode(jnp.asarray(lbl), jnp.asarray(wgt))[0]) == 3


def test_weighted_bucketed_kernel_matches_sort_kernel(rng, monkeypatch):
    """r2: weighted LPA rides the fused bucketed fast path (VERDICT r1
    weak item 7). Parity with the sort-based superstep across the fused,
    non-fused, and mega-hub-histogram paths. Weights are multiples of
    1/4 so float32 sums are exact under any summation order — the two
    kernels sum per-label weights in different orders, and near-tie
    rounding is the one place they could legitimately diverge."""
    import importlib

    import jax

    bm = importlib.import_module("graphmine_tpu.ops.bucketed_mode")

    v, e = 300, 6000
    raw = rng.pareto(1.2, size=2 * e)  # power-law skew: many width classes
    ids = np.minimum((raw * v / 20).astype(np.int64), v - 1).astype(np.int32)
    src, dst = ids[:e], ids[e:]
    w = (rng.integers(1, 16, e) / 4.0).astype(np.float32)

    graph, plan = bm.build_graph_and_plan(src, dst, num_vertices=v, edge_weights=w)
    assert plan.weight_mat is not None

    want = jnp.arange(v, dtype=jnp.int32)
    got = jnp.arange(v, dtype=jnp.int32)
    step = jax.jit(bm.lpa_superstep_bucketed)
    for _ in range(4):
        want = lpa_superstep(want, graph)  # sort-based reference
        got = step(got, graph, plan)
    np.testing.assert_array_equal(np.asarray(want), np.asarray(got))

    # non-fused weighted plan (msg_idx + weight_mat) via from_graph
    plan_nf = bm.BucketedModePlan.from_graph(graph)
    got_nf = jnp.arange(v, dtype=jnp.int32)
    for _ in range(4):
        got_nf = step(got_nf, graph, plan_nf)
    np.testing.assert_array_equal(np.asarray(want), np.asarray(got_nf))

    # weighted mega-hub histogram path (threshold lowered to trigger it)
    monkeypatch.setattr(bm, "_HIST_MIN_DEG", 8)
    graph_h, plan_h = bm.build_graph_and_plan(
        src, dst, num_vertices=v, edge_weights=w
    )
    assert plan_h.hist_vertex_ids is not None and plan_h.hist_weight is not None
    got_h = jnp.arange(v, dtype=jnp.int32)
    want_h = jnp.arange(v, dtype=jnp.int32)
    for _ in range(3):
        want_h = lpa_superstep(want_h, graph_h)
        got_h = step(got_h, graph_h, plan_h)
    np.testing.assert_array_equal(np.asarray(want_h), np.asarray(got_h))

    # degree-1/degree-2 weighted exact classes: a tiny graph whose every
    # decision is a w=1 copy or a w=2 weighted pick
    src2 = np.array([0, 1, 3], np.int32)
    dst2 = np.array([2, 2, 4], np.int32)
    w2 = np.array([1.0, 2.0, 1.0], np.float32)
    g2, p2 = bm.build_graph_and_plan(src2, dst2, num_vertices=5, edge_weights=w2)
    lbl = step(jnp.arange(5, dtype=jnp.int32), g2, p2)
    assert int(lbl[2]) == 1  # weight 2.0 from vertex 1 beats 1.0 from 0
    assert int(lbl[4]) == 3 and int(lbl[3]) == 4  # w=1 copies


def test_weighted_hub_all_zero_weights_cross_path_agreement(monkeypatch):
    """ADVICE r2: a mega-hub whose every incoming weight is exactly 0
    (legal — validation only requires >= 0) must still adopt the smallest
    *received* label, not label 0. The unmasked all-zero histogram row
    argmaxed to 0 even when the hub never received label 0.

    Own-seed rng (not the session fixture): cross-path equality tests must
    be order-independent — the r2 full-suite-only flakes came from shared
    fixture state."""
    import importlib

    import jax

    bm = importlib.import_module("graphmine_tpu.ops.bucketed_mode")

    rng = np.random.default_rng(42)
    v = 64
    hub = 50  # hub id > all its neighbor labels, and != 0
    deg = 20
    # hub receives from vertices 5..24 with weight 0; plus background edges
    src = np.concatenate([
        np.arange(5, 5 + deg, dtype=np.int32),
        rng.integers(30, hub, 40).astype(np.int32),
    ])
    dst = np.concatenate([
        np.full(deg, hub, np.int32),
        rng.integers(30, hub, 40).astype(np.int32),
    ])
    w = np.concatenate([
        np.zeros(deg, np.float32),
        np.ones(40, np.float32),
    ])
    monkeypatch.setattr(bm, "_HIST_MIN_DEG", 8)
    graph, plan = bm.build_graph_and_plan(src, dst, num_vertices=v, edge_weights=w)
    assert plan.hist_vertex_ids is not None and hub in np.asarray(plan.hist_vertex_ids)

    init = jnp.arange(v, dtype=jnp.int32)
    got = jax.jit(bm.lpa_superstep_bucketed)(init, graph, plan)
    want = lpa_superstep(init, graph)  # sort-based segment_mode reference
    # the hub's messages all carry weight 0 -> smallest received label (5)
    assert int(want[hub]) == 5
    np.testing.assert_array_equal(np.asarray(want), np.asarray(got))


def test_weighted_build_validation():
    import pytest

    with pytest.raises(ValueError, match="non-negative"):
        build_graph([0, 1], [1, 0], num_vertices=2,
                    edge_weights=np.array([1.0, -0.5], np.float32))
    with pytest.raises(ValueError, match="out of range"):
        build_graph([0, 5], [1, 2], num_vertices=3,
                    edge_weights=np.array([1.0, 1.0], np.float32))
    with pytest.raises(ValueError, match="one float per edge"):
        build_graph([0, 1], [1, 0], num_vertices=2,
                    edge_weights=np.array([1.0], np.float32))


def test_weighted_mode_no_catastrophic_cancellation():
    """Per-run accumulation: a huge prefix run must not quantize away
    small weight differences later in the array (float32 global-cumsum
    differencing fails this at ~2^24 elements)."""
    from graphmine_tpu.ops.segment import segment_mode

    m = (1 << 24) + 16
    seg = np.zeros(m, np.int32)
    val = np.zeros(m, np.int32)
    w = np.ones(m, np.float32)
    # segment 1 at the tail: label 1 sums to 5.0, label 2 sums to 5.7
    seg[-16:] = 1
    val[-16:-8] = 1
    w[-16:-8] = np.float32(5.0 / 8)
    val[-8:] = 2
    w[-8:] = np.float32(5.7 / 8)
    mode, count = segment_mode(jnp.asarray(seg), jnp.asarray(val), 2,
                               weights=jnp.asarray(w))
    assert int(mode[1]) == 2
    np.testing.assert_allclose(float(count[1]), 5.7, rtol=1e-5)


def test_weight_nan_rejected_and_hist_plan_label_range_guard(rng):
    import pytest

    with pytest.raises(ValueError, match="NaN"):
        build_graph([0, 1], [1, 0], num_vertices=2,
                    edge_weights=np.array([1.0, np.nan], np.float32))

    # explicit fused plan + out-of-range init_labels: loud error, not
    # silent label-0 corruption via the dropped histogram scatter
    import importlib

    bm = importlib.import_module("graphmine_tpu.ops.bucketed_mode")
    v, e = 100, 1500
    src = np.concatenate([np.zeros(900, np.int32),
                          rng.integers(1, v, 600).astype(np.int32)])
    dst = rng.integers(1, v, 1500).astype(np.int32)
    import unittest.mock
    with unittest.mock.patch.object(bm, "_HIST_MIN_DEG", 8):
        plan = bm.BucketedModePlan.from_edges(src, dst, v)
    assert plan.hist_vertex_ids is not None
    g = build_graph(src, dst, num_vertices=v)
    bad = jnp.arange(v, dtype=jnp.int32) + 1_000_000
    import pytest
    with pytest.raises(ValueError, match="histogram path"):
        label_propagation(g, max_iter=1, init_labels=bad, plan=plan)
    # in-range custom labels still work through the fused plan
    ok = jnp.asarray(rng.integers(0, v, v).astype(np.int32))
    want = np.asarray(label_propagation(g, max_iter=2, init_labels=ok, plan=None))
    got = np.asarray(label_propagation(g, max_iter=2, init_labels=ok, plan=plan))
    np.testing.assert_array_equal(want, got)
