"""Tracing & telemetry subsystem (ISSUE 3; marker ``obs``).

Covers the span tree (run -> phase -> rung -> superstep), record schema
validation, the counter/gauge registry + Prometheus textfile exporter,
heartbeats, on-device superstep telemetry (parity + no-extra-cadence),
the MetricsSink stream-append/finalize semantics, maybe_profile
hardening — and the acceptance e2e: a fault-injected CPU pipeline
(device loss + poisoned shard) whose JSONL alone lets
``tools/obs_report.py`` render a recovery timeline and per-superstep
throughput table.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from graphmine_tpu.obs import Registry, Tracer, schema
from graphmine_tpu.obs.heartbeat import Heartbeat
from graphmine_tpu.pipeline.config import PipelineConfig
from graphmine_tpu.pipeline.metrics import MetricsSink, maybe_profile
from graphmine_tpu.pipeline.resilience import ResilienceConfig

from conftest import cached_edgelist

pytestmark = pytest.mark.obs

_E2E: dict = {}


def _edgelist_path() -> str:
    if "path" not in _E2E:
        rng = np.random.default_rng(11)
        v, e = 160, 800
        src = rng.integers(0, v, e)
        dst = (src + rng.integers(1, v // 2, e)) % (v // 2) + (src // (v // 2)) * (v // 2)
        text = "".join(f"{s} {t}\n" for s, t in zip(src, dst))
        _E2E["path"] = cached_edgelist("graphmine_obs", text)
    return _E2E["path"]


def _cfg(**kw):
    base = dict(
        data_path=_edgelist_path(), data_format="edgelist",
        outlier_method="none", num_devices=1, max_iter=5,
        resilience=ResilienceConfig(backoff_base_s=0.001, backoff_max_s=0.01),
    )
    base.update(kw)
    return PipelineConfig(**base)


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------


def test_span_tree_paths_and_ids():
    tr = Tracer(run_id="r1")
    assert tr.run_id == "r1" and tr.root.path == "run"
    with tr.span("lpa") as lpa:
        assert lpa.parent_id == tr.root.span_id
        assert lpa.path == "run/lpa"
        with tr.span("rung:primary") as rung:
            assert rung.parent_id == lpa.span_id
            assert rung.path == "run/lpa/rung:primary"
            assert tr.current() is rung
        assert tr.current() is lpa
    assert tr.current() is tr.root
    assert lpa.end_mono is not None and lpa.seconds >= 0


def test_span_error_status_and_monotonic_close():
    tr = Tracer()
    with pytest.raises(ValueError):
        with tr.span("boom") as sp:
            raise ValueError("x")
    assert sp.status == "error" and sp.end_mono is not None
    # the stack unwound; the tracer is reusable
    with tr.span("after") as sp2:
        assert sp2.parent_id == tr.root.span_id


def test_tracer_other_thread_falls_back_to_root():
    tr = Tracer()
    seen = {}
    with tr.span("phase") as sp:
        def probe():
            seen["current"] = tr.current()
            seen["latest"] = tr.latest()
        t = threading.Thread(target=probe)
        t.start()
        t.join()
    # a threadless-span worker still gets run identity (root), while
    # latest() reports what the run was actually doing
    assert seen["current"] is tr.root
    assert seen["latest"] is sp


# ---------------------------------------------------------------------------
# MetricsSink integration: ids on records, span records, of_phase
# ---------------------------------------------------------------------------


def test_emit_stamps_trace_identity_and_of_phase_filters():
    m = MetricsSink(tracer=Tracer(run_id="rX"))
    with m.span("lpa"):
        m.emit("retry", stage="lpa", attempt=1, backoff_s=0.1, error="e")
    rec = m.of_phase("retry")[0]
    assert rec["run_id"] == "rX"
    assert rec["span_path"] == "run/lpa"
    assert rec["trace_id"] and rec["span_id"]
    # the span close emitted its own record, carrying its OWN identity
    sp = m.of_phase("span")[0]
    assert sp["name"] == "lpa" and sp["span_path"] == "run/lpa"
    assert sp["parent_span_id"]  # root
    # of_phase filtering is unaffected by the extra trace keys
    assert len(m.of_phase("retry")) == 1 and not m.of_phase("lpa")
    assert schema.validate_records(m.records) == []


def test_sink_without_tracer_is_unchanged():
    m = MetricsSink()
    rec = m.emit("resume", iteration=3)
    assert "run_id" not in rec and "span_id" not in rec
    with m.span("x") as sp:   # no tracer: yields None, no record
        assert sp is None
    assert not m.of_phase("span")


def test_timed_failure_identity():
    """Satellite: a raising body must leave ok=false + the classified
    error kind on the record (and re-raise) — not masquerade as success."""
    m = MetricsSink()
    with pytest.raises(ValueError, match="boom"):
        with m.timed("census"):
            raise ValueError("boom")
    rec = m.of_phase("census")[0]
    assert rec["ok"] is False and rec["error"] == "fatal"
    assert "boom" in rec["error_detail"] and rec["seconds"] >= 0

    with pytest.raises(ConnectionError):
        with m.timed("load", path="p"):
            raise ConnectionError("transport closed")
    rec = m.of_phase("load")[0]
    assert rec["ok"] is False and rec["error"] == "retryable"

    # success records carry no failure keys
    with m.timed("census"):
        pass
    assert "ok" not in m.of_phase("census")[1]


# ---------------------------------------------------------------------------
# stream append / run_start header / finalize fallbacks
# ---------------------------------------------------------------------------


def test_stream_appends_across_runs_with_run_start_headers(tmp_path):
    """Satellite: a resumed run reusing --metrics-out must append a new
    run_start-delimited segment, not clobber the prior run's records."""
    from graphmine_tpu.pipeline.driver import run_pipeline

    mo = str(tmp_path / "m.jsonl")
    run_pipeline(_cfg(max_iter=2, metrics_out=mo))
    run_pipeline(_cfg(max_iter=2, metrics_out=mo))
    recs = [json.loads(x) for x in open(mo)]
    starts = [r for r in recs if r["phase"] == "run_start"]
    ends = [r for r in recs if r["phase"] == "run_end"]
    assert len(starts) == 2 and len(ends) == 2
    assert starts[0]["run_id"] != starts[1]["run_id"]
    # both segments fully present (first run's records not clobbered)
    first = [r for r in recs if r["run_id"] == starts[0]["run_id"]]
    assert any(r["phase"] == "lpa_iter" for r in first)
    assert schema.validate_records(recs) == []


def test_finalize_append_tail_after_stream_failure(tmp_path):
    """Satellite: stream fails mid-run -> finalize appends exactly the
    records the stream never persisted (no loss, no duplicates)."""
    p = str(tmp_path / "m.jsonl")
    m = MetricsSink(stream_path=p)
    m.emit("resume", iteration=1)           # streams fine

    class _Broken:
        def write(self, _):
            raise OSError("disk full")
        def flush(self):
            pass
        def close(self):
            pass

    m._stream = _Broken()
    m.emit("resume", iteration=2)           # write fails -> streaming off
    assert m._stream_ok is False
    m.emit("resume", iteration=3)           # memory only
    out = m.finalize(p)
    assert out == p
    recs = [json.loads(x) for x in open(p)]
    assert [r["iteration"] for r in recs] == [1, 2, 3]


def test_finalize_repairs_torn_final_line(tmp_path):
    """A stream that died mid-write leaves a torn final line; finalize's
    append must not merge it with the first re-appended record."""
    p = str(tmp_path / "m.jsonl")
    m = MetricsSink(stream_path=p)
    m.emit("resume", iteration=1)
    # simulate a partial write that crashed before its newline
    m._stream.close()
    m._stream, m._stream_ok = None, False
    with open(p, "a") as f:
        f.write('{"phase": "resu')
    m.emit("resume", iteration=2)  # memory only (streaming disabled)
    m.finalize(p)
    from tools.obs_report import load_records

    recs, bad = load_records(p)
    assert bad == 1  # the torn line, counted, not merged
    assert [r["iteration"] for r in recs] == [1, 2]


def test_finalize_to_different_path_writes_all_records(tmp_path):
    p1, p2 = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    m = MetricsSink(stream_path=p1)
    m.emit("resume", iteration=1)
    m.emit("resume", iteration=2)
    m.finalize(p2)
    assert [json.loads(x)["iteration"] for x in open(p2)] == [1, 2]
    # the stream file keeps its own copy
    assert [json.loads(x)["iteration"] for x in open(p1)] == [1, 2]


def test_finalize_without_streaming_appends(tmp_path):
    p = str(tmp_path / "m.jsonl")
    with open(p, "w") as f:
        f.write(json.dumps({"phase": "resume", "t": 0, "iteration": 0}) + "\n")
    m = MetricsSink()
    m.emit("resume", iteration=1)
    m.finalize(p)
    assert [json.loads(x)["iteration"] for x in open(p)] == [0, 1]


# ---------------------------------------------------------------------------
# maybe_profile hardening
# ---------------------------------------------------------------------------


def test_maybe_profile_stop_failure_does_not_mask_body_error(tmp_path, monkeypatch):
    import jax

    monkeypatch.setattr(jax.profiler, "start_trace", lambda d: None)

    def bad_stop():
        raise RuntimeError("No profiler session active")

    monkeypatch.setattr(jax.profiler, "stop_trace", bad_stop)
    m = MetricsSink()
    with pytest.raises(ValueError, match="the real error"):
        with maybe_profile(str(tmp_path), sink=m):
            raise ValueError("the real error")
    rec = m.of_phase("profile_capture")[0]
    assert rec["ok"] is False and str(tmp_path) in rec["dir"]


def test_maybe_profile_start_failure_runs_unprofiled(tmp_path, monkeypatch):
    import jax

    def bad_start(d):
        raise RuntimeError("profiler already active")

    monkeypatch.setattr(jax.profiler, "start_trace", bad_start)
    m = MetricsSink()
    ran = []
    with maybe_profile(str(tmp_path), sink=m):
        ran.append(1)
    assert ran == [1]
    assert m.of_phase("profile_capture")[0]["ok"] is False


def test_maybe_profile_success_records_trace_dir(tmp_path, monkeypatch):
    import jax

    monkeypatch.setattr(jax.profiler, "start_trace", lambda d: None)
    monkeypatch.setattr(jax.profiler, "stop_trace", lambda: None)
    m = MetricsSink()
    with maybe_profile(str(tmp_path), sink=m):
        pass
    rec = m.of_phase("profile_capture")[0]
    assert rec["ok"] is True and rec["dir"] == str(tmp_path)


# ---------------------------------------------------------------------------
# registry + Prometheus textfile + heartbeat
# ---------------------------------------------------------------------------


def test_registry_counters_gauges_and_conflicts():
    reg = Registry()
    c = reg.counter("graphmine_retries_total", "retries")
    c.inc()
    c.inc(2)
    g = reg.gauge("graphmine_superstep")
    g.set(7)
    assert reg.values() == {"graphmine_retries_total": 3, "graphmine_superstep": 7}
    assert reg.counter("graphmine_retries_total") is c  # get-or-create
    with pytest.raises(ValueError, match="already registered"):
        reg.gauge("graphmine_retries_total")
    with pytest.raises(ValueError, match="invalid metric name"):
        reg.counter("bad name!")
    with pytest.raises(ValueError, match="only increase"):
        c.inc(-1)


def test_prometheus_textfile_format_and_labels(tmp_path):
    reg = Registry()
    reg.counter("graphmine_retries_total", "total retries").inc(4)
    reg.gauge("graphmine_superstep").set(2.5)
    p = str(tmp_path / "gm.prom")
    reg.write_textfile(p, labels={"run_id": 'r"1"'})
    text = open(p).read()
    assert "# HELP graphmine_retries_total total retries" in text
    assert "# TYPE graphmine_retries_total counter" in text
    assert 'graphmine_retries_total{run_id="r\\"1\\""} 4' in text
    assert "# TYPE graphmine_superstep gauge" in text
    assert "graphmine_superstep" in text and "2.5" in text
    # atomic: no tmp litter
    assert os.listdir(tmp_path) == ["gm.prom"]


def test_heartbeat_records_phase_gauges_rss(tmp_path):
    tr = Tracer()
    m = MetricsSink(tracer=tr)
    m.registry.gauge("graphmine_superstep").set(3)
    prom = str(tmp_path / "hb.prom")
    hb = Heartbeat(m, every_s=0.01, prom_path=prom)
    with tr.span("lpa"):
        hb.beat()
    rec = m.of_phase("heartbeat")[0]
    assert rec["uptime_s"] >= 0 and rec["busy"] == "run/lpa"
    assert rec["gauges"]["graphmine_superstep"] == 3
    assert rec.get("rss_mb", 1) > 0  # None is dropped off-Linux
    assert os.path.exists(prom)
    assert schema.validate_records(m.records) == []


def test_heartbeat_thread_beats_and_stops():
    m = MetricsSink(tracer=Tracer())
    hb = Heartbeat(m, every_s=0.01).start()
    deadline = time.time() + 2.0
    while not m.of_phase("heartbeat") and time.time() < deadline:
        time.sleep(0.01)
    hb.stop()
    n = len(m.of_phase("heartbeat"))
    assert n >= 1
    time.sleep(0.05)
    assert len(m.of_phase("heartbeat")) == n  # stopped means stopped


# ---------------------------------------------------------------------------
# schema validator
# ---------------------------------------------------------------------------


def test_schema_rejects_unknown_phase_and_missing_keys():
    ok = {"phase": "retry", "t": 1.0, "stage": "lpa", "attempt": 1,
          "backoff_s": 0.1, "error": "e"}
    assert schema.validate_record(ok) == []
    bad = dict(ok, phase="retyr")
    assert any("unknown phase" in p for p in schema.validate_record(bad))
    missing = {"phase": "retry", "t": 1.0}
    assert any("missing required keys" in p
               for p in schema.validate_record(missing))
    partial = dict(ok, run_id="r")
    assert any("partial trace identity" in p
               for p in schema.validate_record(partial))
    assert schema.validate_record({"t": 1.0}) == ["missing/empty phase in {'t': 1.0}"]


def test_schema_register_extends():
    schema.register("obs_test_phase", "k1")
    try:
        assert schema.validate_record(
            {"phase": "obs_test_phase", "t": 0.0, "k1": 1}
        ) == []
    finally:
        del schema.SCHEMAS["obs_test_phase"]


# ---------------------------------------------------------------------------
# on-device superstep telemetry (sharded API)
# ---------------------------------------------------------------------------


def _mesh_graph(num_devices=4, symmetric=True):
    import jax

    if len(jax.devices()) < num_devices:
        pytest.skip(f"needs {num_devices} virtual devices")
    from graphmine_tpu.graph.container import build_graph
    from graphmine_tpu.parallel.mesh import make_mesh
    from graphmine_tpu.parallel.sharded import (
        partition_graph,
        shard_graph_arrays,
    )

    rng = np.random.default_rng(3)
    v, e = 96, 500
    src = rng.integers(0, v, e)
    dst = rng.integers(0, v, e)
    mesh = make_mesh(num_devices)
    g = build_graph(src, dst, num_vertices=v, symmetric=symmetric,
                    to_device=False)
    sg = shard_graph_arrays(partition_graph(g, mesh=mesh), mesh)
    return g, sg, mesh, (src, dst, v)


def test_sharded_lpa_telemetry_matches_manual_diffs():
    from graphmine_tpu.parallel.sharded import sharded_label_propagation

    _, sg, mesh, _ = _mesh_graph()
    plain = np.asarray(sharded_label_propagation(sg, mesh, max_iter=4))
    labels, tel = sharded_label_propagation(sg, mesh, max_iter=4,
                                            telemetry=True)
    np.testing.assert_array_equal(np.asarray(labels), plain)  # bit-identical
    assert tel.iterations == 4
    assert tel.labels_changed.shape == (4,)
    assert tel.shard_changed.shape == (4, sg.num_shards)
    # per-shard counts sum to the global count; frontier aliases it
    np.testing.assert_array_equal(tel.shard_changed.sum(1), tel.labels_changed)
    np.testing.assert_array_equal(tel.frontier, tel.labels_changed)
    # replay the supersteps one at a time: the counters must match the
    # actual per-iteration label diffs
    prev = np.arange(sg.num_vertices, dtype=np.int32)
    for t in range(4):
        cur = np.asarray(sharded_label_propagation(
            sg, mesh, max_iter=1, init_labels=prev
        ))
        assert int((cur != prev).sum()) == tel.labels_changed[t]
        prev = cur
    imb = tel.imbalance_ratio()
    assert imb.shape == (4,) and (imb >= 1.0 - 1e-6).all()


def test_sharded_cc_and_pagerank_telemetry():
    from graphmine_tpu.ops.degrees import out_degrees
    from graphmine_tpu.parallel.sharded import (
        sharded_connected_components,
        sharded_pagerank,
    )

    _, sg, mesh, _ = _mesh_graph()
    plain = np.asarray(sharded_connected_components(sg, mesh))
    labels, tel = sharded_connected_components(sg, mesh, telemetry=True)
    np.testing.assert_array_equal(np.asarray(labels), plain)
    assert tel.iterations >= 1
    assert len(tel.labels_changed) == tel.iterations
    assert tel.labels_changed[-1] == 0  # converged: final pass changed nothing

    g, sgd, mesh, _ = _mesh_graph(symmetric=False)
    od = out_degrees(g)
    plain = np.asarray(sharded_pagerank(sgd, mesh, od, max_iter=40))
    ranks, rtel = sharded_pagerank(sgd, mesh, od, max_iter=40, telemetry=True)
    np.testing.assert_allclose(np.asarray(ranks), plain, atol=1e-6)
    assert rtel.iterations >= 2
    assert rtel.residuals.shape == (rtel.iterations,)
    assert rtel.shard_residuals.shape == (rtel.iterations, sgd.num_shards)
    # the power iteration's residual trail is broadly decreasing
    assert rtel.residuals[-1] < rtel.residuals[0]
    # per-shard residuals sum to the global L1 delta
    np.testing.assert_allclose(
        rtel.shard_residuals.sum(1), rtel.residuals, rtol=1e-4
    )


def test_sharded_lpa_telemetry_with_tripwires_armed():
    from graphmine_tpu.parallel.sharded import sharded_label_propagation

    _, sg, mesh, _ = _mesh_graph()
    plain = np.asarray(sharded_label_propagation(sg, mesh, max_iter=3))
    labels, tel = sharded_label_propagation(
        sg, mesh, max_iter=3, telemetry=True, tripwire_every=2
    )
    np.testing.assert_array_equal(np.asarray(labels), plain)
    assert tel.labels_changed.shape == (3,)


# ---------------------------------------------------------------------------
# driver cadence: telemetry piggybacks on tripwire/checkpoint boundaries
# ---------------------------------------------------------------------------


def test_superstep_telemetry_cadence(tmp_path):
    from graphmine_tpu.pipeline.driver import run_pipeline

    # no tripwires, no checkpoints: only the final superstep reports
    res = run_pipeline(_cfg(max_iter=4))
    tele = res.metrics.of_phase("superstep_telemetry")
    assert [r["iteration"] for r in tele] == [4]
    rec = tele[0]
    assert rec["frontier"] == rec["labels_changed"]
    assert sum(rec["shard_changed"]) == rec["labels_changed"]
    assert rec["imbalance"] >= 1.0

    # checkpoint cadence 2: boundaries 2, 4 and the final 5
    res = run_pipeline(_cfg(
        max_iter=5, checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=2
    ))
    tele = res.metrics.of_phase("superstep_telemetry")
    assert [r["iteration"] for r in tele] == [2, 4, 5]
    # checkpoint saves joined the stream too, span-tagged
    saves = res.metrics.of_phase("checkpoint_save")
    assert [r["iteration"] for r in saves] == [2, 4, 5]
    assert all(r["span_path"].endswith("/superstep") for r in saves)


# ---------------------------------------------------------------------------
# obs_report units
# ---------------------------------------------------------------------------


def _rec(phase, t, **kv):
    return {"phase": phase, "t": t, **kv}


def test_split_runs_and_liveness_verdicts():
    from tools.obs_report import _liveness, split_runs

    recs = (
        [_rec("run_start", 0.0, run_id="a", pid=1),
         _rec("run_end", 1.0, run_id="a", ok=True)]
        + [_rec("run_start", 2.0, run_id="b", pid=2)]
    )
    runs, order = split_runs(recs)
    assert order == ["a", "b"] and len(runs["a"]) == 2

    ok = _liveness(runs["a"], 0.0)
    assert ok[0] == "ok"
    # no run_end, no trailing heartbeats -> DEAD
    dead = _liveness([_rec("run_start", 0.0, pid=1),
                      _rec("lpa_iter", 1.0)], 0.0)
    assert dead[0] == "DEAD"
    # heartbeats continued past the last phase record -> HUNG
    hung = _liveness(
        [_rec("run_start", 0.0, pid=1), _rec("lpa_iter", 1.0),
         _rec("heartbeat", 5.0, uptime_s=5.0, busy="run/lpa/superstep")],
        0.0,
    )
    assert hung[0] == "HUNG" and "run/lpa/superstep" in hung[1]


def test_obs_report_tolerates_torn_lines(tmp_path):
    from tools.obs_report import load_records

    p = tmp_path / "m.jsonl"
    p.write_text(
        json.dumps(_rec("run_start", 0.0, run_id="a", pid=1)) + "\n"
        + '{"phase": "lpa_iter", "t": 1.0, "itera'  # torn final line
    )
    recs, bad = load_records(str(p))
    assert len(recs) == 1 and bad == 1


# ---------------------------------------------------------------------------
# acceptance e2e: fault-injected pipeline -> JSONL -> triage report
# ---------------------------------------------------------------------------


def test_recovery_records_and_report_e2e(tmp_path, capsys):
    """Acceptance: device loss + poisoned shard (testing/faults.py) on a
    4-device CPU run; every recovery record carries run/trace/span
    identity, and obs_report renders a recovery timeline + per-superstep
    throughput table from the JSONL alone."""
    import jax

    from graphmine_tpu.pipeline.driver import run_pipeline
    from graphmine_tpu.testing import faults
    from tools.obs_report import main as report_main

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    mo = str(tmp_path / "metrics.jsonl")
    cfg = _cfg(
        num_devices=4, metrics_out=mo,
        checkpoint_dir=str(tmp_path / "ck"), heartbeat_every_s=0.05,
        resilience=ResilienceConfig(
            backoff_base_s=0.001, backoff_max_s=0.01, tripwire_every_k=1,
        ),
    )
    inj = faults.FaultInjector()
    inj.add("lpa_superstep", faults.device_loss, at=3)
    inj.add("lpa_superstep", faults.poison_labels(shard=1, num_shards=2), at=6)
    with inj.installed():
        res = run_pipeline(cfg)
    assert inj.fired() == 2

    # -- every recovery record joinable: run/trace/span identity --------
    recovery = [
        r for r in res.metrics.records
        if r["phase"] in ("retry", "degrade", "mesh_degrade", "tripwire",
                          "checkpoint_rollback", "resume")
    ]
    assert {r["phase"] for r in recovery} >= {
        "retry", "degrade", "mesh_degrade", "tripwire", "resume"
    }
    run_ids = set()
    for r in recovery:
        assert r["run_id"] and r["trace_id"] and r["span_id"], r
        assert r["span_path"].startswith("run/lpa"), r
        run_ids.add((r["run_id"], r["trace_id"]))
    assert len(run_ids) == 1  # one causal timeline
    # rung identity: the mesh_degrade landed on the elastic rung's span
    md = res.metrics.of_phase("mesh_degrade")[0]
    assert "rung:elastic@2dev" in md["span_path"]
    # the tripwire fired inside a superstep span of that rung
    tw = res.metrics.of_phase("tripwire")[0]
    assert tw["span_path"].endswith("/superstep")
    # the whole stream passes schema validation — unknown shapes fail loud
    assert schema.validate_records(res.metrics.records) == []

    # -- offline triage from the JSONL alone ----------------------------
    assert report_main([mo]) == 0
    report = capsys.readouterr().out
    assert "recovery timeline" in report
    assert "mesh_degrade" in report and "from_devices=4" in report
    assert "tripwire" in report and "label_out_of_range" in report
    assert "[lpa/rung:elastic@2dev" in report      # span path rendered
    # per-superstep throughput table: all 5 supersteps with the metric
    assert "edges/sec/chip" in report
    table = report.split("-- lpa supersteps --")[1].split("--")[0]
    rows = [ln for ln in table.splitlines() if ln.strip()]
    assert len(rows) == 1 + 5  # header + max_iter supersteps
    assert "status: ok" in report
    assert "beats" in report  # heartbeat section rendered


def test_report_flags_dead_run(tmp_path, capsys):
    """A preempted run (no run_end) must read as DEAD, with its partial
    superstep trail still rendered from the streamed records."""
    from graphmine_tpu.pipeline.driver import run_pipeline
    from graphmine_tpu.testing import faults
    from tools.obs_report import main as report_main

    mo = str(tmp_path / "metrics.jsonl")
    inj = faults.FaultInjector()
    inj.add("lpa_superstep", faults.preemption, at=3)
    with inj.installed():
        with pytest.raises(faults.SimulatedPreemption):
            run_pipeline(_cfg(metrics_out=mo, checkpoint_dir=str(tmp_path / "ck")))
    # simulate the kill: strip the orderly run_end/finalize tail the real
    # preemption would never have written
    lines = [ln for ln in open(mo)
             if json.loads(ln)["phase"] not in ("run_end",)]
    with open(mo, "w") as f:
        f.writelines(lines)
    assert report_main([mo]) == 0
    report = capsys.readouterr().out
    assert "DEAD" in report
    assert "lpa supersteps" in report


def test_report_missing_file_and_unknown_run(tmp_path, capsys):
    from tools.obs_report import main as report_main

    assert report_main([str(tmp_path / "nope.jsonl")]) == 2
    mo = str(tmp_path / "m.jsonl")
    with open(mo, "w") as f:
        f.write(json.dumps(_rec("run_start", 0.0, run_id="a", pid=1)) + "\n")
    assert report_main([mo, "--run-id", "zzz"]) == 2
    capsys.readouterr()
