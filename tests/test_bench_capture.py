"""Unit tests for bench.py's capture orchestration (the r2 fix for the
round-1 artifact failures: probe watchdog, retry, record salvage, honest
CPU fallback, one parseable JSON line in every outcome).

The measurement tiers themselves are exercised by running them (verify
skill); these tests pin the *orchestration* logic with subprocess calls
mocked, so every failure branch is cheap and deterministic.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import bench  # noqa: E402


class _Proc:
    def __init__(self, returncode=0, stdout="", stderr=""):
        self.returncode = returncode
        self.stdout = stdout
        self.stderr = stderr


def _record(metric="m", **kw):
    rec = {"metric": metric, "value": 1, "unit": "u", "vs_baseline": 1.0}
    rec.update(kw)
    return json.dumps(rec)


def test_probe_reports_platform(monkeypatch):
    monkeypatch.setattr(
        bench.subprocess, "run",
        lambda *a, **k: _Proc(stdout="tpu 1 TPU_0\n"),
    )
    ok, platform, info = bench._probe_tpu(timeout_s=1)
    assert ok and platform == "tpu" and "TPU_0" in info


def test_probe_timeout_and_rc(monkeypatch):
    def boom(*a, **k):
        raise subprocess.TimeoutExpired(cmd="x", timeout=1)

    monkeypatch.setattr(bench.subprocess, "run", boom)
    ok, platform, info = bench._probe_tpu(timeout_s=1)
    assert not ok and platform is None and "timed out" in info

    monkeypatch.setattr(
        bench.subprocess, "run",
        lambda *a, **k: _Proc(returncode=1, stderr="RuntimeError: dead\n"),
    )
    ok, platform, info = bench._probe_tpu(timeout_s=1)
    assert not ok and "rc=1" in info and "dead" in info


def test_run_child_parses_last_record_and_forwards_noise(monkeypatch, capsys):
    noise = 'warming up\n{"not": "a record"}\n{bad json\n'
    monkeypatch.setattr(
        bench.subprocess, "run",
        lambda *a, **k: _Proc(stdout=noise + _record("good") + "\n"),
    )
    rec, err = bench._run_child("chip", dict(os.environ), 5)
    assert err is None and rec["metric"] == "good"
    # non-record stdout lines went to stderr, not into the record stream
    assert "warming up" in capsys.readouterr().err


def test_run_child_salvages_record_on_nonzero_exit(monkeypatch):
    """A completed measurement followed by a teardown crash (the round-1
    flaky-exit class) keeps the real record and discloses the rc."""
    monkeypatch.setattr(
        bench.subprocess, "run",
        lambda *a, **k: _Proc(returncode=139, stdout=_record("salvaged") + "\n"),
    )
    rec, err = bench._run_child("chip", dict(os.environ), 5)
    assert err is None
    assert rec["metric"] == "salvaged"
    assert rec["detail"]["child_rc"] == 139


def test_run_child_failure_paths(monkeypatch):
    monkeypatch.setattr(
        bench.subprocess, "run", lambda *a, **k: _Proc(returncode=1)
    )
    rec, err = bench._run_child("chip", dict(os.environ), 5)
    assert rec is None and "rc=1" in err

    def boom(*a, **k):
        raise subprocess.TimeoutExpired(cmd="x", timeout=5)

    monkeypatch.setattr(bench.subprocess, "run", boom)
    rec, err = bench._run_child("chip", dict(os.environ), 5)
    assert rec is None and "timed out" in err

    monkeypatch.setattr(bench.subprocess, "run", lambda *a, **k: _Proc())
    rec, err = bench._run_child("chip", dict(os.environ), 5)
    assert rec is None and "no JSON record" in err


def _fake_runner(script):
    """Build a subprocess.run replacement driven by a list of outcomes.

    Each entry handles one call: a _Proc to return, or 'timeout' to raise.
    Records (cmd, env) per call for assertions.
    """
    calls = []

    def run(cmd, **kw):
        calls.append((cmd, kw.get("env")))
        out = script.pop(0)
        if out == "timeout":
            raise subprocess.TimeoutExpired(cmd=cmd, timeout=kw.get("timeout"))
        return out

    return run, calls


def _probe_ok(platform="tpu"):
    return _Proc(stdout=f"{platform} 1 dev\n")


def test_orchestrate_happy_path_annotates_capture(monkeypatch, capsys):
    run, calls = _fake_runner([
        _probe_ok(),
        _Proc(stdout=_record("tpu_result") + "\n"),
        _Proc(returncode=0, stdout="all backends agree\n"),  # audit
    ])
    monkeypatch.setattr(bench.subprocess, "run", run)
    monkeypatch.delenv("GRAPHMINE_BENCH_AUDIT", raising=False)
    monkeypatch.delenv("GRAPHMINE_BENCH_BUDGET", raising=False)
    rc = bench.orchestrate("chip")
    assert rc == 0
    rec = json.loads(capsys.readouterr().out.strip())
    cap = rec["detail"]["capture"]
    assert rec["metric"] == "tpu_result"
    assert cap["attempts"] == 1 and cap["platform"] == "tpu"
    assert cap["cpu_fallback"] is None
    assert cap["backend_audit"] == "agree"


def test_orchestrate_retries_then_falls_back(monkeypatch, capsys):
    """Probe ok but both measurement attempts die -> scrubbed CPU fallback
    with the failure trail attached."""
    run, calls = _fake_runner([
        _probe_ok(),
        "timeout",          # run1
        _probe_ok(),
        _Proc(returncode=1),  # run2
        _Proc(stdout=_record("fallback_result") + "\n"),  # cpu fallback
    ])
    monkeypatch.setattr(bench.subprocess, "run", run)
    monkeypatch.setenv("GRAPHMINE_BENCH_AUDIT", "0")
    monkeypatch.delenv("GRAPHMINE_BENCH_BUDGET", raising=False)
    rc = bench.orchestrate("chip")
    assert rc == 0
    rec = json.loads(capsys.readouterr().out.strip())
    cap = rec["detail"]["capture"]
    assert rec["metric"] == "fallback_result"
    assert "run1" in cap["cpu_fallback"] and "run2" in cap["cpu_fallback"]
    # the fallback child got the scrubbed env with the fallback flag
    fb_env = calls[-1][1]
    assert fb_env["GRAPHMINE_BENCH_CPU_FALLBACK"] == "1"
    assert fb_env["JAX_PLATFORMS"] == "cpu"
    assert fb_env["PALLAS_AXON_POOL_IPS"] == ""


def test_orchestrate_cpu_platform_goes_straight_to_fallback(monkeypatch, capsys):
    """A probe that finds a CPU-only backend must not run the full-scale
    tier under the TPU metric name (and must skip the vacuous audit)."""
    run, calls = _fake_runner([
        _probe_ok(platform="cpu"),
        _Proc(stdout=_record("fallback_result") + "\n"),
    ])
    monkeypatch.setattr(bench.subprocess, "run", run)
    monkeypatch.delenv("GRAPHMINE_BENCH_BUDGET", raising=False)
    rc = bench.orchestrate("chip")
    assert rc == 0
    rec = json.loads(capsys.readouterr().out.strip())
    cap = rec["detail"]["capture"]
    assert cap["cpu_fallback"] and "not tpu" in cap["cpu_fallback"]
    assert "backend_audit" not in cap
    assert calls[-1][1]["GRAPHMINE_BENCH_CPU_FALLBACK"] == "1"


def test_orchestrate_total_failure_emits_error_record(monkeypatch, capsys):
    def always_timeout(*a, **k):
        raise subprocess.TimeoutExpired(cmd="x", timeout=1)

    monkeypatch.setattr(bench.subprocess, "run", always_timeout)
    monkeypatch.delenv("GRAPHMINE_BENCH_BUDGET", raising=False)
    rc = bench.orchestrate("chip")
    assert rc == 1
    rec = json.loads(capsys.readouterr().out.strip())
    assert rec["metric"] == "bench_chip_capture_failed"
    assert rec["value"] == 0.0 and "error" in rec


def test_orchestrate_budget_skips_attempts(monkeypatch, capsys):
    """An exhausted budget skips TPU attempts but still reserves room for
    the fallback record."""
    run, calls = _fake_runner([
        _Proc(stdout=_record("fallback_result") + "\n"),
    ])
    monkeypatch.setattr(bench.subprocess, "run", run)
    monkeypatch.setenv("GRAPHMINE_BENCH_BUDGET", "100")  # < reserve + 60
    rc = bench.orchestrate("chip")
    assert rc == 0
    rec = json.loads(capsys.readouterr().out.strip())
    cap = rec["detail"]["capture"]
    assert any("budget exhausted" in f for f in cap["failures"])
    assert len(calls) == 1  # no probes, straight to fallback
