"""Unit tests for bench.py's capture orchestration (the r2 fix for the
round-1 artifact failures: probe watchdog, retry, record salvage, honest
CPU fallback, one parseable JSON line in every outcome).

The measurement tiers themselves are exercised by running them (verify
skill); these tests pin the *orchestration* logic with subprocess calls
mocked, so every failure branch is cheap and deterministic.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import bench  # noqa: E402


class _Proc:
    def __init__(self, returncode=0, stdout="", stderr=""):
        self.returncode = returncode
        self.stdout = stdout
        self.stderr = stderr


def _record(metric="m", **kw):
    rec = {"metric": metric, "value": 1, "unit": "u", "vs_baseline": 1.0}
    rec.update(kw)
    return json.dumps(rec)


def test_probe_reports_platform(monkeypatch):
    monkeypatch.setattr(
        bench.subprocess, "run",
        lambda *a, **k: _Proc(stdout="tpu 1 TPU_0\n"),
    )
    ok, platform, info = bench._probe_tpu(timeout_s=1)
    assert ok and platform == "tpu" and "TPU_0" in info


def test_probe_timeout_and_rc(monkeypatch):
    def boom(*a, **k):
        raise subprocess.TimeoutExpired(cmd="x", timeout=1)

    monkeypatch.setattr(bench.subprocess, "run", boom)
    ok, platform, info = bench._probe_tpu(timeout_s=1)
    assert not ok and platform is None and "timed out" in info

    monkeypatch.setattr(
        bench.subprocess, "run",
        lambda *a, **k: _Proc(returncode=1, stderr="RuntimeError: dead\n"),
    )
    ok, platform, info = bench._probe_tpu(timeout_s=1)
    assert not ok and "rc=1" in info and "dead" in info


def test_run_child_parses_last_record_and_forwards_noise(monkeypatch, capsys):
    noise = 'warming up\n{"not": "a record"}\n{bad json\n'
    monkeypatch.setattr(
        bench.subprocess, "run",
        lambda *a, **k: _Proc(stdout=noise + _record("good") + "\n"),
    )
    rec, err = bench._run_child("chip", dict(os.environ), 5)
    assert err is None and rec["metric"] == "good"
    # non-record stdout lines went to stderr, not into the record stream
    assert "warming up" in capsys.readouterr().err


def test_run_child_salvages_record_on_nonzero_exit(monkeypatch):
    """A completed measurement followed by a teardown crash (the round-1
    flaky-exit class) keeps the real record and discloses the rc."""
    monkeypatch.setattr(
        bench.subprocess, "run",
        lambda *a, **k: _Proc(returncode=139, stdout=_record("salvaged") + "\n"),
    )
    rec, err = bench._run_child("chip", dict(os.environ), 5)
    assert err is None
    assert rec["metric"] == "salvaged"
    assert rec["detail"]["child_rc"] == 139


def test_run_child_failure_paths(monkeypatch):
    monkeypatch.setattr(
        bench.subprocess, "run", lambda *a, **k: _Proc(returncode=1)
    )
    rec, err = bench._run_child("chip", dict(os.environ), 5)
    assert rec is None and "rc=1" in err

    def boom(*a, **k):
        raise subprocess.TimeoutExpired(cmd="x", timeout=5)

    monkeypatch.setattr(bench.subprocess, "run", boom)
    rec, err = bench._run_child("chip", dict(os.environ), 5)
    assert rec is None and "timed out" in err

    monkeypatch.setattr(bench.subprocess, "run", lambda *a, **k: _Proc())
    rec, err = bench._run_child("chip", dict(os.environ), 5)
    assert rec is None and "no JSON record" in err


def _fake_runner(script):
    """Build a subprocess.run replacement driven by a list of outcomes.

    Each entry handles one call: a _Proc to return, or 'timeout' to raise.
    Records (cmd, env) per call for assertions.
    """
    calls = []

    def run(cmd, **kw):
        calls.append((cmd, kw.get("env")))
        out = script.pop(0)
        if out == "timeout":
            raise subprocess.TimeoutExpired(cmd=cmd, timeout=kw.get("timeout"))
        return out

    return run, calls


def _probe_ok(platform="tpu"):
    return _Proc(stdout=f"{platform} 1 dev\n")


def test_orchestrate_happy_path_annotates_capture(monkeypatch, capsys):
    run, calls = _fake_runner([
        _probe_ok(),
        _Proc(stdout=_record("tpu_result") + "\n"),
        _Proc(returncode=0, stdout="all backends agree\n"),  # audit
    ])
    monkeypatch.setattr(bench.subprocess, "run", run)
    monkeypatch.delenv("GRAPHMINE_BENCH_AUDIT", raising=False)
    monkeypatch.delenv("GRAPHMINE_BENCH_BUDGET", raising=False)
    rc = bench.orchestrate("chip")
    assert rc == 0
    lines = capsys.readouterr().out.strip().splitlines()
    rec = json.loads(lines[0])
    cap = rec["detail"]["capture"]
    assert rec["metric"] == "tpu_result"
    assert cap["attempts"] == 1 and cap["platform"] == "tpu"
    assert cap["cpu_fallback"] is None
    assert cap["backend_audit"] == "agree"
    # every orchestrated run ends with the suite-summary record
    summary = json.loads(lines[-1])
    assert summary["metric"] == "tpu_result" and "suite" in summary


def test_orchestrate_retries_then_falls_back(monkeypatch, capsys):
    """Probe ok but both measurement attempts die -> scrubbed CPU fallback
    with the failure trail attached."""
    run, calls = _fake_runner([
        _probe_ok(),
        "timeout",          # run1
        _probe_ok(),
        _Proc(returncode=1),  # run2
        _Proc(stdout=_record("fallback_result") + "\n"),  # cpu fallback
    ])
    monkeypatch.setattr(bench.subprocess, "run", run)
    monkeypatch.setenv("GRAPHMINE_BENCH_AUDIT", "0")
    monkeypatch.delenv("GRAPHMINE_BENCH_BUDGET", raising=False)
    rc = bench.orchestrate("chip")
    assert rc == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[0])
    cap = rec["detail"]["capture"]
    assert rec["metric"] == "fallback_result"
    assert "run1" in cap["cpu_fallback"] and "run2" in cap["cpu_fallback"]
    # the fallback child got the scrubbed env with the fallback flag
    fb_env = calls[-1][1]
    assert fb_env["GRAPHMINE_BENCH_CPU_FALLBACK"] == "1"
    assert fb_env["JAX_PLATFORMS"] == "cpu"
    assert fb_env["PALLAS_AXON_POOL_IPS"] == ""


def test_orchestrate_cpu_platform_goes_straight_to_fallback(monkeypatch, capsys):
    """A probe that finds a CPU-only backend must not run the full-scale
    tier under the TPU metric name (and must skip the vacuous audit)."""
    run, calls = _fake_runner([
        _probe_ok(platform="cpu"),
        _Proc(stdout=_record("fallback_result") + "\n"),
    ])
    monkeypatch.setattr(bench.subprocess, "run", run)
    monkeypatch.delenv("GRAPHMINE_BENCH_BUDGET", raising=False)
    rc = bench.orchestrate("chip")
    assert rc == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[0])
    cap = rec["detail"]["capture"]
    assert cap["cpu_fallback"] and "not tpu" in cap["cpu_fallback"]
    assert "backend_audit" not in cap
    assert calls[-1][1]["GRAPHMINE_BENCH_CPU_FALLBACK"] == "1"


def test_orchestrate_total_failure_emits_error_record(monkeypatch, capsys):
    """All probes and the fallback dead: spaced re-probes burn the probe
    window (with inter-probe sleeps) and the error record still prints."""
    def always_timeout(*a, **k):
        raise subprocess.TimeoutExpired(cmd="x", timeout=1)

    sleeps = []
    monkeypatch.setattr(bench.subprocess, "run", always_timeout)
    monkeypatch.setattr(bench, "_sleep", sleeps.append)
    monkeypatch.delenv("GRAPHMINE_BENCH_BUDGET", raising=False)
    rc = bench.orchestrate("chip")
    assert rc == 1
    lines = capsys.readouterr().out.strip().splitlines()
    rec = json.loads(lines[0])
    assert rec["metric"] == "bench_chip_capture_failed"
    assert rec["value"] == 0.0 and "error" in rec
    # spaced probing actually happened: multiple probes, sleeps between
    assert len(sleeps) >= 2 and all(0 <= s <= 180 for s in sleeps)
    assert rec["error"].count("probe") >= 3
    # with no real record anywhere, the summary headline is the error
    summary = json.loads(lines[-1])
    assert summary["metric"] == "bench_chip_capture_failed"
    assert summary["suite"]["probes"]["ok"] == 0
    assert summary["suite"]["probes"]["n"] >= 3


def test_orchestrate_all_healthy_prints_every_tier_chip_first(
    monkeypatch, capsys
):
    """A healthy TPU window captures the whole evidence suite: one JSON
    line per tier, chip first (the driver parses the first line), full
    reachability trace + audit attached to the chip record only."""
    script = [_probe_ok()]
    for t in bench._TIER_ORDER:
        script.append(_Proc(stdout=_record(f"{t}_result") + "\n"))
    # audit runs after the chip child, before the chip record prints
    script.insert(2, _Proc(returncode=0, stdout="all backends agree\n"))
    run, calls = _fake_runner(script)
    monkeypatch.setattr(bench.subprocess, "run", run)
    monkeypatch.delenv("GRAPHMINE_BENCH_AUDIT", raising=False)
    monkeypatch.delenv("GRAPHMINE_BENCH_BUDGET", raising=False)
    rc = bench.orchestrate("all")
    assert rc == 0
    lines = capsys.readouterr().out.strip().splitlines()
    recs = [json.loads(l) for l in lines]
    assert [r["metric"] for r in recs[:-1]] == [
        f"{t}_result" for t in bench._TIER_ORDER
    ]
    chip_cap = recs[0]["detail"]["capture"]
    assert chip_cap["backend_audit"] == "agree"
    assert chip_cap["trace"] and chip_cap["trace"][0]["ok"]
    assert "utc" in chip_cap["trace"][0]
    for r in recs[1:-1]:
        cap = r["detail"]["capture"]
        assert cap["platform"] == "tpu" and "trace" not in cap
    # LAST line = suite summary: chip headline + every tier + probe digest,
    # bounded well inside the driver artifact's 2000-char stdout tail
    summary = recs[-1]
    assert summary["metric"] == "chip_result"
    assert summary["value"] == 1 and summary["unit"] == "u"
    assert set(summary["suite"]["tiers"]) == set(bench._TIER_ORDER)
    assert summary["suite"]["platform"] == "tpu"
    assert summary["suite"]["probes"]["ok"] >= 1
    assert len(lines[-1]) < 1600


def test_orchestrate_all_dead_tunnel_fallback_all_tiers(monkeypatch, capsys):
    """Tunnel dead all round: reduced-scale CPU fallback records for every
    fallback tier, chip first, with the probe trace proving the
    environment (not the code) was the blocker."""
    script = ["timeout"]  # single probe (window shrunk below)
    for t in bench._FALLBACK_TIERS:
        script.append(_Proc(stdout=_record(f"{t}_fb") + "\n"))
    run, calls = _fake_runner(script)
    monkeypatch.setattr(bench.subprocess, "run", run)
    monkeypatch.setattr(bench, "_sleep", lambda s: None)
    monkeypatch.setenv("GRAPHMINE_BENCH_PROBE_WINDOW", "0")
    monkeypatch.delenv("GRAPHMINE_BENCH_BUDGET", raising=False)
    rc = bench.orchestrate("all")
    assert rc == 0
    lines = capsys.readouterr().out.strip().splitlines()
    recs = [json.loads(l) for l in lines]
    assert [r["metric"] for r in recs[:-1]] == [
        f"{t}_fb" for t in bench._FALLBACK_TIERS
    ]
    cap = recs[0]["detail"]["capture"]
    assert cap["cpu_fallback"] and "timed out" in cap["cpu_fallback"]
    assert cap["trace"] and not cap["trace"][0]["ok"]
    # roofline is TPU-model validation: absent from the fallback suite
    assert not any("roofline" in r["metric"] for r in recs)
    # every fallback child ran scrubbed with the reduced-scale flag
    for _, env in calls[1:]:
        assert env["GRAPHMINE_BENCH_CPU_FALLBACK"] == "1"
    # the dead-tunnel rehearsal the r3 verdict asked for: the LAST record
    # (what the driver artifact parses) carries the chip fallback number,
    # every fallback tier's value, and the probe evidence
    summary = recs[-1]
    assert summary["metric"] == "chip_fb"
    assert set(summary["suite"]["tiers"]) == set(bench._FALLBACK_TIERS)
    assert summary["suite"]["platform"] == "unreachable"
    assert summary["suite"]["probes"]["ok"] == 0
    assert "timed out" in summary["suite"]["probes"]["first"]["info"]
    assert len(lines[-1]) < 1600


def test_orchestrate_all_backend_death_mid_capture_skips_rest(
    monkeypatch, capsys
):
    """Tunnel dies between tiers: the failing tier re-probes, detects the
    dead backend fast, and the remaining tiers are marked skipped instead
    of each eating its own child timeout."""
    script = [
        _probe_ok(),
        _Proc(stdout=_record("chip_ok") + "\n"),       # chip
        "timeout",                                     # roofline run1
        "timeout",                                     # reprobe -> dead
    ]
    run, calls = _fake_runner(script)
    monkeypatch.setattr(bench.subprocess, "run", run)
    monkeypatch.setenv("GRAPHMINE_BENCH_AUDIT", "0")
    monkeypatch.delenv("GRAPHMINE_BENCH_BUDGET", raising=False)
    rc = bench.orchestrate("all")
    assert rc == 0  # chip's real record landed
    recs = [json.loads(l) for l in capsys.readouterr().out.strip().splitlines()]
    assert recs[0]["metric"] == "chip_ok"
    assert recs[1]["metric"] == "bench_roofline_capture_failed"
    for r, t in zip(recs[2:-1], bench._TIER_ORDER[2:]):
        assert r["metric"] == f"bench_{t}_capture_failed"
        assert "unreachable mid-capture" in r["error"]
    assert len(recs) == len(bench._TIER_ORDER) + 1
    # the summary still headlines the chip number and records the skips
    summary = recs[-1]
    assert summary["metric"] == "chip_ok"
    assert "unreachable" in summary["suite"]["tiers"]["quality"]["err"]


def test_orchestrate_budget_skips_attempts(monkeypatch, capsys):
    """An exhausted budget skips TPU attempts but still reserves room for
    the fallback record."""
    run, calls = _fake_runner([
        _Proc(stdout=_record("fallback_result") + "\n"),
    ])
    monkeypatch.setattr(bench.subprocess, "run", run)
    monkeypatch.setenv("GRAPHMINE_BENCH_BUDGET", "100")  # < reserve + 60
    rc = bench.orchestrate("chip")
    assert rc == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[0])
    cap = rec["detail"]["capture"]
    assert any("budget exhausted" in f for f in cap["failures"])
    assert len(calls) == 1  # no probes, straight to fallback


def test_orchestrate_all_first_tier_total_failure_does_not_abort_suite(
    monkeypatch, capsys
):
    """Healthy backend but the chip tier is broken (both attempts + CPU
    fallback): the suite must continue — the driver-parsed first line is
    the chip error record, and every later tier still captures."""
    script = [
        _probe_ok(),
        _Proc(returncode=1),   # chip run1
        _probe_ok(),           # reprobe before retry
        _Proc(returncode=1),   # chip run2
        _Proc(returncode=1),   # chip cpu fallback
    ]
    for t in bench._TIER_ORDER[1:]:
        script.append(_Proc(stdout=_record(f"{t}_result") + "\n"))
    run, calls = _fake_runner(script)
    monkeypatch.setattr(bench.subprocess, "run", run)
    monkeypatch.setenv("GRAPHMINE_BENCH_AUDIT", "0")
    monkeypatch.delenv("GRAPHMINE_BENCH_BUDGET", raising=False)
    rc = bench.orchestrate("all")
    assert rc == 0  # later tiers produced real records
    recs = [json.loads(l) for l in capsys.readouterr().out.strip().splitlines()]
    assert recs[0]["metric"] == "bench_chip_capture_failed"
    assert "run1" in recs[0]["error"] and "cpu-fallback" in recs[0]["error"]
    assert [r["metric"] for r in recs[1:-1]] == [
        f"{t}_result" for t in bench._TIER_ORDER[1:]
    ]
    # chip produced no real number: the summary headline falls back to the
    # first real tier record instead of a 0.0 error line
    summary = recs[-1]
    assert summary["metric"] == "roofline_result"
    assert "run1" in summary["suite"]["tiers"]["chip"]["err"]


def test_orchestrate_all_clean_tiers_do_not_inherit_failures(
    monkeypatch, capsys
):
    """A retry on one tier must not annotate every later clean tier's
    capture.failures (the failure list is per-tier, probe-phase reasons
    ride only the first record)."""
    script = [
        _probe_ok(),
        _Proc(returncode=1),                          # chip run1 fails
        _probe_ok(),                                  # reprobe
        _Proc(stdout=_record("chip_ok") + "\n"),      # chip run2 succeeds
    ]
    for t in bench._TIER_ORDER[1:]:
        script.append(_Proc(stdout=_record(f"{t}_result") + "\n"))
    run, calls = _fake_runner(script)
    monkeypatch.setattr(bench.subprocess, "run", run)
    monkeypatch.setenv("GRAPHMINE_BENCH_AUDIT", "0")
    monkeypatch.delenv("GRAPHMINE_BENCH_BUDGET", raising=False)
    rc = bench.orchestrate("all")
    assert rc == 0
    recs = [json.loads(l) for l in capsys.readouterr().out.strip().splitlines()]
    assert recs[0]["metric"] == "chip_ok"
    assert recs[0]["detail"]["capture"]["failures"] == [
        "run1: measurement child rc=1"
    ]
    for r in recs[1:-1]:
        assert r["detail"]["capture"]["failures"] is None


def _run_tier_body(tier, timeout=600, **env_overrides):
    """Run one measurement tier's REAL body as a CPU-fallback child (the
    ``_GRAPHMINE_BENCH_CHILD`` path, no orchestration) and return its one
    parsed JSON record."""
    env = dict(
        os.environ,
        _GRAPHMINE_BENCH_CHILD="1",
        GRAPHMINE_BENCH_CPU_FALLBACK="1",
        **env_overrides,
    )
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--tier", tier],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=timeout,
    )
    assert p.returncode == 0, p.stderr[-2000:]
    lines = [l for l in p.stdout.splitlines() if l.strip().startswith("{")]
    assert len(lines) == 1, p.stdout
    return json.loads(lines[0])


def test_roofline_body_cpu_smoke():
    """VERDICT r3 item 4: run ``main_roofline``'s ACTUAL measurement body
    (not a mock) end-to-end on CPU at env-capped tiny scale, asserting it
    produces a well-formed record — so the tier cannot fail its first-ever
    execution inside a precious real-TPU capture window."""
    rec = _run_tier_body(
        "roofline",
        timeout=300,
        GRAPHMINE_ROOFLINE_TABLE=str(1 << 12),
        GRAPHMINE_ROOFLINE_SLOTS=str(1 << 14),
        GRAPHMINE_ROOFLINE_ITERS="2",
    )
    assert rec["metric"] == "roofline_gather_slots_per_sec_cpu_fallback"
    assert rec["value"] > 0
    # CPU rates carry no ratio against the TPU hardware model
    assert rec["vs_baseline"] == 0.0
    meas = rec["detail"]["measured"]
    for k in (
        "gather_slots_per_sec", "scatter_add_per_sec",
        "row_sort_elems_per_sec", "segment_sum_elems_per_sec",
    ):
        assert meas[k] > 0, k
    assert rec["detail"]["implied_lpa_ceiling_edges_per_sec"] > 0
    assert set(rec["detail"]["measured_vs_model"]) == set(rec["detail"]["model"])


def test_stream_tier_auroc_band_across_seeds():
    """VERDICT r3 item 6: the stream tier's injected outliers sit on a
    [4, 6] radial shell just outside the chi(8) inlier envelope, so
    ``auroc_injected`` is a real measurement — meaningfully below the old
    saturated 1.0, stable across seeds, and with room to regress in both
    directions. Runs the REAL tier body at env-capped scale."""
    vals = []
    devices = []
    for seed in ("11", "12", "13"):
        rec = _run_tier_body(
            "stream",
            GRAPHMINE_STREAM_SEED=seed,
            GRAPHMINE_STREAM_POINTS=str(1 << 14),
            GRAPHMINE_STREAM_CHUNK=str(1 << 11),
            GRAPHMINE_STREAM_WINDOW=str(1 << 11),
        )
        vals.append(rec["detail"]["auroc_injected"])
        devices.append(rec["detail"]["device"])
    # The saturation check is the point of the r3 fix: it holds on every
    # backend. The shell geometry leaves real headroom below 1.0.
    assert all(v < 0.999 for v in vals), vals
    if all("CPU" in d for d in devices):
        # measured band 0.9857-0.9901 across these seeds ON CPU; the
        # tight band is gated to where it was measured (ADVICE r4) —
        # under GRAPHMINE_TEST_TPU=1 the child runs on the accelerator,
        # whose kNN tie/rounding behavior can legitimately shift it.
        assert all(0.9 < v for v in vals), vals
        assert max(vals) - min(vals) < 0.03, vals
    else:
        # accelerator run: loose floor still catches a detection collapse
        assert all(0.8 < v for v in vals), (vals, devices)


def test_snap_tier_sharded_branch_executes():
    """VERDICT r4 item 7 / weak 4: the snap TIER's own multi-device
    composition — ``main_snap`` routing a rung through the sharded branch
    of ``_run_snap_rung`` (host build → make_mesh → replicated/ring
    LPA+CC) — executes end-to-end in the REAL child process, not just
    unit scope. 8 virtual devices make ``plan_run`` route every rung
    through the distributed schedules (D=8 never returns "single"), so
    the one bench path no capture had ever run is exercised exactly as a
    capture would run it."""
    rec = _run_tier_body(
        "snap", timeout=900,
        XLA_FLAGS="--xla_force_host_platform_device_count=8",
    )
    assert rec["metric"] == "snap_ladder_lpa_edges_per_sec_cpu_fallback"
    assert rec["value"] > 0
    measured = [r for r in rec["detail"]["rungs"] if "lpa_edges_per_sec" in r]
    assert measured, rec["detail"]["rungs"]
    for r in measured:
        # the sharded branch, not the fused single-device path
        assert r["schedule"] in ("replicated", "ring"), r
        assert r["components"] >= 1 and r["lpa_communities"] >= 1


def test_quality_margin_config_ari_band_across_seeds():
    """VERDICT r4 item 4: the quality headline comes from the
    detectability-MARGIN SBM, not the 50-100x-ratio configs any good
    method fully recovers (ARI 1.0 carried no information for four
    rounds). Runs the REAL deployed margin-20k parameters (read from
    bench.QUALITY_CONFIGS, not a copy) across seeds and pins the band:
    saturation (~1.0) or a detection collapse both fail."""
    import numpy as np

    from graphmine_tpu.datasets import sbm
    from graphmine_tpu.graph.container import build_graph
    from graphmine_tpu.ops.cluster_metrics import adjusted_rand_index
    from graphmine_tpu.ops.louvain import leiden, louvain
    from graphmine_tpu.ops.lpa import label_propagation

    name, sizes, p_in, p_out = bench.QUALITY_CONFIGS[-1]
    assert name == "sbm-margin-20k"  # the headline IS the margin config
    vals = []
    for seed in (3, 4, 5):
        src, dst, truth = sbm(sizes, p_in, p_out, seed=seed)
        g = build_graph(src, dst, num_vertices=int(truth.shape[0]))
        best = max(
            float(adjusted_rand_index(np.asarray(algo()), truth))
            for algo in (
                lambda: label_propagation(g, max_iter=5),
                lambda: louvain(g)[0],
                lambda: leiden(g)[0],
            )
        )
        vals.append(best)
    # measured band 0.81-0.94 across seeds 3/4/5/11 on the r5 CPU sweep
    # (p_in=0.026 collapses to 0.54, p_in=0.03 saturates at 0.98); the
    # assertion leaves jitter slack while failing on saturation or collapse
    assert all(0.7 < v < 0.97 for v in vals), vals
    assert max(vals) - min(vals) < 0.15, vals


def test_snap_rung_multi_device_dispatch(tmp_path, monkeypatch):
    """r3 top-rung path: a real edge-list file plus a budget one chip
    cannot satisfy routes the rung through the planner to the ring
    schedule over the visible mesh, and the record says so. An impossible
    budget yields a numeric `skipped` record, never a crash."""
    import numpy as np

    # a small real "twitter-2010" file (the path logic only checks name)
    rng = np.random.default_rng(4)
    lines = [
        f"{a} {b}" for a, b in zip(
            rng.integers(0, 200, 3000), rng.integers(0, 200, 3000)
        )
    ]
    (tmp_path / "twitter-2010.txt").write_text("\n".join(lines) + "\n")

    from graphmine_tpu.ops.bucketed_mode import (
        build_graph_and_plan,
        lpa_superstep_bucketed,
    )

    # force multi-device: tiny budget -> replicated V-terms don't fit but
    # ring's sharded ones do (8 virtual devices from conftest)
    # V~200, E=3000: ring models ~14.1 KB/device, replicated ~16.7 KB;
    # 0.9 * 17222 = 15.5 KB sits between them
    monkeypatch.setenv("GRAPHMINE_HBM_BYTES", "17222")
    rec = bench._run_snap_rung(
        "twitter-2010", str(tmp_path), None,
        build_graph_and_plan, lpa_superstep_bucketed,
    )
    assert rec["source"] == "snap" and rec["schedule"] == "ring"
    assert rec["lpa_edges_per_sec"] > 0 and rec["components"] >= 1

    # cross-schedule agreement: the default budget on the 8-device test
    # mesh selects replicated; partition counts must match ring's
    monkeypatch.delenv("GRAPHMINE_HBM_BYTES")
    rec1 = bench._run_snap_rung(
        "twitter-2010", str(tmp_path), None,
        build_graph_and_plan, lpa_superstep_bucketed,
    )
    assert rec1["schedule"] == "replicated"
    assert rec1["components"] == rec["components"]
    assert rec1["lpa_communities"] == rec["lpa_communities"]

    # reject: a budget nothing fits -> skipped record with the numbers
    monkeypatch.setenv("GRAPHMINE_HBM_BYTES", "10")
    rec2 = bench._run_snap_rung(
        "twitter-2010", str(tmp_path), None,
        build_graph_and_plan, lpa_superstep_bucketed,
    )
    assert "skipped" in rec2 and "no LPA schedule fits" in rec2["skipped"]
