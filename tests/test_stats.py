"""Graph statistics vs NetworkX oracles."""

import numpy as np
import pytest

from graphmine_tpu.graph.container import build_graph
from graphmine_tpu.ops.stats import (
    degree_assortativity,
    density,
    diameter,
    reciprocity,
)

nx = pytest.importorskip("networkx")


def random_edges(seed=0, v=50, e=240):
    rng = np.random.default_rng(seed)
    src = rng.integers(0, v, e).astype(np.int32)
    dst = rng.integers(0, v, e).astype(np.int32)
    keep = src != dst
    return src[keep], dst[keep], v


def test_assortativity_matches_networkx():
    src, dst, v = random_edges()
    g = build_graph(src, dst, num_vertices=v)
    G = nx.Graph()
    G.add_nodes_from(range(v))
    G.add_edges_from(zip(src.tolist(), dst.tolist()))
    assert degree_assortativity(g) == pytest.approx(
        nx.degree_assortativity_coefficient(G), abs=1e-9)
    # star graph: perfectly disassortative
    star = build_graph(np.zeros(5, np.int32), np.arange(1, 6, dtype=np.int32),
                       num_vertices=6)
    assert degree_assortativity(star) == pytest.approx(-1.0)


def test_reciprocity_matches_networkx():
    src, dst, v = random_edges(seed=1)
    g = build_graph(src, dst, num_vertices=v, symmetric=False)
    G = nx.DiGraph()
    G.add_nodes_from(range(v))
    G.add_edges_from(zip(src.tolist(), dst.tolist()))
    assert reciprocity(g) == pytest.approx(nx.reciprocity(G), abs=1e-12)
    one_way = build_graph(np.array([0], np.int32), np.array([1], np.int32),
                          num_vertices=2, symmetric=False)
    assert reciprocity(one_way) == 0.0
    with pytest.raises(ValueError, match="directed"):
        reciprocity(build_graph(src, dst, num_vertices=v))  # symmetric


def test_density_matches_networkx():
    src, dst, v = random_edges(seed=2)
    gu = build_graph(src, dst, num_vertices=v)
    G = nx.Graph()
    G.add_nodes_from(range(v))
    G.add_edges_from(zip(src.tolist(), dst.tolist()))
    assert density(gu) == pytest.approx(nx.density(G), abs=1e-12)
    gd = build_graph(src, dst, num_vertices=v, symmetric=False)
    GD = nx.DiGraph()
    GD.add_nodes_from(range(v))
    GD.add_edges_from(zip(src.tolist(), dst.tolist()))
    assert density(gd) == pytest.approx(nx.density(GD), abs=1e-12)
    # self-loops count toward m, as in nx
    sl = build_graph(np.array([0, 1, 1], np.int32), np.array([1, 2, 1], np.int32),
                     num_vertices=3, symmetric=False)
    SL = nx.DiGraph([(0, 1), (1, 2), (1, 1)])
    assert density(sl) == pytest.approx(nx.density(SL), abs=1e-12)


def test_diameter_exact_and_double_sweep():
    src, dst, v = random_edges(seed=3)
    g = build_graph(src, dst, num_vertices=v)
    G = nx.Graph()
    G.add_nodes_from(range(v))
    G.add_edges_from(zip(src.tolist(), dst.tolist()))
    comps = [G.subgraph(c) for c in nx.connected_components(G)]
    oracle = max(nx.diameter(c) for c in comps if len(c) > 1)
    assert diameter(g, exact=True) == oracle
    lb = diameter(g)  # double-sweep lower bound
    assert 0 < lb <= oracle + 0  # a valid lower bound
    # exact on a path graph even for the sweep
    path = build_graph(np.arange(9, dtype=np.int32),
                       np.arange(1, 10, dtype=np.int32), num_vertices=10)
    assert diameter(path) == 9 and diameter(path, exact=True) == 9
    # isolated vertices must not swallow the sweep's starting point
    padded = build_graph(np.arange(9, dtype=np.int32),
                         np.arange(1, 10, dtype=np.int32), num_vertices=60)
    for s in range(5):
        assert diameter(padded, seed=s) == 9
