"""Motif finding tests — brute-force oracle on small graphs."""

import itertools

import numpy as np
import pytest

from graphmine_tpu.graph.container import build_graph
from graphmine_tpu.ops.motifs import find, parse_pattern


def _graph(edges, v=None):
    src = np.array([e[0] for e in edges], np.int32)
    dst = np.array([e[1] for e in edges], np.int32)
    return build_graph(src, dst, num_vertices=v), list(edges)


def _brute_chain2(edges):
    """All (a,b,c) with a->b and b->c (relational: repeats allowed)."""
    out = []
    for (a, b1) in edges:
        for (b2, c) in edges:
            if b1 == b2:
                out.append((a, b1, c))
    return sorted(out)


def test_single_edge_pattern_is_edge_table():
    g, edges = _graph([(0, 1), (1, 2), (1, 2), (2, 0)])
    r = find(g, "(a)-[e]->(b)")
    assert r.num_matches == 4  # duplicates kept, like GraphFrames joins
    got = sorted(zip(r.vertices["a"], r.vertices["b"]))
    assert got == sorted(edges)
    assert set(r.edges["e"]) == {0, 1, 2, 3}


def test_two_hop_chain_vs_brute_force():
    g, edges = _graph([(0, 1), (1, 2), (1, 3), (3, 0), (2, 2)])
    r = find(g, "(a)-[]->(b); (b)-[]->(c)")
    got = sorted(zip(r.vertices["a"], r.vertices["b"], r.vertices["c"]))
    assert got == _brute_chain2(edges)


def test_directed_triangle_count():
    g, _ = _graph([(0, 1), (1, 2), (2, 0), (0, 2), (3, 0)])
    r = find(g, "(a)-[]->(b); (b)-[]->(c); (c)-[]->(a)")
    # directed 3-cycles: (0,1,2) rotated 3 ways; (0,2,0)? no—needs 3 edges:
    # 0->2,2->0,0->0 missing. So exactly the rotations of 0->1->2->0.
    got = sorted(zip(r.vertices["a"], r.vertices["b"], r.vertices["c"]))
    assert got == [(0, 1, 2), (1, 2, 0), (2, 0, 1)]


def test_negation_one_directional_edges():
    g, _ = _graph([(0, 1), (1, 0), (1, 2), (2, 3), (3, 2)])
    r = find(g, "(a)-[]->(b); !(b)-[]->(a)")
    got = sorted(zip(r.vertices["a"], r.vertices["b"]))
    assert got == [(1, 2)]


def test_anonymous_vertex_one_row_per_edge():
    g, _ = _graph([(0, 1), (0, 2), (1, 2)])
    r = find(g, "(a)-[]->()")
    assert sorted(r.vertices["a"]) == [0, 0, 1]
    assert list(r.vertices) == ["a"]


def test_self_loop_binding():
    g, _ = _graph([(0, 0), (0, 1), (1, 1)])
    r = find(g, "(a)-[]->(a)")
    assert sorted(r.vertices["a"]) == [0, 1]


def test_unbound_cross_join_terms():
    # two independent edges: second term not connected to the first
    g, edges = _graph([(0, 1), (2, 3)])
    r = find(g, "(a)-[]->(b); (c)-[]->(d)")
    assert r.num_matches == 4  # 2 x 2 cross product
    rows = set(zip(r.vertices["a"], r.vertices["b"], r.vertices["c"], r.vertices["d"]))
    assert rows == {
        (a, b, c, d) for (a, b), (c, d) in itertools.product(edges, edges)
    }


def test_vertex_appearing_in_middle():
    # bind by dst: (a)-[]->(b) then (c)-[]->(a)
    g, _ = _graph([(0, 1), (2, 0), (3, 0)])
    r = find(g, "(a)-[]->(b); (c)-[]->(a)")
    got = sorted(zip(r.vertices["a"], r.vertices["b"], r.vertices["c"]))
    assert got == [(0, 1, 2), (0, 1, 3)]


def test_parse_errors():
    with pytest.raises(ValueError):
        parse_pattern("(a)->(b)")
    with pytest.raises(ValueError):
        parse_pattern("!(a)-[e]->(b)")  # named edge in negation
    with pytest.raises(ValueError):
        parse_pattern("!(a)-[]->(b)")  # vertices never positively bound
    with pytest.raises(ValueError):
        parse_pattern("(a)-[a]->(b)")  # name reused across classes
    with pytest.raises(ValueError):
        parse_pattern("(a)-[e]->(b); (b)-[e]->(c)")  # duplicate edge name
    with pytest.raises(ValueError):
        parse_pattern("")


def test_no_matches():
    g, _ = _graph([(0, 1)])
    assert find(g, "(a)-[]->(b); (b)-[]->(c)").num_matches == 0


def test_all_negated_pattern():
    # "no edge exists at all": one (empty) match on an edgeless graph,
    # zero on a graph with edges
    empty = build_graph(np.array([], np.int32), np.array([], np.int32), num_vertices=3)
    assert find(empty, "!()-[]->()").num_matches == 1
    g, _ = _graph([(0, 1)])
    assert find(g, "!()-[]->()").num_matches == 0


@pytest.mark.parametrize("seed", [0, 1])
def test_random_two_hop_vs_brute(seed):
    rng = np.random.default_rng(seed)
    e = 40
    edges = list(zip(rng.integers(0, 12, e).tolist(), rng.integers(0, 12, e).tolist()))
    g, _ = _graph(edges)
    r = find(g, "(x)-[]->(y); (y)-[]->(z)")
    got = sorted(zip(r.vertices["x"], r.vertices["y"], r.vertices["z"]))
    assert got == _brute_chain2(edges)
