"""Streaming LOF tests: sklearn novelty-mode oracle + sliding-window behavior."""

import numpy as np
import pytest

from graphmine_tpu.ops.knn import cross_knn
from graphmine_tpu.ops.streaming_lof import StreamingLOF, fit_lof, score_lof


def test_cross_knn_matches_brute(rng):
    q = rng.normal(size=(37, 4)).astype(np.float32)
    r = rng.normal(size=(53, 4)).astype(np.float32)
    d2, idx = cross_knn(q, r, k=5, row_tile=16)
    full = ((q[:, None, :] - r[None, :, :]) ** 2).sum(-1)
    want_idx = np.argsort(full, axis=1, kind="stable")[:, :5]
    np.testing.assert_allclose(
        np.sort(np.asarray(d2), axis=1),
        np.sort(np.take_along_axis(full, want_idx, 1), axis=1),
        rtol=2e-4, atol=2e-4,
    )


def test_cross_knn_mask_excludes_slots(rng):
    q = rng.normal(size=(8, 3)).astype(np.float32)
    r = np.concatenate([q, rng.normal(size=(20, 3)).astype(np.float32)])
    mask = np.ones(28, bool)
    mask[:8] = False  # the exact copies are masked out
    _, idx = cross_knn(q, r, k=4, ref_mask=mask)
    assert (np.asarray(idx) >= 8).all()


def test_score_matches_sklearn_novelty(rng):
    from sklearn.neighbors import LocalOutlierFactor

    refs = rng.normal(size=(300, 5)).astype(np.float32)
    queries = np.concatenate(
        [rng.normal(size=(40, 5)), rng.normal(loc=6.0, size=(10, 5))]
    ).astype(np.float32)
    k = 15
    model = fit_lof(refs, k=k)
    got = np.asarray(score_lof(model, queries))
    oracle = LocalOutlierFactor(n_neighbors=k, novelty=True).fit(refs)
    want = -oracle.score_samples(queries)
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)


def test_fit_with_padding_matches_unpadded(rng):
    pts = rng.normal(size=(100, 4)).astype(np.float32)
    padded = np.zeros((160, 4), np.float32)
    padded[:100] = pts
    mask = np.zeros(160, bool)
    mask[:100] = True
    m1 = fit_lof(pts, k=10)
    m2 = fit_lof(padded, mask, k=10)
    np.testing.assert_allclose(np.asarray(m2.kdist[:100]), np.asarray(m1.kdist), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(m2.lrd[:100]), np.asarray(m1.lrd), rtol=1e-4)
    q = rng.normal(size=(20, 4)).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(score_lof(m2, q)), np.asarray(score_lof(m1, q)), rtol=1e-4
    )


def test_streaming_flags_outliers(rng):
    # admit_threshold keeps flagged outliers out of the window, so a
    # persistent outlier cluster cannot launder itself into "normal"
    s = StreamingLOF(k=10, capacity=512, admit_threshold=2.0)
    aurocs = []
    for step in range(6):
        inliers = rng.normal(size=(120, 3)).astype(np.float32)
        outliers = rng.normal(loc=7.0, size=(8, 3)).astype(np.float32)
        chunk = np.concatenate([inliers, outliers])
        scores = s.update(chunk)
        assert scores.shape == (128,)
        if step == 0:
            continue  # bootstrap chunk scored in-window
        from graphmine_tpu.ops.lof import auroc

        y = np.zeros(128, bool)
        y[120:] = True
        aurocs.append(auroc(scores, y))
    assert min(aurocs) > 0.95


def test_persistent_cluster_absorbed_without_threshold(rng):
    # documents the flip side: with no admit threshold, a recurring outlier
    # cluster eventually joins the window and scores as normal
    s = StreamingLOF(k=10, capacity=512)
    for _ in range(4):
        chunk = np.concatenate(
            [rng.normal(size=(120, 3)), rng.normal(loc=7.0, size=(8, 3))]
        ).astype(np.float32)
        scores = s.update(chunk)
    assert scores[120:].mean() < 1.5  # absorbed


def test_window_eviction_adapts(rng):
    # distribution shift: after the window slides, the new regime is inlier
    s = StreamingLOF(k=8, capacity=256)
    a = rng.normal(loc=0.0, size=(256, 2)).astype(np.float32)
    s.update(a)
    b = rng.normal(loc=10.0, size=(256, 2)).astype(np.float32)
    high = s.update(b).mean()  # shifted chunk looks outlying vs regime A
    c = rng.normal(loc=10.0, size=(256, 2)).astype(np.float32)
    low = s.update(c).mean()  # window is now full of regime B
    assert high > 5 * low


def test_ivf_refit_reuses_one_trained_index():
    """r6 index reuse: impl="ivf" trains k-means ONCE (the first window
    big enough for the index), re-fits every later window against the
    reused centers, and scores must track the exact-impl stream tightly;
    ivf_retrain_every=N re-trains on the drift cadence."""
    rng = np.random.default_rng(11)
    n, f, chunk, cap, k = 1 << 14, 8, 1 << 10, 1 << 11, 16
    centers = rng.normal(size=(8, f)).astype(np.float32) * 4
    pts = (
        centers[rng.integers(0, 8, n)]
        + rng.normal(size=(n, f)).astype(np.float32)
    )

    def run(**kw):
        s = StreamingLOF(k=k, capacity=cap, **kw)
        out = np.empty(n, np.float32)
        for lo in range(0, n, chunk):
            out[lo:lo + chunk] = s.update(pts[lo:lo + chunk])
        s.sync()
        return s, out

    s_exact, sc_exact = run()
    s_ivf, sc_ivf = run(impl="ivf")
    assert s_ivf.ivf_retrains == 1  # trained once, reused ever after
    assert s_ivf._ivf_fits >= 10
    warm = slice(cap, None)
    frac_close = np.mean(
        np.abs(sc_ivf[warm] - sc_exact[warm])
        < 0.05 * np.abs(sc_exact[warm]) + 0.01
    )
    assert frac_close > 0.95, frac_close

    s_rt, _ = run(impl="ivf", ivf_retrain_every=4)
    assert s_rt.ivf_retrains > 1

    with pytest.raises(ValueError, match="impl"):
        StreamingLOF(k=4, capacity=64, impl="annoy")
    with pytest.raises(ValueError, match="ivf_retrain_every"):
        StreamingLOF(k=4, capacity=64, impl="ivf", ivf_retrain_every=-1)


def test_ivf_small_windows_warm_up_exact(rng):
    """Windows that have not FILLED yet take the exact fit — the stream
    warms up exact (bit-for-bit the same fit as impl='exact') and the
    index trains only on a full window, never on a small early sample
    that would index every later window badly."""
    pts = rng.normal(size=(90, 4)).astype(np.float32)
    s_e = StreamingLOF(k=8, capacity=512)
    s_i = StreamingLOF(k=8, capacity=512, impl="ivf")
    np.testing.assert_array_equal(s_e.update(pts), s_i.update(pts))
    assert s_i.ivf_retrains == 0  # window not full: no training yet
    q = rng.normal(size=(4, 4)).astype(np.float32)
    np.testing.assert_array_equal(s_e.update(q), s_i.update(q))
    assert s_i.ivf_retrains == 0  # 94/512 valid: still warming up exact


def test_first_chunk_too_small():
    s = StreamingLOF(k=10, capacity=128)
    with pytest.raises(ValueError):
        s.update(np.zeros((5, 2), np.float32))
    with pytest.raises(ValueError):
        StreamingLOF(k=10, capacity=10)


def test_failed_bootstrap_is_retryable(rng):
    # a rejected bootstrap (threshold filters too much) must not corrupt
    # state: the next update re-bootstraps cleanly
    s = StreamingLOF(k=5, capacity=64, admit_threshold=1e-6)
    bad = rng.normal(size=(10, 2)).astype(np.float32)
    with pytest.raises(ValueError):
        s.update(bad)
    assert not s.fitted
    s.admit_threshold = 10.0
    scores = s.update(rng.normal(size=(20, 2)).astype(np.float32))
    assert s.fitted and scores.shape == (20,)


def test_update_with_empty_chunk():
    import numpy as np
    from graphmine_tpu.ops.streaming_lof import StreamingLOF

    rng = np.random.default_rng(0)
    s = StreamingLOF(k=3, capacity=32)
    s.update(rng.normal(size=(16, 4)).astype(np.float32))
    out = s.update(np.zeros((0, 4), np.float32))
    assert out.shape == (0,)
