"""Ring-sharded kNN/LOF parity with the single-device path (r2).

Same multi-chip-without-a-cluster strategy as the rest of the parallel
suite: the real shard_map/ppermute code runs on the 8-device virtual CPU
mesh and must reproduce the single-device ops exactly.
"""

import numpy as np
import pytest

from graphmine_tpu.ops.knn import knn
from graphmine_tpu.ops.lof import lof_scores


@pytest.fixture(scope="module")
def mesh8():
    import jax

    from graphmine_tpu.parallel.mesh import make_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return make_mesh(8)


def test_sharded_knn_matches_single_device(mesh8):
    from graphmine_tpu.parallel.knn import sharded_knn

    # Own rng (session-fixture state is order-dependent). Index parity is
    # asserted except where the two paths saw a near-tie: the full-row and
    # per-chunk matmuls round d2 differently in the last ulp, which can
    # swap neighbors whose true distances are (nearly) equal — at any such
    # disagreement the distances themselves must match, proving the swap
    # is a legitimate tie, not a wrong neighborhood.
    for n, f, k, seed in ((400, 8, 5, 0), (1000, 16, 32, 1), (257, 4, 3, 2)):
        r = np.random.default_rng(seed)
        pts = r.normal(size=(n, f)).astype(np.float32)
        want_d, want_i = knn(pts, k=k, impl="xla")
        got_d, got_i = sharded_knn(pts, mesh8, k=k, row_tile=64)
        got_d, got_i = np.asarray(got_d), np.asarray(got_i)
        want_d, want_i = np.asarray(want_d), np.asarray(want_i)
        np.testing.assert_allclose(got_d, want_d, rtol=1e-5, atol=1e-6)
        diff = got_i != want_i
        assert diff.mean() < 0.01  # near-tie swaps are rare
        np.testing.assert_allclose(
            got_d[diff], want_d[diff], rtol=1e-5, atol=1e-6
        )


def test_sharded_knn_handles_duplicates_and_ragged_n(mesh8):
    # duplicate points (zero distances, self still excluded by id) and an
    # N that doesn't divide the mesh (padding rows must never be neighbors)
    from graphmine_tpu.parallel.knn import sharded_knn

    r = np.random.default_rng(0)  # own rng: session-fixture state varies
    base = r.normal(size=(61, 6)).astype(np.float32)
    pts = np.concatenate([base, base[:10]])  # 71 rows, 10 exact duplicates
    want_d, want_i = knn(pts, k=4, impl="xla")
    got_d, got_i = sharded_knn(pts, mesh8, k=4, row_tile=16)
    # atol 1e-5: a duplicate pair's true distance is 0, and the
    # |q|^2 - 2 q.r + |r|^2 expansion leaves an O(|x|^2 eps) cancellation
    # residue that differs between the full-row and per-chunk matmuls.
    np.testing.assert_allclose(
        np.asarray(got_d), np.asarray(want_d), rtol=1e-5, atol=1e-5
    )
    # duplicate-point ties can legitimately order differently across the
    # merge tree; distances pin the neighborhoods, ids must be valid
    got_i = np.asarray(got_i)
    assert got_i.min() >= 0 and got_i.max() < len(pts)
    assert (got_i != np.arange(len(pts))[:, None]).all()  # self excluded


def test_sharded_lof_matches_single_device(mesh8):
    from graphmine_tpu.parallel.knn import sharded_lof

    pts = np.random.default_rng(7).normal(size=(600, 8)).astype(np.float32)
    pts[0] = 40.0  # one blatant outlier
    want = np.asarray(lof_scores(pts, k=16, impl="xla"))
    got = np.asarray(sharded_lof(pts, mesh8, k=16, row_tile=64))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    assert got[0] == got.max() and got[0] > 2.0


def test_sharded_ivf_lof_matches_fused(mesh8):
    """r6: sharded_lof(impl="ivf") distributes the IVF search stage over
    the mesh; the chunk partition must not change a single candidate, so
    scores are BIT-identical to the fused single-device IVF scorer (the
    same index, the same merges — only the lax.map rows moved devices)."""
    from graphmine_tpu.parallel.knn import sharded_lof

    r = np.random.default_rng(5)
    c = r.normal(size=(8, 8)).astype(np.float32) * 3
    pts = (
        c[r.integers(0, 8, 6000)]
        + r.normal(size=(6000, 8)).astype(np.float32)
    )
    fused = np.asarray(lof_scores(pts, k=16, impl="ivf"))
    got = np.asarray(sharded_lof(pts, mesh8, k=16, impl="ivf"))
    np.testing.assert_array_equal(got, fused)


def test_sharded_lof_auto_policy_and_record(mesh8, monkeypatch):
    """impl="auto" on the sharded scorer applies the same measured
    crossover as lof_scores and emits the impl_selected record; unknown
    impl strings are rejected, not silently coerced to exact."""
    from graphmine_tpu.parallel.knn import sharded_lof
    from graphmine_tpu.pipeline.metrics import MetricsSink

    r = np.random.default_rng(6)
    pts = r.normal(size=(600, 8)).astype(np.float32)
    m = MetricsSink()
    got = np.asarray(sharded_lof(pts, mesh8, k=16, sink=m))
    rec = m.of_phase("impl_selected")
    assert rec and rec[0]["impl"] == "exact" and rec[0]["devices"] == 8
    want = np.asarray(lof_scores(pts, k=16, impl="xla"))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    with pytest.raises(ValueError, match="unknown sharded LOF impl"):
        sharded_lof(pts, mesh8, k=16, impl="IVF")

    m2 = MetricsSink()
    monkeypatch.setenv("GRAPHMINE_LOF_IVF_MIN_N", "100")
    c = r.normal(size=(8, 8)).astype(np.float32) * 3
    blob = (
        c[r.integers(0, 8, 4000)]
        + r.normal(size=(4000, 8)).astype(np.float32)
    )
    got2 = np.asarray(sharded_lof(blob, mesh8, k=16, sink=m2))
    rec2 = m2.of_phase("impl_selected")
    assert rec2 and rec2[0]["impl"] == "ivf"
    np.testing.assert_array_equal(
        got2, np.asarray(lof_scores(blob, k=16, impl="ivf"))
    )


def test_sharded_knn_validates_k(mesh8):
    from graphmine_tpu.parallel.knn import sharded_knn

    r = np.random.default_rng(3)
    pts = r.normal(size=(32, 4)).astype(np.float32)
    with pytest.raises(ValueError, match="chunk"):
        sharded_knn(pts, mesh8, k=5)  # chunk = 4 < k
    with pytest.raises(ValueError, match="must be <"):
        sharded_knn(r.normal(size=(8, 2)).astype(np.float32), mesh8, k=8)


def test_shard_map_cache_bounded_lru():
    """ADVICE r2: the compiled-program cache must not grow without bound —
    sweep workloads visit many distinct shapes and each entry pins an
    executable. LRU: recently-used keys survive, the oldest are evicted."""
    from graphmine_tpu.parallel import mesh as mesh_mod

    saved = dict(mesh_mod._SHARD_MAP_CACHE)
    mesh_mod._SHARD_MAP_CACHE.clear()
    try:
        cap = mesh_mod._SHARD_MAP_CACHE_MAX
        for i in range(cap + 10):
            mesh_mod.cached_jit_shard_map(("t", i), lambda: (lambda x: x))
            mesh_mod.cached_jit_shard_map(("t", 0), lambda: (lambda x: x))  # keep hot
        assert len(mesh_mod._SHARD_MAP_CACHE) == cap
        assert ("t", 0) in mesh_mod._SHARD_MAP_CACHE          # LRU-protected
        assert ("t", 1) not in mesh_mod._SHARD_MAP_CACHE      # evicted
        assert ("t", cap + 9) in mesh_mod._SHARD_MAP_CACHE    # newest kept
        # a hit must not rebuild: identity is stable
        f1 = mesh_mod.cached_jit_shard_map(("t", 0), lambda: (lambda x: x))
        f2 = mesh_mod.cached_jit_shard_map(("t", 0), lambda: (lambda x: x))
        assert f1 is f2
    finally:
        mesh_mod._SHARD_MAP_CACHE.clear()
        mesh_mod._SHARD_MAP_CACHE.update(saved)
