"""Relational-layer tests: the Spark DataFrame op contract (SURVEY §2.2).

Covers the exact op sequence of the reference's preprocessing phase
(``Graphframes.py:16-32, 53, 70-74, 85-110``) including its literal SQL
filter string, plus the dead data-slicer ops (``:34-47``).
"""

import os

import numpy as np
import pytest

from graphmine_tpu.table import Table

from conftest import REFERENCE_PARQUET


def small():
    return Table(
        {
            "Parent": np.array(["u1", "u2", "u3", "u4"], dtype=object),
            "ParentDomain": np.array(["a.com", "a.com", None, "b.com"], dtype=object),
            "ChildDomain": np.array(["b.com", "c.com", "b.com", None], dtype=object),
            "n": np.array([1, 2, 3, 4]),
        }
    )


def test_rename_and_null_filter():
    t = small().with_column_renamed("Parent", "ParentURL")
    assert t.columns == ["ParentURL", "ParentDomain", "ChildDomain", "n"]
    # the reference's literal filter string, Graphframes.py:30
    f = t.filter("ParentDomain is not null and ChildDomain is not null")
    assert f.count() == 2
    assert list(f["n"]) == [1, 2]
    # rename of a missing column is a silent no-op (Spark semantics)
    assert t.with_column_renamed("nope", "x").columns == t.columns


def test_sql_predicates():
    t = small()
    assert t.filter("n > 2").count() == 2
    assert t.filter("n >= 2 and n < 4").count() == 2
    assert t.filter("ParentDomain = 'a.com'").count() == 2
    assert t.filter("ParentDomain != 'a.com'").count() == 1  # null rows drop
    assert t.filter("ParentDomain is null or ChildDomain is null").count() == 2
    assert t.filter("not (n = 1)").count() == 3
    assert t.filter("ParentDomain in ('a.com', 'z.com')").count() == 2
    assert t.filter("ParentDomain like 'a%'").count() == 2
    assert t.filter("ChildDomain like '_.com'").count() == 3
    with pytest.raises((ValueError, KeyError)):
        t.filter("Bogus = 1")


def test_select_withcolumn_distinct_collect():
    t = small()
    s = t.select("ParentDomain", "ChildDomain")
    assert s.columns == ["ParentDomain", "ChildDomain"]
    w = t.with_column("n2", lambda tb: tb["n"] * 10)
    assert list(w["n2"]) == [10, 20, 30, 40]
    d = Table({"x": np.array([1, 1, 2, 2, 3])}).distinct()
    assert list(d["x"]) == [1, 2, 3]
    rows = t.select("n").collect()
    assert [r.n for r in rows] == [1, 2, 3, 4]
    # persist is the eager-engine identity (Graphframes.py:82)
    assert t.persist() is t


def test_distinct_with_nulls_and_multicol():
    t = Table(
        {
            "a": np.array(["x", "x", None, None], dtype=object),
            "b": np.array([1, 1, 2, 2]),
        }
    )
    assert t.distinct().count() == 2
    assert t.drop_duplicates(["b"]).count() == 2


def test_slicer_ops_row_ids_sort_limit_subtract():
    # the dead data-slicer pattern, Graphframes.py:34-47
    t = Table({"v": np.array([30, 10, 20, 40])}).with_row_ids("id")
    assert list(t["id"]) == [0, 1, 2, 3]
    first2 = t.sort("v").limit(2)
    assert list(first2["v"]) == [10, 20]
    rest = t.subtract(first2)
    assert sorted(rest["v"]) == [30, 40]
    assert t.union(t).count() == 8


def test_show_renders(capsys):
    out = small().show(2, truncate=8)
    assert "ParentDomain" in out and "only showing top 2 rows" in out
    assert "null" not in out.split("\n")[3]  # first two rows have no nulls


def test_flat_map_distinct_vertex_idiom():
    # Graphframes.py:53 — union of the two domain columns, nulls dropped
    t = small()
    verts = t.flat_map_distinct("ParentDomain", "ChildDomain")
    assert list(verts) == ["a.com", "b.com", "c.com"]


def test_to_edge_table_bridge():
    t = small().filter("ParentDomain is not null and ChildDomain is not null")
    et = t.to_edge_table("ParentDomain", "ChildDomain")
    assert et.num_edges == 2 and et.num_vertices == 3
    assert et.names[et.src[0]] == "a.com" and et.names[et.dst[0]] == "b.com"


@pytest.mark.skipif(
    not os.path.exists(REFERENCE_PARQUET), reason="bundled parquet not available"
)
def test_reference_preprocessing_phase_end_to_end():
    """The reference's whole phase 1 (Graphframes.py:16-30) through Table."""
    df = Table.read_parquet(REFERENCE_PARQUET)
    assert df.count() == 18399  # Graphframes.py:18
    df = (
        df.with_column_renamed("_c0", "Parent")
        .with_column_renamed("_c1", "ParentDomain")
        .with_column_renamed("_c2", "ChildDomain")
        .with_column_renamed("_c3", "Child")
        .filter("ParentDomain is not null and ChildDomain is not null")
    )
    assert df.count() == 18398  # one null row dropped
    assert len(df.flat_map_distinct("ParentDomain", "ChildDomain")) == 4613
    et = df.to_edge_table("ParentDomain", "ChildDomain")
    assert et.num_edges == 18398 and et.num_vertices == 4613
    assert len(et.distinct_edges()) == 7742


def test_sort_with_nulls():
    t = Table({"s": np.array(["b", None, "a"], dtype=object), "n": np.array([1, 2, 3])})
    asc = t.sort("s")
    assert list(asc["n"]) == [2, 3, 1]  # nulls first ascending
    desc = t.sort("s", ascending=False)
    assert list(desc["n"]) == [1, 3, 2]  # nulls last descending


def test_review_fixes_rename_collision_rowkeys_3vl():
    # rename onto an existing name must not silently drop data
    t = Table({"a": np.array([1, 2]), "b": np.array([3, 4])})
    with pytest.raises(ValueError):
        t.with_column_renamed("a", "b")
    # delimiter bytes inside values must not collide row keys
    t2 = Table({"x": np.array(["x\x1fy", "x"], dtype=object),
                "y": np.array(["z", "y\x1fz"], dtype=object)})
    assert t2.distinct().count() == 2
    assert t2.subtract(Table({"x": np.array(["x"], dtype=object),
                              "y": np.array(["y\x1fz"], dtype=object)})).count() == 1
    # SQL three-valued logic: NOT(null = x) is unknown -> row drops
    t3 = small()
    assert t3.filter("not (ParentDomain = 'a.com')").count() == 1
    assert t3.filter("not (ParentDomain like 'a%')").count() == 1
    assert t3.filter("not (ParentDomain in ('a.com'))").count() == 1


def test_like_on_null_is_unknown():
    t = Table({"x": np.array([1.0, np.nan])})
    assert t.filter("x like 'nan'").count() == 0
    t2 = Table({"s": np.array(["abc", None], dtype=object)})
    assert t2.filter("s like 'a%'").count() == 1
    assert t2.filter("not (s like 'a%')").count() == 0
