"""Spectral embedding: scipy-eigsh subspace oracle + planted-block
recovery (eigenvectors are sign/rotation-ambiguous, so agreement is
measured with principal angles, not per-column equality)."""

import numpy as np
import pytest

from graphmine_tpu.datasets import sbm
from graphmine_tpu.graph.container import build_graph
from graphmine_tpu.ops.cluster_metrics import adjusted_rand_index
from graphmine_tpu.ops.embedding import spectral_embedding


def sbm_graph(blocks=4, size=150, seed=1):
    src, dst, planted = sbm([size] * blocks, p_in=0.08, p_out=0.003, seed=seed)
    return build_graph(src, dst, num_vertices=len(planted)), src, dst, planted


def test_orthonormal_and_deterministic():
    g, *_ = sbm_graph()
    x = np.asarray(spectral_embedding(g, dim=3))
    np.testing.assert_allclose(x.T @ x, np.eye(3), atol=1e-5)
    y = np.asarray(spectral_embedding(g, dim=3))
    np.testing.assert_array_equal(x, y)


def test_subspace_matches_scipy_eigsh():
    spla = pytest.importorskip("scipy.sparse.linalg")
    sp = pytest.importorskip("scipy.sparse")

    g, src, dst, planted = sbm_graph(blocks=4, seed=2)
    v = len(planted)
    dim = 3  # 4 blocks -> 3 structural nontrivial eigenvectors
    x = np.asarray(spectral_embedding(g, dim=dim, num_iters=120))

    a = sp.coo_matrix(
        (np.ones(2 * len(src)), (np.r_[src, dst], np.r_[dst, src])),
        shape=(v, v),
    ).tocsr()
    deg = np.asarray(a.sum(1)).ravel()
    dm = sp.diags(1.0 / np.sqrt(np.maximum(deg, 1)))
    m = dm @ a @ dm
    w, vecs = spla.eigsh(m, k=dim + 1, which="LA")
    oracle = vecs[:, np.argsort(-w)][:, 1:]  # drop the trivial direction
    cosines = np.linalg.svd(x.T @ oracle, compute_uv=False)
    assert cosines.min() > 0.99


def test_embedding_recovers_planted_blocks():
    g, *_, planted = sbm_graph(blocks=3, seed=3)
    x = np.asarray(spectral_embedding(g, dim=2))

    def kmeans(pts, k, iters=40, seed=0):
        rng = np.random.default_rng(seed)
        centers = pts[rng.choice(len(pts), k, replace=False)]
        assign = np.zeros(len(pts), np.int64)
        for _ in range(iters):
            d = ((pts[:, None, :] - centers[None]) ** 2).sum(-1)
            assign = d.argmin(1)
            for j in range(k):
                if (assign == j).any():
                    centers[j] = pts[assign == j].mean(0)
        inertia = ((pts - centers[assign]) ** 2).sum()
        return assign, inertia

    # best of 5 inits (vanilla k-means is init-sensitive; the embedding
    # itself is what's under test)
    assign, _ = min((kmeans(x, 3, seed=s) for s in range(5)),
                    key=lambda r: r[1])
    assert adjusted_rand_index(assign, planted) > 0.95


def test_bipartite_negative_eigenvalues_do_not_dominate():
    # Two K_{8,8} blocks joined by one edge: the spectrum mirrors (+1/-1
    # pairs). Without the (M+I)/2 shift, subspace iteration converges to
    # largest-|λ| mixtures; the embedding must track the algebraically
    # largest (which='LA') subspace instead.
    spla = pytest.importorskip("scipy.sparse.linalg")
    sp = pytest.importorskip("scipy.sparse")

    edges = ([(a, b) for a in range(8) for b in range(8, 16)]
             + [(16 + a, 24 + b) for a in range(8) for b in range(8)]
             + [(0, 16)])
    src = np.array([e[0] for e in edges], np.int32)
    dst = np.array([e[1] for e in edges], np.int32)
    v = 32
    g = build_graph(src, dst, num_vertices=v)
    x = np.asarray(spectral_embedding(g, dim=2, num_iters=200))

    a = sp.coo_matrix((np.ones(2 * len(src)),
                       (np.r_[src, dst], np.r_[dst, src])), shape=(v, v)).tocsr()
    deg = np.asarray(a.sum(1)).ravel()
    dm = sp.diags(1.0 / np.sqrt(np.maximum(deg, 1)))
    w, vecs = spla.eigsh(dm @ a @ dm, k=3, which="LA")
    oracle = vecs[:, np.argsort(-w)][:, 1:]
    cosines = np.linalg.svd(x.T @ oracle, compute_uv=False)
    assert cosines.min() > 0.99


def test_isolated_vertices_embed_at_origin_and_validation():
    g = build_graph(np.array([0, 1], np.int32), np.array([1, 2], np.int32),
                    num_vertices=5)
    x = np.asarray(spectral_embedding(g, dim=2, num_iters=30))
    assert np.abs(x[3:]).max() < 1e-5
    gd = build_graph(np.array([0], np.int32), np.array([1], np.int32),
                     num_vertices=2, symmetric=False)
    with pytest.raises(ValueError, match="symmetric"):
        spectral_embedding(gd)
