"""SBM generator, ARI/NMI metrics (sklearn oracle), weighted shortest
paths (NetworkX oracle), and community-recovery accuracy — the evaluation
axis the reference names (Overview:9) but never measures."""

import numpy as np
import pytest

from graphmine_tpu.datasets import sbm
from graphmine_tpu.graph.container import build_graph
from graphmine_tpu.ops.cluster_metrics import (
    adjusted_rand_index,
    normalized_mutual_info,
)


def test_ari_nmi_match_sklearn_oracle():
    sk = pytest.importorskip("sklearn.metrics")
    rng = np.random.default_rng(0)
    for trial in range(6):
        a = rng.integers(0, rng.integers(2, 9), 300)
        b = rng.integers(0, rng.integers(2, 9), 300)
        assert adjusted_rand_index(a, b) == pytest.approx(
            sk.adjusted_rand_score(a, b), abs=1e-10)
        assert normalized_mutual_info(a, b) == pytest.approx(
            sk.normalized_mutual_info_score(a, b), abs=1e-10)
    # permutation invariance + perfect/degenerate cases
    a = rng.integers(0, 5, 200)
    perm = rng.permutation(5)
    assert adjusted_rand_index(a, perm[a]) == 1.0
    assert normalized_mutual_info(a, perm[a]) == pytest.approx(1.0)
    assert adjusted_rand_index(np.zeros(10), np.zeros(10)) == 1.0
    assert normalized_mutual_info(np.zeros(10), np.arange(10)) == pytest.approx(
        sk.normalized_mutual_info_score(np.zeros(10), np.arange(10)))


def test_sbm_shape_and_structure():
    src, dst, blocks = sbm([100, 100, 100], p_in=0.2, p_out=0.005, seed=3)
    assert blocks.shape == (300,) and set(blocks) == {0, 1, 2}
    assert (src != dst).all()  # no self-loops
    intra = (blocks[src] == blocks[dst]).mean()
    assert intra > 0.8  # planted structure dominates
    # deduplicated directed pairs
    assert len(np.unique(src.astype(np.int64) * 300 + dst)) == len(src)


def test_lpa_and_louvain_recover_planted_blocks():
    from graphmine_tpu.ops.louvain import louvain
    from graphmine_tpu.ops.lpa import label_propagation

    src, dst, blocks = sbm([150, 150, 150], p_in=0.15, p_out=0.002, seed=5)
    g = build_graph(src, dst, num_vertices=len(blocks))
    lpa = np.asarray(label_propagation(g, max_iter=10))
    assert adjusted_rand_index(lpa, blocks) > 0.85
    lv, q = louvain(g)
    lv = np.asarray(lv)
    assert adjusted_rand_index(lv, blocks) > 0.85
    assert normalized_mutual_info(lv, blocks) > 0.85
    assert q > 0.5  # strong community structure


def test_sbm_equal_probabilities_mean_no_structure():
    # p_in == p_out must give a structureless Erdos-Renyi graph: intra and
    # inter unordered-pair densities agree (regression: the diagonal used
    # to double-count orientations, planting phantom communities)
    src, dst, blocks = sbm([200, 200], p_in=0.05, p_out=0.05, seed=9)
    intra_edges = (blocks[src] == blocks[dst]).sum()
    inter_edges = len(src) - intra_edges
    intra_pairs = 2 * (200 * 199 // 2)
    inter_pairs = 200 * 200
    ratio = (intra_edges / intra_pairs) / (inter_edges / inter_pairs)
    assert 0.85 < ratio < 1.15


def test_metrics_scale_to_fine_partitions():
    # ~n-cluster vs ~n-cluster comparison must not materialize a ka*kb
    # table (sparse contingency): 50k x 50k would be ~20 GB dense
    n = 50_000
    rng = np.random.default_rng(4)
    a = np.arange(n) // 2           # 25k clusters
    b = rng.permutation(n) // 2     # 25k clusters, unrelated
    assert abs(adjusted_rand_index(a, b)) < 0.01
    assert normalized_mutual_info(a, a) == pytest.approx(1.0)


def test_weighted_shortest_paths_rejects_nan():
    from graphmine_tpu.ops.paths import weighted_shortest_paths

    g = build_graph(np.array([0], np.int32), np.array([1], np.int32),
                    num_vertices=2)
    with pytest.raises(ValueError, match="NaN"):
        weighted_shortest_paths(g, np.array([0], np.int32),
                                np.array([np.nan], np.float32))


def test_weighted_shortest_paths_vs_networkx():
    nx = pytest.importorskip("networkx")

    from graphmine_tpu.ops.paths import weighted_shortest_paths

    rng = np.random.default_rng(2)
    v, e = 60, 240
    src = rng.integers(0, v, e).astype(np.int32)
    dst = rng.integers(0, v, e).astype(np.int32)
    w = rng.uniform(0.1, 5.0, e).astype(np.float32)
    g = build_graph(src, dst, num_vertices=v, symmetric=False)
    dist = np.asarray(weighted_shortest_paths(g, np.array([0], np.int32), w))

    G = nx.DiGraph()
    G.add_nodes_from(range(v))
    for s, d, ww in zip(src, dst, w):  # parallel edges: keep the lightest
        if G.has_edge(int(s), int(d)):
            G[int(s)][int(d)]["weight"] = min(G[int(s)][int(d)]["weight"], float(ww))
        else:
            G.add_edge(int(s), int(d), weight=float(ww))
    oracle = nx.single_source_dijkstra_path_length(G, 0)
    for u in range(v):
        if u in oracle:
            assert dist[u] == pytest.approx(oracle[u], rel=1e-5)
        else:
            assert np.isinf(dist[u])


def test_weighted_shortest_paths_both_directions():
    from graphmine_tpu.ops.paths import weighted_shortest_paths

    # path 0 -1.0- 1 -2.0- 2, directed 0->1->2; "both" makes 2 reach 0
    g = build_graph(np.array([0, 1], np.int32), np.array([1, 2], np.int32),
                    num_vertices=3)
    w = np.array([1.0, 2.0], np.float32)
    d_out = np.asarray(weighted_shortest_paths(g, np.array([2], np.int32), w))
    assert np.isinf(d_out[0]) and d_out[2] == 0
    d_both = np.asarray(weighted_shortest_paths(g, np.array([2], np.int32), w,
                                                direction="both"))
    assert d_both[0] == pytest.approx(3.0) and d_both[1] == pytest.approx(2.0)
