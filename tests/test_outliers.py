"""Census, recursive-LPA outliers (parity path) and kNN/LOF (north-star path)."""

import numpy as np
import jax.numpy as jnp
import pytest

from graphmine_tpu.graph.container import build_graph
from graphmine_tpu.ops.census import census_table, community_sizes, intra_community_edge_mask
from graphmine_tpu.ops.knn import knn
from graphmine_tpu.ops.lof import auroc, lof_scores
from graphmine_tpu.ops.lpa import label_propagation
from graphmine_tpu.ops.outliers import masked_label_propagation, recursive_lpa_outliers


def test_community_sizes_and_census(bundled_graph):
    labels = label_propagation(bundled_graph, max_iter=5)
    present, sizes, edges = census_table(labels, bundled_graph)
    assert sizes.sum() == bundled_graph.num_vertices
    assert len(present) == len(np.unique(np.asarray(labels)))
    # BASELINE.md: top community sizes around 288, 240, 220 (tie-break dependent)
    assert 150 <= sizes.max() <= 600


def test_intra_mask_matches_numpy(rng):
    src = rng.integers(0, 30, 100)
    dst = rng.integers(0, 30, 100)
    g = build_graph(src, dst, num_vertices=30)
    labels = label_propagation(g, max_iter=3)
    mask = np.asarray(intra_community_edge_mask(labels, g))
    l = np.asarray(labels)
    np.testing.assert_array_equal(mask, l[src] == l[dst])


def test_masked_lpa_stays_within_communities(rng):
    src = rng.integers(0, 60, 300)
    dst = rng.integers(0, 60, 300)
    g = build_graph(src, dst, num_vertices=60)
    comm = label_propagation(g, max_iter=3)
    sub = np.asarray(masked_label_propagation(g, comm, max_iter=5))
    comm_np = np.asarray(comm)
    # every sub-community is contained in exactly one parent community
    for s in np.unique(sub):
        members = np.flatnonzero(sub == s)
        assert len(np.unique(comm_np[members])) == 1


def test_masked_lpa_equals_per_community_lpa():
    # Two disjoint triangles: masking with the 2-community partition must give
    # the same result as running LPA on each triangle separately.
    src = np.array([0, 1, 2, 3, 4, 5])
    dst = np.array([1, 2, 0, 4, 5, 3])
    g = build_graph(src, dst)
    comm = jnp.array([0, 0, 0, 1, 1, 1], jnp.int32)
    sub = np.asarray(masked_label_propagation(g, comm, max_iter=4))
    ga = build_graph([0, 1, 2], [1, 2, 0])
    sub_a = np.asarray(label_propagation(ga, max_iter=4))
    assert (sub[:3] == sub_a).all()


def test_recursive_outliers_bundled(bundled_graph):
    comm = label_propagation(bundled_graph, max_iter=5)
    report = recursive_lpa_outliers(bundled_graph, comm)
    assert report.sub_sizes.sum() == bundled_graph.num_vertices
    # outlier sub-communities must be small ones
    if report.outlier_vertices.any():
        flagged = np.unique(report.sub_labels[report.outlier_vertices])
        sub_index = {s: i for i, s in enumerate(np.unique(report.sub_labels))}
        for s in flagged:
            parent = report.sub_parents[sub_index[s]]
            thr = report.thresholds[int(parent)]
            assert report.sub_sizes[sub_index[s]] <= thr


def test_recursive_outliers_sharded_matches_masked(bundled_graph):
    """The scale-out composition (host intra-community edge filter →
    distributed LPA → shared decile) reproduces the single-device masked
    pass bit-for-bit on both distributed schedules (VERDICT r3 item 2)."""
    from graphmine_tpu.ops.outliers import recursive_lpa_outliers_sharded
    from graphmine_tpu.parallel.mesh import make_mesh

    comm = label_propagation(bundled_graph, max_iter=5)
    ref = recursive_lpa_outliers(bundled_graph, comm)
    mesh = make_mesh(8)
    for schedule in ("replicated", "ring"):
        got = recursive_lpa_outliers_sharded(
            bundled_graph, comm, mesh, schedule=schedule
        )
        np.testing.assert_array_equal(ref.sub_labels, got.sub_labels)
        np.testing.assert_array_equal(ref.outlier_vertices, got.outlier_vertices)
        np.testing.assert_array_equal(ref.sub_sizes, got.sub_sizes)
        np.testing.assert_array_equal(ref.sub_parents, got.sub_parents)
        assert ref.thresholds == got.thresholds


def test_recursive_outliers_sharded_ignores_weights_like_masked(rng):
    """The recursive pass is unweighted by definition (parity with
    masked_label_propagation, whose mode is a count) — on a WEIGHTED
    graph the sharded composition must still match the masked pass
    bit-for-bit, i.e. neither may let msg_weight leak into the
    sub-community LPA."""
    from graphmine_tpu.ops.outliers import recursive_lpa_outliers_sharded
    from graphmine_tpu.parallel.mesh import make_mesh

    src = rng.integers(0, 200, 1200).astype(np.int32)
    dst = rng.integers(0, 200, 1200).astype(np.int32)
    w = (rng.integers(1, 16, 1200) / 4.0).astype(np.float32)
    g = build_graph(src, dst, num_vertices=200, edge_weights=w)
    comm = label_propagation(g, max_iter=3)
    ref = recursive_lpa_outliers(g, comm, max_iter=4)
    got = recursive_lpa_outliers_sharded(
        g, comm, make_mesh(8), max_iter=4, schedule="ring"
    )
    np.testing.assert_array_equal(ref.sub_labels, got.sub_labels)
    np.testing.assert_array_equal(ref.outlier_vertices, got.outlier_vertices)
    assert ref.thresholds == got.thresholds


def test_recursive_outliers_sharded_all_cross_community():
    """Degenerate mask: every edge crosses communities, so the filtered
    graph is empty and every vertex is its own sub-community — on the
    distributed path too (empty-message partition)."""
    from graphmine_tpu.ops.outliers import recursive_lpa_outliers_sharded
    from graphmine_tpu.parallel.mesh import make_mesh

    # bipartite edges, communities = the two sides
    src = np.array([0, 1, 2, 3], np.int32)
    dst = np.array([4, 5, 6, 7], np.int32)
    g = build_graph(src, dst, num_vertices=8)
    comm = jnp.array([0, 0, 0, 0, 1, 1, 1, 1], jnp.int32)
    ref = recursive_lpa_outliers(g, comm)
    got = recursive_lpa_outliers_sharded(g, comm, make_mesh(8))
    np.testing.assert_array_equal(ref.sub_labels, got.sub_labels)
    np.testing.assert_array_equal(got.sub_labels, np.arange(8, dtype=np.int32))
    assert not got.outlier_vertices.any()


def test_knn_matches_sklearn(rng):
    from sklearn.neighbors import NearestNeighbors

    x = rng.normal(size=(300, 5)).astype(np.float32)
    d, i = knn(jnp.asarray(x), k=7, row_tile=64)
    sk = NearestNeighbors(n_neighbors=7).fit(x)
    sk_d, sk_i = sk.kneighbors(None)  # None: exclude each point itself
    np.testing.assert_allclose(np.sqrt(np.asarray(d)), sk_d, rtol=1e-4, atol=1e-4)
    np.testing.assert_array_equal(np.asarray(i), sk_i)


def test_lof_matches_sklearn(rng):
    from sklearn.neighbors import LocalOutlierFactor

    x = rng.normal(size=(400, 4)).astype(np.float32)
    x[:10] += 6.0  # inject a clear outlier cluster
    ours = np.asarray(lof_scores(jnp.asarray(x), k=15, row_tile=128))
    sk = LocalOutlierFactor(n_neighbors=15)
    sk.fit(x)
    theirs = -sk.negative_outlier_factor_
    np.testing.assert_allclose(ours, theirs, rtol=1e-3, atol=1e-3)


def test_lof_auroc_on_injected_anomalies(rng):
    x = rng.normal(size=(500, 5)).astype(np.float32)
    y = np.zeros(500, dtype=bool)
    y[:25] = True
    x[:25] += rng.normal(scale=5.0, size=(25, 5))
    scores = np.asarray(lof_scores(jnp.asarray(x), k=20, row_tile=128))
    assert auroc(scores, y) > 0.95


def test_auroc_sanity():
    assert auroc([0.1, 0.2, 0.9, 0.8], [False, False, True, True]) == 1.0
    assert auroc([0.9, 0.8, 0.1, 0.2], [False, False, True, True]) == 0.0
