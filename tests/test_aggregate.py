"""aggregate_messages / pregel substrate tests (SURVEY §4 algorithm-semantics)."""

import jax.numpy as jnp
import numpy as np

from graphmine_tpu.graph.container import build_graph
from graphmine_tpu.ops.aggregate import aggregate_messages, pregel
from graphmine_tpu.ops.cc import connected_components
from graphmine_tpu.ops.degrees import in_degrees, out_degrees
from graphmine_tpu.ops.lpa import lpa_superstep


def _graph():
    # 0->1, 1->2, 2->0 triangle plus 3->4 pendant, 5 isolated
    src = np.array([0, 1, 2, 3], np.int32)
    dst = np.array([1, 2, 0, 4], np.int32)
    return build_graph(src, dst, num_vertices=6)


def test_degree_via_aggregate_matches_degrees_op():
    g = _graph()
    ones = jnp.ones((g.num_vertices,), jnp.int32)
    indeg = aggregate_messages(g, ones, to_dst=lambda s, d, e: s, reduce="sum")
    outdeg = aggregate_messages(g, ones, to_src=lambda s, d, e: d, reduce="sum")
    np.testing.assert_array_equal(np.asarray(indeg), np.asarray(in_degrees(g)))
    np.testing.assert_array_equal(np.asarray(outdeg), np.asarray(out_degrees(g)))


def test_mode_reduce_matches_lpa_superstep():
    g = _graph()
    labels = jnp.arange(g.num_vertices, dtype=jnp.int32)
    agg = aggregate_messages(
        g, labels, to_dst=lambda s, d, e: s, to_src=lambda s, d, e: d, reduce="mode"
    )
    expect = lpa_superstep(labels, g)
    # lpa_superstep keeps old label for isolated vertices; mask the same way
    deg = np.asarray(g.degrees())
    got = np.where(deg > 0, np.asarray(agg), np.asarray(labels))
    np.testing.assert_array_equal(got, np.asarray(expect))


def test_mean_and_edge_values():
    g = _graph()
    w = jnp.array([1.0, 2.0, 3.0, 4.0])
    x = jnp.arange(6, dtype=jnp.float32)
    got = aggregate_messages(
        g, x, edge_values=w, to_dst=lambda s, d, e: s * e, reduce="mean"
    )
    # vertex 1 gets 0*1; vertex 2 gets 1*2; vertex 0 gets 2*3; vertex 4 gets 3*4
    np.testing.assert_allclose(np.asarray(got)[:5], [6.0, 0.0, 2.0, 0.0, 12.0])


def test_pregel_min_propagation_reaches_cc_fixpoint():
    g = _graph()
    init = jnp.arange(g.num_vertices, dtype=jnp.int32)
    state = pregel(
        g,
        init,
        to_dst=lambda s, d, e: s,
        to_src=lambda s, d, e: d,
        reduce="min",
        update=lambda st, agg: jnp.minimum(st, agg),
        max_iter=6,
    )
    expect = connected_components(g)
    np.testing.assert_array_equal(np.asarray(state), np.asarray(expect))


def test_pregel_pytree_state():
    g = _graph()
    init = {"v": jnp.arange(6, dtype=jnp.int32), "steps": jnp.zeros((6,), jnp.int32)}
    out = pregel(
        g,
        init,
        to_dst=lambda s, d, e: s["v"],
        reduce="max",
        update=lambda st, agg: {
            "v": jnp.maximum(st["v"], agg),
            "steps": st["steps"] + 1,
        },
        max_iter=3,
    )
    assert int(out["steps"][0]) == 3
    # max propagation along 0->1->2->0 cycle converges to 2 on the cycle
    assert np.asarray(out["v"])[:3].tolist() == [2, 2, 2]
