"""Louvain + modularity tests: hand-checked fixtures, a networkx oracle,
determinism, and partition-quality comparison against LPA (SURVEY §7.7)."""

import numpy as np
import pytest

from graphmine_tpu.graph.container import build_graph
from graphmine_tpu.ops.louvain import louvain
from graphmine_tpu.ops.lpa import label_propagation
from graphmine_tpu.ops.modularity import modularity


def _two_cliques_bridge():
    """Two K4s joined by one edge. Optimal partition = the cliques,
    Q = 2 * (12/26 - (13/26)^2) = 0.42307..."""
    edges = []
    for base in (0, 4):
        for i in range(4):
            for j in range(i + 1, 4):
                edges.append((base + i, base + j))
    edges.append((0, 4))
    src, dst = np.array(edges, np.int32).T
    return build_graph(src, dst, num_vertices=8)


def test_modularity_two_cliques():
    g = _two_cliques_bridge()
    labels = np.array([0, 0, 0, 0, 1, 1, 1, 1], np.int32)
    q = float(modularity(labels, g))
    assert abs(q - (24 / 26 - 0.5)) < 1e-6
    # all-singletons partition has known Q too: -sum((k_i/2m)^2)
    singles = np.arange(8, dtype=np.int32)
    deg = np.asarray(g.degrees())
    want = -np.sum((deg / 26) ** 2)
    assert abs(float(modularity(singles, g)) - want) < 1e-6


def test_modularity_matches_networkx(rng):
    nx = pytest.importorskip("networkx")
    gnx = nx.gnm_random_graph(60, 180, seed=3)
    edges = np.array(gnx.edges(), np.int32)
    g = build_graph(edges[:, 0], edges[:, 1], num_vertices=60)
    labels = rng.integers(0, 5, 60).astype(np.int32)
    comms = [set(np.flatnonzero(labels == c)) for c in range(5)]
    comms = [c for c in comms if c]
    want = nx.algorithms.community.modularity(gnx, comms)
    assert abs(float(modularity(labels, g)) - want) < 1e-5


def test_louvain_two_cliques():
    g = _two_cliques_bridge()
    labels, q = louvain(g)
    labels = np.asarray(labels)
    assert len(set(labels[:4])) == 1 and len(set(labels[4:])) == 1
    assert labels[0] != labels[4]
    assert abs(q - (24 / 26 - 0.5)) < 1e-6


def test_louvain_ring_of_cliques():
    """8 K5s in a ring: every clique must land inside one community and
    Q must be near the known optimum (~0.72 for merged-pair solutions,
    ~0.7578 for the clique partition)."""
    edges = []
    s, r = 5, 8
    for c in range(r):
        base = c * s
        for i in range(s):
            for j in range(i + 1, s):
                edges.append((base + i, base + j))
        edges.append((base, ((c + 1) % r) * s))
    src, dst = np.array(edges, np.int32).T
    g = build_graph(src, dst, num_vertices=s * r)
    labels, q = louvain(g)
    labels = np.asarray(labels)
    for c in range(r):
        assert len(set(labels[c * s:(c + 1) * s])) == 1, f"clique {c} split"
    assert q > 0.70


def test_louvain_beats_lpa_on_bundled(bundled_graph):
    lpa_q = float(modularity(label_propagation(bundled_graph, max_iter=5), bundled_graph))
    _, louvain_q = louvain(bundled_graph)
    assert louvain_q > lpa_q
    assert louvain_q > 0.3  # real community structure in the web graph


def test_louvain_same_parity_singletons_merge():
    """Regression: two adjacent same-parity singletons must merge, not swap
    labels forever (the synchronous-move swap cycle; broken by the
    singleton-ordering rule)."""
    g = build_graph([0], [2], num_vertices=3)
    labels, q = louvain(g)
    labels = np.asarray(labels)
    assert labels[0] == labels[2]
    assert abs(q - 0.0) < 1e-6  # one edge, one community: Q = 1/2m*2m... = 0

    # an even-id-only path: 0-2-4-6; all moves are even->even
    g2 = build_graph([0, 2, 4], [2, 4, 6], num_vertices=7)
    l2, q2 = louvain(g2)
    l2 = np.asarray(l2)
    assert len({l2[0], l2[2], l2[4], l2[6]}) <= 2  # path communities merge
    assert q2 > 0.0


def test_louvain_deterministic():
    g = _two_cliques_bridge()
    l1, q1 = louvain(g)
    l2, q2 = louvain(g)
    np.testing.assert_array_equal(np.asarray(l1), np.asarray(l2))
    assert q1 == q2


def test_leiden_dominates_louvain_and_splits_disconnected():
    """Leiden's refinement: modularity within a fraction of a percent of
    Louvain's, and communities Louvain leaves internally disconnected are
    split (the R-MAT cases produce ~10 such communities under Louvain —
    the connectivity property is the hard guarantee here)."""
    import networkx as nx

    from graphmine_tpu.datasets import rmat, sbm
    from graphmine_tpu.ops.louvain import leiden, louvain

    def disconnected_count(labels, src, dst, v):
        G = nx.Graph()
        G.add_nodes_from(range(v))
        G.add_edges_from((int(a), int(b)) for a, b in zip(src, dst) if a != b)
        labels = np.asarray(labels)
        bad = 0
        for lab in np.unique(labels):
            mem = np.flatnonzero(labels == lab)
            if len(mem) > 1 and not nx.is_connected(G.subgraph(mem.tolist())):
                bad += 1
        return bad

    cases = []
    s, d, blocks = sbm([150] * 4, 0.06, 0.004, seed=2)
    cases.append((s, d, len(blocks)))
    for seed in (3, 7):
        s, d = rmat(10, 8, seed=seed)
        cases.append((s, d, 1 << 10))

    for src, dst, v in cases:
        g = build_graph(src, dst, num_vertices=v)
        _, ql = louvain(g)
        labels, qe = leiden(g)
        assert qe >= ql - 0.005  # comparable modularity
        assert disconnected_count(labels, src, dst, v) == 0


def test_leiden_recovers_planted_blocks():
    from graphmine_tpu.datasets import sbm
    from graphmine_tpu.ops.cluster_metrics import adjusted_rand_index
    from graphmine_tpu.ops.louvain import leiden

    src, dst, blocks = sbm([120] * 5, 0.08, 0.003, seed=9)
    g = build_graph(src, dst, num_vertices=len(blocks))
    labels, q = leiden(g)
    assert adjusted_rand_index(np.asarray(labels), blocks) > 0.95
    assert q > 0.5
