"""Native C++ graph builder vs the NumPy fallback (parity + robustness)."""

import os
import subprocess

import numpy as np
import pytest

from graphmine_tpu.io import native
from graphmine_tpu.io.edges import load_edge_list

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module", autouse=True)
def built_lib():
    if not native.available():
        subprocess.run(["make", "-C", os.path.join(REPO, "native")], check=True)
        native._LIB_TRIED = False  # re-probe after build
    if not native.available():
        pytest.skip("native lib unavailable")


def _write(tmp_path, text):
    p = tmp_path / "edges.txt"
    p.write_text(text)
    return str(p)


def test_native_matches_numpy(tmp_path):
    path = _write(tmp_path, "# header\na b\nb c\na b\n  c a\n")
    et_native = native.load_edge_list_native(path)
    et_numpy = load_edge_list(path, use_native=False)
    assert et_native.src.tolist() == et_numpy.src.tolist()
    assert et_native.dst.tolist() == et_numpy.dst.tolist()
    assert et_native.names.tolist() == et_numpy.names.tolist()


def test_native_integer_ids(tmp_path):
    path = _write(tmp_path, "10 20\n20 30\n10 30\n")
    et = native.load_edge_list_native(path)
    assert et.num_edges == 3
    assert et.names.tolist() == ["10", "20", "30"]
    assert et.src.tolist() == [0, 1, 0]


def test_native_empty_and_blank_lines(tmp_path):
    path = _write(tmp_path, "\n\n# only comments\n\n")
    et = native.load_edge_list_native(path)
    assert et.num_edges == 0 and et.num_vertices == 0


def test_native_missing_file():
    assert native.load_edge_list_native("/nonexistent/e.txt") is None


def test_native_large_roundtrip(tmp_path, rng):
    src = rng.integers(0, 1000, 20000)
    dst = rng.integers(0, 1000, 20000)
    path = _write(tmp_path, "".join(f"v{s} v{d}\n" for s, d in zip(src, dst)))
    et = native.load_edge_list_native(path)
    assert et.num_edges == 20000
    # decode back through names and compare to the original ids
    back_src = np.array([et.names[i] for i in et.src])
    assert (back_src == np.array([f"v{s}" for s in src])).all()


def test_native_message_csr_matches_numpy():
    from graphmine_tpu.graph.container import _message_csr
    from graphmine_tpu.io import native

    if not native.available():
        pytest.skip("native lib unavailable")
    rng = np.random.default_rng(3)
    src = rng.integers(0, 50, 400).astype(np.int32)
    dst = rng.integers(0, 50, 400).astype(np.int32)
    for sym in (True, False):
        pn, rn, sn, _ = _message_csr(src, dst, 50, sym, use_native=True)
        pp, rp, sp, _ = _message_csr(src, dst, 50, sym, use_native=False)
        np.testing.assert_array_equal(pn, pp)
        np.testing.assert_array_equal(rn, rp)
        np.testing.assert_array_equal(sn, sp)
    with pytest.raises(ValueError):
        native.build_message_csr(np.array([99], np.int32), np.array([0], np.int32), 50)


def test_native_weighted_message_csr_matches_numpy():
    """r2: the weighted build rides the native counting sort too (was
    NumPy-argsort-only); layout AND weight permutation must match the
    NumPy path bit-for-bit."""
    from graphmine_tpu.graph.container import _message_csr
    from graphmine_tpu.io import native

    if not native.available():
        pytest.skip("native lib unavailable")
    if not hasattr(native._lib(), "gb_build_message_csr_weighted"):
        # stale .so: the wrapper would fall back to NumPy and this test
        # would vacuously compare NumPy against NumPy
        pytest.skip("libgraphbuild.so predates the weighted builder")
    rng = np.random.default_rng(5)
    src = rng.integers(0, 50, 400).astype(np.int32)
    dst = rng.integers(0, 50, 400).astype(np.int32)
    w = rng.uniform(0.1, 9.0, 400).astype(np.float32)
    for sym in (True, False):
        pn, rn, sn, wn = _message_csr(src, dst, 50, sym, use_native=True, weights=w)
        pp, rp, sp, wp = _message_csr(src, dst, 50, sym, use_native=False, weights=w)
        assert wn is not None
        np.testing.assert_array_equal(pn, pp)
        np.testing.assert_array_equal(rn, rp)
        np.testing.assert_array_equal(sn, sp)
        np.testing.assert_array_equal(wn, wp)
    with pytest.raises(ValueError):
        native.build_message_csr(
            np.array([99], np.int32), np.array([0], np.int32), 50,
            weights=np.array([1.0], np.float32),
        )
