"""Serving-SLO observability suite (marker ``slo``;
``tools/run_tier1.sh --slo-only``): bucket histograms, the live
``/metrics`` + ``/statusz`` endpoints, request tracing, repair-debt
accounting, and the obs_report serving-SLO section.

The acceptance pins (ISSUE 6):
- concurrent histogram observes lose nothing, and merge is associative
  (bucket counts exactly; sums to float tolerance);
- ``GET /metrics`` and ``GET /statusz`` serve mid-flight under the
  live-query hammer, across a delta publish, with no torn exposition
  (every scrape parses; cumulative buckets monotone; ``+Inf`` ==
  ``_count``);
- the ``/statusz`` per-endpoint quantiles agree with quantiles computed
  offline from the ``access_log`` JSONL alone to within one histogram
  bucket;
- ``access_log`` / ``slo_rollup`` records are schema-registered and
  carry full trace identity.
"""

import bisect
import json
import math
import os
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from graphmine_tpu.graph.container import build_graph
from graphmine_tpu.obs.histogram import (
    DEFAULT_LATENCY_BUCKETS,
    Histogram,
)
from graphmine_tpu.obs.registry import Registry
from graphmine_tpu.obs.schema import validate_records
from graphmine_tpu.obs.spans import Tracer
from graphmine_tpu.pipeline.checkpoint import graph_fingerprint
from graphmine_tpu.pipeline.metrics import MetricsSink
from graphmine_tpu.serve import (
    DeltaIngestor,
    EdgeDelta,
    QueryEngine,
    RepairDebt,
    SnapshotStore,
)
from graphmine_tpu.serve.delta import cold_recompute
from graphmine_tpu.serve.server import SnapshotServer

pytestmark = pytest.mark.slo


# ---- fixtures -------------------------------------------------------------


def _clique(lo, hi):
    ids = np.arange(lo, hi)
    s, d = np.meshgrid(ids, ids)
    m = s.ravel() < d.ravel()
    return s.ravel()[m], d.ravel()[m]


def _community_graph():
    parts = [_clique(0, 12), _clique(12, 26), _clique(26, 40)]
    src = np.concatenate([p[0] for p in parts]).astype(np.int32)
    dst = np.concatenate([p[1] for p in parts]).astype(np.int32)
    return src, dst, 40


def _publish_base(tmp_path, sink=None):
    src, dst, v = _community_graph()
    g = build_graph(src, dst, num_vertices=v)
    labels, cc, _ = cold_recompute(g)
    store = SnapshotStore(str(tmp_path / "snap"))
    store.publish(
        {
            "src": src, "dst": dst, "labels": labels, "cc_labels": cc,
            "lof": np.linspace(0.5, 2.5, v).astype(np.float32),
        },
        fingerprint=graph_fingerprint(src, dst),
        sink=sink,
    )
    return store


def _get(host, port, path, headers=None):
    req = urllib.request.Request(
        f"http://{host}:{port}{path}", headers=headers or {}
    )
    with urllib.request.urlopen(req, timeout=30) as r:
        return r.read(), dict(r.headers)


def _get_json(host, port, path, headers=None):
    body, hdrs = _get(host, port, path, headers)
    return json.loads(body), hdrs


def _post(host, port, path, payload, headers=None):
    req = urllib.request.Request(
        f"http://{host}:{port}{path}", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    with urllib.request.urlopen(req, timeout=120) as r:
        return json.loads(r.read()), dict(r.headers)


# ---- histograms -----------------------------------------------------------


def test_histogram_observe_count_sum_quantile():
    h = Histogram("h", buckets=(0.001, 0.01, 0.1, 1.0))
    for v in (0.0005, 0.005, 0.005, 0.05, 0.5, 5.0):
        h.observe(v)
    snap = h.snapshot()
    assert snap.count == 6
    assert snap.sum == pytest.approx(5.5605)
    # per-bucket: one <=1ms, two <=10ms, one <=100ms, one <=1s, one +Inf
    assert snap.counts == (1, 2, 1, 1, 1)
    assert snap.cumulative() == [1, 3, 4, 5, 6]
    # the median rank lands at the top of the (0.001, 0.01] bucket
    assert h.quantile(0.5) == pytest.approx(0.01)
    # a rank in the +Inf overflow reports the largest finite bound
    assert h.quantile(0.999) == 1.0
    # empty histogram: 0.0, never NaN (statusz must stay strict-JSON)
    assert Histogram("e").quantile(0.5) == 0.0
    with pytest.raises(ValueError, match="quantile"):
        h.quantile(1.5)


def test_histogram_bucket_validation():
    with pytest.raises(ValueError, match="strictly increasing"):
        Histogram("h", buckets=(0.1, 0.1))
    with pytest.raises(ValueError, match="finite"):
        Histogram("h", buckets=(0.1, float("inf")))
    with pytest.raises(ValueError, match="at least one"):
        Histogram("h", buckets=())


def test_histogram_merge_associativity():
    """Merge over one bucket ladder is associative: bucket counts
    exactly (integer adds), sums to float tolerance — the property that
    lets per-replica histograms roll up into a fleet view in any
    grouping."""
    rng = np.random.default_rng(0)

    def mk(vals):
        h = Histogram("m")
        for v in vals:
            h.observe(float(v))
        return h

    a_vals = rng.exponential(0.001, 40)
    b_vals = rng.exponential(0.1, 30)
    c_vals = rng.exponential(2.0, 20)
    ab_c = mk([]).merge(mk(a_vals)).merge(mk(b_vals)).merge(mk(c_vals))
    bc = mk([]).merge(mk(b_vals)).merge(mk(c_vals))
    a_bc = mk([]).merge(mk(a_vals)).merge(bc)
    assert ab_c.snapshot().counts == a_bc.snapshot().counts
    assert ab_c.snapshot().sum == pytest.approx(a_bc.snapshot().sum)
    assert ab_c.count == 90
    # commutes too
    c_a_b = mk([]).merge(mk(c_vals)).merge(mk(a_vals)).merge(mk(b_vals))
    assert c_a_b.snapshot().counts == ab_c.snapshot().counts
    # mismatched ladders refuse instead of silently re-binning
    with pytest.raises(ValueError, match="different bucket ladders"):
        mk([]).merge(Histogram("x", buckets=(1.0, 2.0)))


def test_histogram_concurrent_observes_lose_nothing():
    h = Histogram("c")
    n_threads, per_thread = 8, 2000

    def work(seed):
        rng = np.random.default_rng(seed)
        for v in rng.exponential(0.01, per_thread):
            h.observe(float(v))

    threads = [
        threading.Thread(target=work, args=(i,)) for i in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = h.snapshot()
    assert snap.count == n_threads * per_thread
    assert sum(snap.counts) == snap.count


def test_registry_histogram_family_and_conflicts():
    reg = Registry()
    h1 = reg.histogram("req_s", "latency", endpoint="query")
    assert reg.histogram("req_s", endpoint="query") is h1
    h2 = reg.histogram("req_s", endpoint="vertex")
    assert h2 is not h1
    fam = reg.histogram_family("req_s")
    assert [c.labels["endpoint"] for c in fam.children()] == [
        "query", "vertex"
    ]
    assert reg.histogram_family("nope") is None
    # one name, one kind / one ladder
    reg.counter("c_total").inc()
    with pytest.raises(ValueError, match="already registered"):
        reg.histogram("c_total")
    with pytest.raises(ValueError, match="already registered"):
        reg.gauge("req_s")
    with pytest.raises(ValueError, match="bucket ladder"):
        reg.histogram("req_s", buckets=(1.0, 2.0))
    # values() folds a histogram family to its total observation count
    h1.observe(0.1)
    h2.observe(0.2)
    assert reg.values()["req_s"] == 2
    # an invalid ladder raises WITHOUT registering: the name is not
    # poisoned for the later, valid call
    with pytest.raises(ValueError, match="strictly increasing"):
        reg.histogram("clean", buckets=(0.1, 0.1))
    assert reg.histogram_family("clean") is None
    reg.histogram("clean", buckets=(0.1, 0.2)).observe(0.15)
    assert reg.values()["clean"] == 1


def test_textfile_exposition_deterministic_help_type():
    """The satellite pin: # HELP/# TYPE lines, sorted metric ordering,
    sorted histogram children, byte-identical renders regardless of
    creation order — so successive scrapes diff cleanly."""

    def build(order):
        reg = Registry()
        for what in order:
            if what == "g":
                reg.gauge("aaa_gauge", "a gauge").set(2)
            elif what == "c":
                reg.counter("zzz_total", "a counter").inc(3)
            else:
                reg.histogram(
                    "mid_seconds", "latency", buckets=(0.01, 0.1),
                    endpoint=what,
                ).observe(0.05)
        return reg.render_textfile(labels={"run_id": "r1"})

    a = build(["g", "c", "vertex", "query"])
    b = build(["query", "c", "vertex", "g"])
    assert a == b
    lines = a.splitlines()
    # metric families in name order, children in label order
    assert lines.index("# TYPE aaa_gauge gauge") < lines.index(
        "# TYPE mid_seconds histogram"
    ) < lines.index("# TYPE zzz_total counter")
    assert "# HELP mid_seconds latency" in lines
    q = [ln for ln in lines if ln.startswith("mid_seconds_bucket")]
    assert q == [
        'mid_seconds_bucket{endpoint="query",run_id="r1",le="0.01"} 0',
        'mid_seconds_bucket{endpoint="query",run_id="r1",le="0.1"} 1',
        'mid_seconds_bucket{endpoint="query",run_id="r1",le="+Inf"} 1',
        'mid_seconds_bucket{endpoint="vertex",run_id="r1",le="0.01"} 0',
        'mid_seconds_bucket{endpoint="vertex",run_id="r1",le="0.1"} 1',
        'mid_seconds_bucket{endpoint="vertex",run_id="r1",le="+Inf"} 1',
    ]
    assert 'mid_seconds_count{endpoint="query",run_id="r1"} 1' in lines


# ---- repair debt ----------------------------------------------------------


def test_repair_debt_ledger():
    reg = Registry()
    debt = RepairDebt(registry=reg)
    debt.submitted(10, t=100.0)
    debt.submitted(5, t=200.0)
    snap = debt.snapshot()
    assert snap["pending_deltas"] == 2 and snap["pending_rows"] == 15
    assert debt.ingest_lag_s(now=103.0) == pytest.approx(3.0)
    assert reg.values()["graphmine_serve_repair_debt_rows"] == 15
    debt.applied(method="warm", iterations=6, budget=24)
    snap = debt.snapshot()
    assert snap["pending_rows"] == 5 and snap["applies_warm"] == 1
    assert snap["last_budget_frac"] == pytest.approx(0.25)
    assert snap["rows_applied_total"] == 10
    debt.applied(method="full_recompute", iterations=12, budget=24)
    snap = debt.snapshot()
    assert snap["applies_cold"] == 1 and snap["warm_ratio"] == 0.5
    assert snap["pending_rows"] == 0 and snap["ingest_lag_s"] == 0.0
    assert reg.values()["graphmine_serve_repairs_cold_total"] == 1
    # an abandoned submission (validation refused) drains without
    # counting an apply
    debt.submitted(7)
    debt.abandoned()
    snap = debt.snapshot()
    assert snap["pending_rows"] == 0
    assert snap["applies_warm"] + snap["applies_cold"] == 2


def test_delta_apply_record_carries_budget_and_debt(tmp_path):
    sink = MetricsSink(tracer=Tracer())
    store = _publish_base(tmp_path, sink=sink)
    ing = DeltaIngestor(store, sink=sink, lof_k=4, check_samples=8)
    ing.apply(EdgeDelta.from_pairs(insert=[(40, 12), (40, 13)]))
    rec = [r for r in sink.records if r["phase"] == "delta_apply"][-1]
    assert rec["budget"] > 0 and rec["iterations"] <= rec["budget"]
    debt = rec["repair_debt"]
    assert debt["applies_warm"] == 1 and debt["pending_rows"] == 0
    assert validate_records(sink.records) == []


# ---- query stage split ----------------------------------------------------


def test_query_engine_stage_split(tmp_path):
    store = _publish_base(tmp_path)
    eng = QueryEngine(store.load())
    assert eng.stage_snapshot()["batches"] == 0
    for n in (3, 7, 30):
        eng.query_batch(np.arange(n))
    stages = eng.stage_snapshot()
    assert stages["batches"] == 3 and stages["ids"] == 40
    assert stages["gather_seconds"] > 0.0
    assert stages["pad_seconds"] >= 0.0 and stages["host_seconds"] >= 0.0
    # host-table twin accounts too
    eng_h = QueryEngine(store.load(), device=False)
    eng_h.query_batch([1, 2, 3])
    assert eng_h.stage_snapshot()["batches"] == 1


# ---- HTTP SLO surfaces ----------------------------------------------------


def _parse_exposition(text):
    """Parse histogram bucket/count lines into
    {labels-string-without-le: {"buckets": [(le, v), ...], "count": n}}."""
    out = {}
    for line in text.splitlines():
        if line.startswith("#"):
            continue
        name, _, rest = line.partition("{")
        if name == "graphmine_serve_request_seconds_bucket":
            labels, _, val = rest.partition("} ")
            le = [p for p in labels.split(",") if p.startswith('le="')][0]
            key = ",".join(p for p in labels.split(",") if not p.startswith('le="'))
            out.setdefault(key, {"buckets": [], "count": None})
            out[key]["buckets"].append((le[4:-1], int(val)))
        elif name == "graphmine_serve_request_seconds_count":
            labels, _, val = rest.partition("} ")
            out.setdefault(labels, {"buckets": [], "count": None})
            out[labels]["count"] = int(val)
    return out


def _assert_untorn(text):
    """A scrape is internally consistent: cumulative buckets monotone,
    the +Inf bucket equals _count, every family's sample set complete."""
    for key, fam in _parse_exposition(text).items():
        values = [v for _, v in fam["buckets"]]
        assert values == sorted(values), f"non-monotone buckets for {key}"
        assert fam["buckets"][-1][0] == "+Inf"
        assert fam["count"] == fam["buckets"][-1][1], f"torn family {key}"


def _bucket_index(value, bounds=DEFAULT_LATENCY_BUCKETS):
    return bisect.bisect_left(bounds, value)


def test_live_metrics_statusz_under_query_hammer(tmp_path):
    """The acceptance pin: /metrics and /statusz serve mid-flight while
    the query hammer runs and a delta publishes; no dropped queries, no
    torn exposition, and the statusz quantiles agree with offline
    quantiles from the access_log JSONL to within one histogram
    bucket."""
    stream = tmp_path / "metrics.jsonl"
    sink = MetricsSink(stream_path=str(stream), tracer=Tracer())
    sink.emit("run_start", pid=os.getpid())
    store = _publish_base(tmp_path, sink=sink)
    server = SnapshotServer(store, sink=sink)
    host, port = server.start()
    try:
        errors, versions, scrapes = [], set(), []
        stop = threading.Event()

        def hammer():
            while not stop.is_set():
                try:
                    out, _ = _post(
                        host, port, "/query", {"vertices": [0, 13, 27]}
                    )
                    versions.add(out["version"])
                    if len(out["label"]) != 3:
                        raise AssertionError(f"short response: {out}")
                except Exception as e:  # noqa: BLE001 — collect, assert later
                    errors.append(e)

        def scraper():
            while not stop.is_set():
                try:
                    body, _ = _get(host, port, "/metrics")
                    scrapes.append(body.decode())
                    sz, _ = _get_json(host, port, "/statusz")
                    if "endpoints" not in sz or "repair_debt" not in sz:
                        raise AssertionError(f"bad statusz: {sz}")
                except Exception as e:  # noqa: BLE001
                    errors.append(e)

        threads = [threading.Thread(target=hammer) for _ in range(3)]
        threads.append(threading.Thread(target=scraper))
        for t in threads:
            t.start()
        # the delta publish swaps the engine mid-hammer, mid-scrape
        out, _ = _post(
            host, port, "/delta",
            {"insert": [[40, 12], [40, 13], [40, 14]], "delete": [[0, 1]]},
        )
        assert out["version"] == 2
        stop.set()
        for t in threads:
            t.join(timeout=60)
        assert errors == []
        assert versions <= {1, 2} and versions
        assert len(scrapes) >= 2
        for text in scrapes:
            _assert_untorn(text)

        # quantile agreement: statusz (live bucket estimate) vs offline
        # exact quantiles over the access_log JSONL, within one bucket
        statusz, _ = _get_json(host, port, "/statusz")
        assert statusz["inflight"] >= 1  # the statusz request itself
        q_live = statusz["endpoints"]["query"]
        assert q_live["count"] >= 3 and q_live["error_rate"] == 0.0
    finally:
        server.stop()
    sink.emit("run_end", ok=True)
    sink.finalize(str(stream))
    assert validate_records(sink.records) == []

    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
    import obs_report

    records, bad = obs_report.load_records(str(stream))
    assert bad == 0
    offline = sorted(
        float(r["seconds"]) for r in records
        if r.get("phase") == "access_log" and r.get("endpoint") == "query"
    )
    assert len(offline) >= q_live["count"]
    for q, key in ((0.5, "p50_s"), (0.95, "p95_s"), (0.99, "p99_s")):
        rank = max(1, math.ceil(q * len(offline)))
        exact = offline[rank - 1]
        live = q_live[key]
        assert abs(_bucket_index(live) - _bucket_index(exact)) <= 1, (
            f"{key}: live {live} vs offline {exact} differ by more than "
            "one bucket"
        )

    # and the JSONL alone renders the serving-SLO section
    report = obs_report.build_report(records)
    assert "-- serving SLO (latency / errors / repair debt) --" in report
    assert "repair-debt timeline:" in report
    assert "query" in report


def test_healthz_reports_staleness_and_debt(tmp_path):
    sink = MetricsSink(tracer=Tracer())
    store = _publish_base(tmp_path, sink=sink)
    server = SnapshotServer(store, sink=sink)
    host, port = server.start()
    try:
        hz, _ = _get_json(host, port, "/healthz")
        assert hz["ok"] is True and hz["version"] == 1
        assert hz["snapshot_age_s"] >= 0.0
        assert hz["repair_debt_rows"] == 0 and hz["ingest_lag_s"] == 0.0
        _post(host, port, "/delta", {"insert": [[40, 12], [40, 13]]})
        hz, _ = _get_json(host, port, "/healthz")
        assert hz["version"] == 2
        # debt drained after the apply; age restarts from the publish
        assert hz["repair_debt_rows"] == 0
        assert hz["snapshot_age_s"] < 60.0
    finally:
        server.stop()
    assert validate_records(sink.records) == []


def test_request_id_propagated_and_generated(tmp_path):
    sink = MetricsSink(tracer=Tracer())
    store = _publish_base(tmp_path, sink=sink)
    server = SnapshotServer(store, sink=sink)
    host, port = server.start()
    try:
        # client-supplied id echoes back and lands in the access_log
        _, hdrs = _get_json(
            host, port, "/healthz", headers={"X-Request-Id": "lb-42.az1"}
        )
        assert hdrs["X-Request-Id"] == "lb-42.az1"
        # absent or hostile ids get a generated one
        _, hdrs2 = _get_json(host, port, "/healthz")
        assert hdrs2["X-Request-Id"] and hdrs2["X-Request-Id"] != "lb-42.az1"
        _, hdrs3 = _get_json(
            host, port, "/healthz",
            headers={"X-Request-Id": "x" * 200},
        )
        assert len(hdrs3["X-Request-Id"]) <= 64
    finally:
        server.stop()
    logs = [r for r in sink.records if r["phase"] == "access_log"]
    assert [r["request_id"] for r in logs][0] == "lb-42.az1"
    # trace identity rides along: access_log joins the span timeline
    assert {"run_id", "trace_id", "span_id", "span_path"} <= set(logs[0])
    assert validate_records(sink.records) == []


def test_slow_request_digest_and_error_accounting(tmp_path):
    sink = MetricsSink(tracer=Tracer())
    store = _publish_base(tmp_path, sink=sink)
    # slow_request_s=0: EVERY request is "slow", so POST bodies digest
    server = SnapshotServer(store, sink=sink, slow_request_s=0.0)
    host, port = server.start()
    try:
        _post(host, port, "/query", {"vertices": [1, 2]})
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(host, port, "/query", {"vertices": [1.5]})
        assert e.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(host, port, "/nope")
        assert e.value.code == 404
        statusz, _ = _get_json(host, port, "/statusz")
    finally:
        server.stop()
    eps = statusz["endpoints"]
    assert eps["query"]["count"] == 2 and eps["query"]["errors"] == 1
    assert eps["query"]["error_rate"] == 0.5
    # unknown paths share ONE bucket — no unbounded label cardinality
    assert eps["unknown"]["errors"] == 1
    logs = [r for r in sink.records if r["phase"] == "access_log"]
    post_logs = [r for r in logs if r["method"] == "POST"]
    assert all(r.get("slow") for r in logs)
    assert all(
        r.get("body_sha256") and r.get("body_bytes") for r in post_logs
    )
    import hashlib

    want = hashlib.sha256(
        json.dumps({"vertices": [1, 2]}).encode()
    ).hexdigest()
    assert post_logs[0]["body_sha256"] == want
    assert validate_records(sink.records) == []


def test_statusz_emits_schema_valid_slo_rollup(tmp_path):
    sink = MetricsSink(tracer=Tracer())
    store = _publish_base(tmp_path, sink=sink)
    server = SnapshotServer(store, sink=sink)
    host, port = server.start()
    try:
        _get_json(host, port, "/healthz")
        _get_json(host, port, "/statusz")
    finally:
        server.stop()
    rollups = [r for r in sink.records if r["phase"] == "slo_rollup"]
    assert len(rollups) == 1
    assert {"uptime_s", "endpoints", "repair_debt"} <= set(rollups[0])
    assert "healthz" in rollups[0]["endpoints"]
    assert validate_records(sink.records) == []


def test_refused_delta_abandons_debt_without_double_drain(tmp_path):
    """A delta the ingestor refuses (a snapshot whose weights column is
    misaligned with its edge arrays — the loud damaged-store refusal)
    must drain its OWN pending entry and nothing else — /healthz on a
    drained queue reports zero backlog, and no phantom apply is
    counted."""
    src, dst, v = _community_graph()
    g = build_graph(src, dst, num_vertices=v)
    labels, cc, _ = cold_recompute(g)
    store = SnapshotStore(str(tmp_path / "snap"))
    store.publish(
        {
            "src": src, "dst": dst, "labels": labels, "cc_labels": cc,
            "weights": np.ones(len(src) - 3, np.float32),
        },
        fingerprint=graph_fingerprint(src, dst),
    )
    server = SnapshotServer(store)
    host, port = server.start()
    try:
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(host, port, "/delta", {"insert": [[1, 2]]})
        assert e.value.code == 400
        hz, _ = _get_json(host, port, "/healthz")
        assert hz["repair_debt_rows"] == 0 and hz["ingest_lag_s"] == 0.0
    finally:
        server.stop()
    snap = server.debt.snapshot()
    assert snap["pending_deltas"] == 0
    assert snap["applies_warm"] + snap["applies_cold"] == 0


def test_client_disconnect_records_499_not_success(tmp_path, monkeypatch):
    """A reply the client never received must not count as a served
    2xx: a dead-socket write (BrokenPipeError) records as 499 and shows
    up in the endpoint's error rate — impatient clients are exactly the
    tail signal the SLO page exists to surface."""
    from graphmine_tpu.serve import server as server_mod

    def dead_socket(self, url):
        self._status = 200  # the write "succeeded" right up to the pipe
        raise BrokenPipeError("client went away")

    monkeypatch.setattr(server_mod._Handler, "_ep_snapshot", dead_socket)
    store = _publish_base(tmp_path)
    server = SnapshotServer(store)
    host, port = server.start()
    try:
        with pytest.raises(Exception):  # noqa: B017 — empty reply, any client error
            _get(host, port, "/snapshot")
        # the server-side ledger saw the failure, and stayed up
        _get_json(host, port, "/healthz")
    finally:
        server.stop()
    eps = server.endpoint_latency()
    assert eps["snapshot"]["count"] == 1
    assert eps["snapshot"]["errors"] == 1
    assert eps["healthz"]["errors"] == 0


def test_sink_max_records_bounds_memory_without_losing_stream(tmp_path):
    """The long-lived-server memory bound: with max_records set, the
    in-memory list stays capped while the JSONL stream keeps every
    record, and finalize neither re-appends survivors nor duplicates
    streamed records."""
    stream = tmp_path / "m.jsonl"
    sink = MetricsSink(
        stream_path=str(stream), tracer=Tracer(), max_records=10
    )
    for i in range(50):
        sink.emit("heartbeat", uptime_s=float(i))
    assert len(sink.records) == 10
    assert sink.records[0]["uptime_s"] == 40.0  # oldest were dropped
    sink.finalize(str(stream))
    lines = [
        json.loads(ln) for ln in stream.read_text().splitlines() if ln
    ]
    assert len(lines) == 50  # disk kept everything, exactly once
    assert [r["uptime_s"] for r in lines] == [float(i) for i in range(50)]


def test_sinkless_server_still_serves_metrics(tmp_path):
    """A server with no record sink still has the full metric surface:
    /metrics and /statusz work off its private registry."""
    store = _publish_base(tmp_path)
    server = SnapshotServer(store)
    host, port = server.start()
    try:
        _get_json(host, port, "/healthz")
        body, _ = _get(host, port, "/metrics")
        text = body.decode()
        assert "# TYPE graphmine_serve_request_seconds histogram" in text
        assert "# TYPE graphmine_serve_snapshot_version gauge" in text
        _assert_untorn(text)
        sz, _ = _get_json(host, port, "/statusz")
        assert sz["endpoints"]["healthz"]["count"] == 1
    finally:
        server.stop()
