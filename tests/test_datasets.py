"""R-MAT generator + scale ladder + anomaly-injection AUROC harness."""

import numpy as np
import pytest

from graphmine_tpu.datasets import (
    LADDER,
    inject_structural_anomalies,
    load,
    planted_anomaly_graph,
    rmat,
)


def test_rmat_shapes_and_ranges():
    src, dst = rmat(10, edge_factor=8, seed=3)
    v, e = 1 << 10, 8 << 10
    assert src.shape == dst.shape == (e,)
    assert src.dtype == dst.dtype == np.int32
    assert src.min() >= 0 and src.max() < v
    assert dst.min() >= 0 and dst.max() < v


def test_rmat_power_law_skew():
    # skewed quadrants must concentrate degree far beyond a uniform graph
    src, _ = rmat(12, edge_factor=16, seed=0)
    deg = np.bincount(src, minlength=1 << 12)
    uniform_max = 16 * 3  # ~Poisson(16) tail bound
    assert deg.max() > 4 * uniform_max
    # uniform quadrants ~ Erdos-Renyi: no such hub
    usrc, _ = rmat(12, edge_factor=16, a=0.25, b=0.25, c=0.25, seed=0)
    udeg = np.bincount(usrc, minlength=1 << 12)
    assert udeg.max() < deg.max() / 3


def test_rmat_determinism_and_dedup():
    a = rmat(8, 4, seed=7)
    b = rmat(8, 4, seed=7)
    np.testing.assert_array_equal(a[0], b[0])
    np.testing.assert_array_equal(a[1], b[1])
    ds, dd = rmat(8, 4, seed=7, dedup=True)
    pairs = set(zip(ds.tolist(), dd.tolist()))
    assert len(pairs) == len(ds) <= len(a[0])


def test_ladder_load_synthetic():
    et = load("ego-facebook", data_dir="/nonexistent", max_scale=10)
    assert et.num_edges > 0 and et.num_vertices <= 1 << 10
    with pytest.raises(KeyError):
        load("not-a-rung")
    assert set(LADDER) == {
        "ego-facebook", "com-amazon", "com-livejournal", "twitter-2010"
    }


def test_anomaly_injection_auroc_end_to_end():
    """The BASELINE.json second metric: LOF AUROC on injected outliers."""
    from graphmine_tpu.graph.container import build_graph
    from graphmine_tpu.ops.features import standardize, vertex_features
    from graphmine_tpu.ops.lof import auroc, lof_scores
    from graphmine_tpu.ops.lpa import label_propagation

    src, dst = rmat(10, edge_factor=12, seed=1)
    v = 1 << 10
    src, dst, truth = inject_structural_anomalies(
        src, dst, v, num_anomalies=12, edges_per_anomaly=40, seed=2
    )
    g = build_graph(src, dst, num_vertices=v)
    labels = label_propagation(g, max_iter=5)
    feats = standardize(vertex_features(g, labels))
    scores = np.asarray(lof_scores(feats, k=15))
    assert auroc(scores, truth) > 0.8


def test_planted_anomaly_graph_contract():
    v, e = 4096, 120_000
    src, dst, mask, comm = planted_anomaly_graph(v, e, seed=7)
    assert src.dtype == dst.dtype == np.int32
    assert len(src) == len(dst) >= e  # anomaly edges appended
    assert src.min() >= 0 and src.max() < v
    assert dst.min() >= 0 and dst.max() < v
    assert mask.shape == (v,) and mask.dtype == bool and mask.sum() >= 32
    assert comm.shape == (v,) and comm.max() >= 7
    # deterministic in the seed
    src2, dst2, mask2, _ = planted_anomaly_graph(v, e, seed=7)
    np.testing.assert_array_equal(src, src2)
    np.testing.assert_array_equal(mask, mask2)


def test_planted_anomaly_graph_detects_end_to_end():
    """The e2e dataset's reason to exist (VERDICT r5 weak 1): every timed
    detection chapter produces NONZERO output on it — a long-tailed LPA
    census, populated recursive deciles with flagged vertices, and LOF
    separating the injected anomalies — at CI scale, same knobs as the
    bench tier."""
    from graphmine_tpu.graph.container import build_graph
    from graphmine_tpu.ops.lof import auroc, lof_scores
    from graphmine_tpu.ops.features import standardize, vertex_features
    from graphmine_tpu.ops.lpa import label_propagation, num_communities
    from graphmine_tpu.ops.outliers import recursive_lpa_outliers

    v, e = 4096, 200_000
    src, dst, truth, _ = planted_anomaly_graph(v, e, seed=9)
    g = build_graph(src, dst, num_vertices=v)
    labels = label_propagation(g, max_iter=5)
    assert int(num_communities(labels)) > 100  # long-tailed, not 3 giants
    rep = recursive_lpa_outliers(g, labels)
    assert int(rep.outlier_vertices.sum()) > 0
    assert len(rep.thresholds) >= 10  # >= 10 parents with populated deciles
    feats = standardize(vertex_features(g, labels))
    lof = np.asarray(lof_scores(feats, k=128))
    assert int((lof > 1.5).sum()) > 0
    assert auroc(lof, truth) > 0.9
