"""Pallas kernel tests — interpreter mode on the virtual-CPU harness.

The XLA implementations are the oracles (SURVEY §4: algorithm-semantics
tests against independent references). Inputs are constructed tie-free so
index agreement is exact.
"""

import numpy as np
import pytest

from graphmine_tpu.ops.knn import _knn_xla
from graphmine_tpu.pallas_kernels.knn_pallas import knn_pallas


def _tie_free_points(n, f, seed=0):
    rng = np.random.default_rng(seed)
    return rng.normal(size=(n, f)).astype(np.float32)


@pytest.mark.parametrize("n,f,k", [(200, 8, 5), (513, 3, 20), (1024, 40, 32)])
def test_knn_pallas_matches_xla(n, f, k):
    pts = _tie_free_points(n, f)
    d_ref, i_ref = _knn_xla(pts, k=k, row_tile=256)
    d_pal, i_pal = knn_pallas(pts, k=k, row_tile=128, col_tile=256, interpret=True)
    np.testing.assert_array_equal(np.asarray(i_pal), np.asarray(i_ref))
    np.testing.assert_allclose(np.asarray(d_pal), np.asarray(d_ref), rtol=1e-4, atol=1e-5)


def test_knn_pallas_ascending_and_self_excluded():
    pts = _tie_free_points(300, 6, seed=3)
    d, i = knn_pallas(pts, k=10, row_tile=128, col_tile=128, interpret=True)
    d = np.asarray(d)
    i = np.asarray(i)
    assert (np.diff(d, axis=1) >= 0).all()
    assert (i != np.arange(300)[:, None]).all()
    assert ((i >= 0) & (i < 300)).all()


def test_knn_pallas_padding_rows_masked():
    # n deliberately far from the tile grid: padded rows/cols must not leak.
    pts = _tie_free_points(130, 4, seed=1)
    d_ref, i_ref = _knn_xla(pts, k=3)
    d_pal, i_pal = knn_pallas(pts, k=3, row_tile=128, col_tile=128, interpret=True)
    np.testing.assert_array_equal(np.asarray(i_pal), np.asarray(i_ref))


def test_lof_pallas_impl_matches_xla():
    from graphmine_tpu.ops.lof import lof_scores

    pts = _tie_free_points(400, 5, seed=2)
    # interpret-mode pallas isn't reachable through the public impl flag on
    # CPU, so compare the two knn paths feeding identical LOF math instead.
    d_x, i_x = _knn_xla(pts, k=15)
    d_p, i_p = knn_pallas(pts, k=15, interpret=True)
    np.testing.assert_array_equal(np.asarray(i_p), np.asarray(i_x))
    s = np.asarray(lof_scores(pts, k=15, impl="xla"))
    assert s.shape == (400,) and np.isfinite(s).all()
