"""Resilience suite: every recovery path exercised on CPU via the
deterministic fault injector (:mod:`graphmine_tpu.testing.faults`).

Acceptance matrix (ISSUE 1), all end-to-end through ``run_pipeline``:
  (a) a transient device error is retried and the run completes with
      labels identical to the no-fault run;
  (b) an injected OOM triggers a recorded degradation (fused kernel →
      sort-based superstep) and still completes;
  (c) a corrupted checkpoint rolls back to the last good generation;
  (d) simulated preemption mid-LPA resumes to the same final labels;
plus unit coverage of the taxonomy/backoff/watchdog primitives, the
graph-fingerprint refusal, and ingestion-quarantine accounting — and
every recovery decision asserted as a structured MetricsSink record.
"""

import os

import numpy as np
import pytest

from graphmine_tpu.pipeline import checkpoint as ckpt
from graphmine_tpu.pipeline import resilience
from graphmine_tpu.pipeline.config import PipelineConfig
from graphmine_tpu.pipeline.metrics import MetricsSink
from graphmine_tpu.pipeline.resilience import (
    DEGRADABLE,
    FATAL,
    RETRYABLE,
    ResilienceConfig,
    RetriesExhausted,
    SuperstepTimeout,
    classify_error,
    run_phase,
    run_with_watchdog,
)
from graphmine_tpu.testing import faults

pytestmark = pytest.mark.faults


# ---------------------------------------------------------------------------
# error taxonomy
# ---------------------------------------------------------------------------


def test_classify_error_taxonomy():
    assert classify_error(RuntimeError("UNAVAILABLE: socket closed")) == RETRYABLE
    assert classify_error(RuntimeError("DEADLINE_EXCEEDED: rpc")) == RETRYABLE
    assert classify_error(ConnectionResetError("peer")) == RETRYABLE
    assert classify_error(
        RuntimeError("RESOURCE_EXHAUSTED: Out of memory allocating 1 bytes")
    ) == DEGRADABLE
    assert classify_error(MemoryError()) == DEGRADABLE
    # degradable wins when an OOM status also mentions transport noise
    assert classify_error(
        RuntimeError("RESOURCE_EXHAUSTED: OOM; socket closed while spilling")
    ) == DEGRADABLE
    assert classify_error(ValueError("bad config")) == FATAL
    assert classify_error(KeyError("x")) == FATAL

    # the explicit protocol attribute beats message sniffing
    e = RuntimeError("UNAVAILABLE: looks transient")
    e.graphmine_error_class = FATAL
    assert classify_error(e) == FATAL

    # the injected fault types classify through the REAL classifier
    assert classify_error(faults.transient_error()) == RETRYABLE
    assert classify_error(faults.oom_error()) == DEGRADABLE
    assert classify_error(faults.preemption()) == FATAL


def test_resilience_config_validation():
    ResilienceConfig().validate()
    with pytest.raises(ValueError):
        ResilienceConfig(max_retries=-1).validate()
    with pytest.raises(ValueError):
        ResilienceConfig(jitter=1.5).validate()
    with pytest.raises(ValueError):
        ResilienceConfig(superstep_timeout_s=0).validate()
    with pytest.raises(ValueError):
        ResilienceConfig(degradation="maybe").validate()


def test_backoff_is_exponential_and_capped():
    import random

    pol = ResilienceConfig(backoff_base_s=0.1, backoff_max_s=0.4, jitter=0.0)
    rng = random.Random(0)
    delays = [resilience.backoff_s(pol, n, rng) for n in (1, 2, 3, 4, 5)]
    assert delays == [0.1, 0.2, 0.4, 0.4, 0.4]  # doubles, then caps
    # jitter stays within the documented band
    pol_j = ResilienceConfig(backoff_base_s=0.1, backoff_max_s=10.0, jitter=0.5)
    for n in range(1, 6):
        d = resilience.backoff_s(pol_j, n, random.Random(n))
        base = 0.1 * 2 ** (n - 1)
        assert base * 0.5 <= d <= base * 1.5


# ---------------------------------------------------------------------------
# run_phase: retry / degrade / fatal
# ---------------------------------------------------------------------------


def _no_sleep(_):
    pass


def test_run_phase_retries_transient_then_succeeds():
    m = MetricsSink()
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise faults.transient_error()
        return "ok"

    out = run_phase("p", flaky, ResilienceConfig(max_retries=3), m,
                    sleep=_no_sleep)
    assert out == "ok" and calls["n"] == 3
    retries = m.of_phase("retry")
    assert [r["attempt"] for r in retries] == [1, 2]
    assert all(r["stage"] == "p" and r["backoff_s"] >= 0 for r in retries)


def test_run_phase_exhausts_retry_budget():
    m = MetricsSink()

    def always():
        raise faults.transient_error()

    with pytest.raises(RetriesExhausted) as ei:
        run_phase("p", always, ResilienceConfig(max_retries=2), m,
                  sleep=_no_sleep)
    assert isinstance(ei.value.__cause__, faults.InjectedTransientError)
    assert m.of_phase("retries_exhausted")[0]["attempts"] == 3
    assert len(m.of_phase("retry")) == 2


def test_retry_budget_is_per_incident_not_per_lifetime():
    """A long-running phase that makes progress between transient
    failures gets a fresh budget per incident — three recovered blips
    across a run must not kill it (each incident stays bounded)."""
    m = MetricsSink()
    state = {"it": 0}
    fail_at = {2, 5, 8}  # independent incidents, progress in between

    def runner():
        while state["it"] < 10:
            if state["it"] in fail_at:
                fail_at.discard(state["it"])
                raise faults.transient_error()
            state["it"] += 1
        return "done"

    out = run_phase("p", runner, ResilienceConfig(max_retries=1), m,
                    sleep=_no_sleep, progress=lambda: state["it"])
    assert out == "done"
    assert len(m.of_phase("retry")) == 3
    # every incident restarted its budget: attempt is always 1
    assert all(r["attempt"] == 1 for r in m.of_phase("retry"))

    # without progress, the same schedule exhausts the lifetime budget
    state2 = {"it": 0}
    fail2 = {2, 5, 8}

    def runner2():
        while state2["it"] < 10:
            if state2["it"] in fail2:
                fail2.discard(state2["it"])
                raise faults.transient_error()
            state2["it"] += 1
        return "done"

    with pytest.raises(RetriesExhausted):
        run_phase("p", runner2, ResilienceConfig(max_retries=1),
                  MetricsSink(), sleep=_no_sleep)


def test_run_phase_fatal_raises_immediately():
    m = MetricsSink()
    calls = {"n": 0}

    def bug():
        calls["n"] += 1
        raise ValueError("a bug, not weather")

    with pytest.raises(ValueError):
        run_phase("p", bug, ResilienceConfig(max_retries=5), m, sleep=_no_sleep)
    assert calls["n"] == 1 and not m.of_phase("retry")


def test_run_phase_walks_degradation_ladder():
    m = MetricsSink()

    def big():
        raise faults.oom_error()

    out = run_phase(
        "p", big, ResilienceConfig(), m,
        ladder=(("smaller", lambda: "degraded-ok"),), sleep=_no_sleep,
    )
    assert out == "degraded-ok"
    deg = m.of_phase("degrade")
    assert deg and deg[0]["to"] == "smaller" and deg[0]["depth"] == 1

    # ladder exhausted -> the degradable error surfaces
    with pytest.raises(faults.InjectedOOM):
        run_phase("p", big, ResilienceConfig(), MetricsSink(), sleep=_no_sleep)

    # degradation="off" surfaces the OOM without touching the ladder
    with pytest.raises(faults.InjectedOOM):
        run_phase(
            "p", big, ResilienceConfig(degradation="off"), MetricsSink(),
            ladder=(("smaller", lambda: "nope"),), sleep=_no_sleep,
        )


def test_run_phase_rung_is_retried_on_transient():
    """Each ladder rung gets its own transient-retry protection."""
    m = MetricsSink()
    calls = {"n": 0}

    def rung():
        calls["n"] += 1
        if calls["n"] == 1:
            raise faults.transient_error()
        return "ok"

    out = run_phase(
        "p", lambda: (_ for _ in ()).throw(faults.oom_error()),
        ResilienceConfig(max_retries=1), m,
        ladder=(("rung", rung),), sleep=_no_sleep,
    )
    assert out == "ok" and calls["n"] == 2
    assert m.of_phase("degrade") and m.of_phase("retry")


# ---------------------------------------------------------------------------
# watchdog
# ---------------------------------------------------------------------------


def test_watchdog_passthrough_and_errors():
    m = MetricsSink()
    assert run_with_watchdog("p", lambda: 42, 5.0, m) == 42
    assert run_with_watchdog("p", lambda: 42, None, m) == 42  # inline, no thread
    with pytest.raises(ValueError):
        run_with_watchdog("p", lambda: (_ for _ in ()).throw(ValueError("x")),
                          5.0, m)
    assert not m.of_phase("watchdog_timeout")


def test_watchdog_times_out_and_checkpoints():
    import time

    m = MetricsSink()
    fired = []
    with pytest.raises(SuperstepTimeout, match="was checkpointed"):
        run_with_watchdog(
            "p", lambda: time.sleep(1.5), 0.1, m,
            on_timeout=lambda: fired.append(True),
        )
    assert fired == [True]
    rec = m.of_phase("watchdog_timeout")
    assert rec and rec[0]["timeout_s"] == 0.1 and rec[0]["checkpointed"]


def test_watchdog_without_hook_does_not_claim_a_checkpoint():
    import time

    m = MetricsSink()
    with pytest.raises(SuperstepTimeout, match="NO checkpoint hook"):
        run_with_watchdog("p", lambda: time.sleep(1.5), 0.1, m)
    assert m.of_phase("watchdog_timeout")[0]["checkpointed"] is False


def test_watchdog_survives_a_failing_checkpoint_hook():
    """A failing save (disk full) must not suppress the timeout — the
    hang is the root cause — and the record must not claim a checkpoint."""
    import time

    m = MetricsSink()

    def bad_save():
        raise OSError("No space left on device")

    with pytest.raises(SuperstepTimeout, match="hook FAILED") as ei:
        run_with_watchdog("p", lambda: time.sleep(1.5), 0.1, m,
                          on_timeout=bad_save)
    assert isinstance(ei.value.__cause__, OSError)
    assert m.of_phase("watchdog_timeout")[0]["checkpointed"] is False


# ---------------------------------------------------------------------------
# fault injector mechanics
# ---------------------------------------------------------------------------


def test_fault_injector_is_deterministic():
    inj = faults.FaultInjector()
    inj.add("s", faults.transient_error, at=2)
    inj.add("s", faults.oom_error, at=4, repeat=2)
    seen = []
    with inj.installed():
        for i in range(1, 7):
            try:
                resilience.fault_point("s", i=i)
                seen.append("ok")
            except faults.InjectedTransientError:
                seen.append("transient")
            except faults.InjectedOOM:
                seen.append("oom")
    assert seen == ["ok", "transient", "ok", "oom", "oom", "ok"]
    assert inj.fired("s") == 3 and inj.fired() == 3
    assert [ctx["i"] for (_, _, ctx) in inj.log] == [1, 2, 3, 4, 5, 6]
    # uninstalled: the seam is inert again
    resilience.fault_point("s", i=99)
    assert len(inj.log) == 6


def test_file_corruptors(tmp_path):
    p = tmp_path / "f.bin"
    p.write_bytes(bytes(range(200)))
    faults.corrupt_file(str(p), offset=-10, nbytes=4)
    data = p.read_bytes()
    assert len(data) == 200 and data[:190] == bytes(range(190))
    assert data[190:194] != bytes(range(190, 194))
    faults.truncate_file(str(p), keep_fraction=0.5)
    assert p.stat().st_size == 100
    with pytest.raises(ValueError):
        faults.truncate_file(str(p), keep_fraction=1.5)


# ---------------------------------------------------------------------------
# checkpoint hardening (API level)
# ---------------------------------------------------------------------------


def test_save_labels_is_atomic_and_rotates(tmp_path):
    d = str(tmp_path)
    lbl1 = np.arange(10, dtype=np.int32)
    path = ckpt.save_labels(d, lbl1, 1)
    assert not [f for f in os.listdir(d) if ".tmp" in f]  # no tmp debris
    ckpt.save_labels(d, lbl1 + 1, 2)
    # previous generation rotated aside, current is the new save
    labels, it = ckpt.load_labels(d)
    assert it == 2
    prev = path[: -len(".npz")] + ".prev.npz"
    assert os.path.exists(prev)


@pytest.mark.parametrize("damage", [faults.corrupt_file,
                                    lambda p: faults.truncate_file(p, 0.3)])
def test_corrupt_checkpoint_rolls_back(tmp_path, damage):
    d = str(tmp_path)
    good = np.arange(32, dtype=np.int32) % 7
    ckpt.save_labels(d, good, 3)
    ckpt.save_labels(d, good * 0, 4)  # current generation, to be damaged
    damage(os.path.join(d, "lpa_labels.npz"))
    m = MetricsSink()
    labels, it = ckpt.load_labels(d, sink=m)
    np.testing.assert_array_equal(labels, good)
    assert it == 3
    assert m.of_phase("checkpoint_rollback") and m.of_phase("checkpoint_rollback_ok")
    # the good generation was promoted back to the current slot
    labels2, it2 = ckpt.load_labels(d)
    assert it2 == 3
    # the condemned file is preserved for forensics, not destroyed (the
    # corruption verdict may stem from a transient read error)
    assert os.path.exists(os.path.join(d, "lpa_labels.npz.corrupt"))


def test_both_generations_corrupt_is_a_clean_failure(tmp_path):
    d = str(tmp_path)
    ckpt.save_labels(d, np.arange(8, dtype=np.int32), 1)
    ckpt.save_labels(d, np.arange(8, dtype=np.int32), 2)
    faults.corrupt_file(os.path.join(d, "lpa_labels.npz"))
    faults.corrupt_file(os.path.join(d, "lpa_labels.prev.npz"))
    with pytest.raises(ckpt.CheckpointCorruptionError, match="both"):
        ckpt.load_labels(d)


def test_unrecoverable_corruption_emits_no_rollback_record(tmp_path):
    """A corrupt sole generation (nothing to roll back TO) must not leave
    a checkpoint_rollback record claiming a recovery that never ran."""
    d = str(tmp_path)
    ckpt.save_labels(d, np.arange(8, dtype=np.int32), 1)
    faults.corrupt_file(os.path.join(d, "lpa_labels.npz"))
    m = MetricsSink()
    with pytest.raises(ckpt.CheckpointCorruptionError, match="no\\s+previous"):
        ckpt.load_labels(d, sink=m)
    assert not m.of_phase("checkpoint_rollback")


def test_checksum_catches_internally_consistent_rewrite(tmp_path):
    """Damage that re-zips cleanly (valid CRCs, wrong content) is still
    caught by the embedded state checksum."""
    d = str(tmp_path)
    ckpt.save_labels(d, np.arange(8, dtype=np.int32), 1)
    ckpt.save_labels(d, np.arange(8, dtype=np.int32), 2)
    path = os.path.join(d, "lpa_labels.npz")
    with np.load(path) as z:
        state = {k: z[k] for k in z.files}
    state["labels"] = state["labels"] + 1  # silent bit damage, then re-save
    np.savez(path, **state)
    m = MetricsSink()
    labels, it = ckpt.load_labels(d, sink=m)
    assert it == 1  # rolled back past the forged file
    assert "checksum" in m.of_phase("checkpoint_rollback")[0]["error"]


# ---------------------------------------------------------------------------
# end-to-end through the driver (8 virtual CPU devices via conftest)
# ---------------------------------------------------------------------------

_E2E = {}


def _edgelist_path() -> str:
    """Small deterministic graph shared by every e2e test: two planted
    communities plus random cross edges — enough structure that LPA takes
    several supersteps (checkpoint/retry boundaries to inject at)."""
    if "path" not in _E2E:
        from conftest import cached_edgelist

        rng = np.random.default_rng(7)
        v, e = 160, 800
        src = rng.integers(0, v, e)
        # bias edges to stay within each half: two communities
        dst = (src + rng.integers(1, v // 2, e)) % (v // 2) + (src // (v // 2)) * (v // 2)
        cross = rng.random(e) < 0.05
        dst = np.where(cross, rng.integers(0, v, e), dst)
        text = "".join(f"{s} {t}\n" for s, t in zip(src, dst))
        _E2E["path"] = cached_edgelist("graphmine_resilience", text)
    return _E2E["path"]


def _cfg(**kw):
    base = dict(
        data_path=_edgelist_path(), data_format="edgelist",
        outlier_method="none", num_devices=1, max_iter=5,
        resilience=ResilienceConfig(backoff_base_s=0.001, backoff_max_s=0.01),
    )
    base.update(kw)
    return PipelineConfig(**base)


def _baseline_labels():
    if "labels" not in _E2E:
        from graphmine_tpu.pipeline.driver import run_pipeline

        _E2E["labels"] = run_pipeline(_cfg()).labels
    return _E2E["labels"]


def test_transient_error_is_retried_to_identical_labels():
    """(a): transient device weather at superstep 2 AND at ingestion —
    both retried, final labels byte-identical to the no-fault run."""
    from graphmine_tpu.pipeline.driver import run_pipeline

    inj = faults.FaultInjector()
    inj.add("load", faults.transient_error, at=1)
    inj.add("lpa_superstep", faults.transient_error, at=2)
    with inj.installed():
        res = run_pipeline(_cfg())
    assert inj.fired() == 2
    np.testing.assert_array_equal(res.labels, _baseline_labels())
    retries = res.metrics.of_phase("retry")
    assert {r["stage"] for r in retries} == {"load", "lpa"}


def test_oom_triggers_recorded_degradation_and_completes():
    """(b): OOM at superstep 2 on the fused single-device kernel — the
    planner's ladder steps down to the sort-based superstep, the run
    completes from the last good state, labels still match."""
    from graphmine_tpu.pipeline.driver import run_pipeline

    inj = faults.FaultInjector()
    inj.add("lpa_superstep", faults.oom_error, at=2)
    with inj.installed():
        res = run_pipeline(_cfg())
    np.testing.assert_array_equal(res.labels, _baseline_labels())
    deg = res.metrics.of_phase("degrade")
    assert deg and deg[0]["stage"] == "lpa" and deg[0]["to"] == "single_sort"
    # supersteps resumed, not restarted: 5 good iterations exactly
    iters = [r["iteration"] for r in res.metrics.of_phase("lpa_iter")]
    assert iters == [1, 2, 3, 4, 5]


def test_corrupted_checkpoint_rolls_back_e2e(tmp_path):
    """(c): the current checkpoint generation is corrupted on disk; resume
    rolls back to the previous good generation and converges to the same
    labels, emitting checkpoint_rollback records."""
    from graphmine_tpu.pipeline.driver import run_pipeline

    ck = str(tmp_path / "ck")
    run_pipeline(_cfg(checkpoint_dir=ck))  # saves every superstep
    faults.corrupt_file(os.path.join(ck, "lpa_labels.npz"))
    res = run_pipeline(_cfg(checkpoint_dir=ck, resume=True))
    np.testing.assert_array_equal(res.labels, _baseline_labels())
    assert res.metrics.of_phase("checkpoint_rollback")
    ok = res.metrics.of_phase("checkpoint_rollback_ok")
    assert ok and ok[0]["iteration"] == 4  # prev generation = superstep 4
    resume = res.metrics.of_phase("resume")
    assert resume and resume[0]["iteration"] == 4


def test_preemption_mid_lpa_resumes_to_same_labels(tmp_path):
    """(d): a simulated preemption kills the run at superstep 3 (fatal by
    contract — no in-process retry); a NEW run with --resume picks up from
    the checkpoint and lands on identical final labels."""
    from graphmine_tpu.pipeline.driver import run_pipeline

    ck = str(tmp_path / "ck")
    inj = faults.FaultInjector()
    inj.add("lpa_superstep", faults.preemption, at=3)
    with inj.installed():
        with pytest.raises(faults.SimulatedPreemption):
            run_pipeline(_cfg(checkpoint_dir=ck))
    # no retry was attempted on the fatal error
    saved = ckpt.load_labels(ck)
    assert saved is not None and saved[1] == 2  # last good superstep
    res = run_pipeline(_cfg(checkpoint_dir=ck, resume=True))
    np.testing.assert_array_equal(res.labels, _baseline_labels())
    resume = res.metrics.of_phase("resume")
    assert resume and resume[0]["iteration"] == 2


def test_hung_superstep_checkpoints_then_aborts(tmp_path):
    """Watchdog contract: a hung superstep trips the timeout, the LAST
    GOOD labels are checkpointed before SuperstepTimeout surfaces, and a
    resumed run completes identically."""
    from graphmine_tpu.pipeline.driver import run_pipeline

    ck = str(tmp_path / "ck")
    inj = faults.FaultInjector()
    inj.add("lpa_superstep", faults.hang(3.0), at=2)
    cfg = _cfg(
        checkpoint_dir=ck, checkpoint_every=10,  # only the watchdog saves
        resilience=ResilienceConfig(
            backoff_base_s=0.001, superstep_timeout_s=0.3
        ),
    )
    with inj.installed():
        with pytest.raises(SuperstepTimeout):
            run_pipeline(cfg)
    saved = ckpt.load_labels(ck)
    assert saved is not None and saved[1] == 1  # superstep before the hang
    res = run_pipeline(_cfg(checkpoint_dir=ck, resume=True))
    np.testing.assert_array_equal(res.labels, _baseline_labels())
    assert res.metrics.of_phase("resume")


def test_fingerprint_mismatch_refuses_resume(tmp_path):
    """Satellite: resuming against a permuted or reweighted edge set must
    refuse with an actionable error, never silently relabel."""
    from graphmine_tpu.pipeline.driver import run_pipeline

    ck = str(tmp_path / "ck")
    run_pipeline(_cfg(checkpoint_dir=ck, max_iter=2))

    # permuted edge order => different id assignment => refuse
    lines = open(_edgelist_path()).readlines()
    permuted = tmp_path / "permuted.txt"
    permuted.write_text("".join(reversed(lines)))
    with pytest.raises(ckpt.FingerprintMismatch, match="different graph"):
        run_pipeline(_cfg(
            data_path=str(permuted), checkpoint_dir=ck, resume=True,
        ))

    # same topology, reweighted => different trajectory => refuse
    weighted = tmp_path / "weighted.txt"
    weighted.write_text("".join(
        f"{ln.rstrip()} {1.0 + i % 3}\n" for i, ln in enumerate(lines)
    ))
    with pytest.raises(ckpt.FingerprintMismatch):
        run_pipeline(_cfg(
            data_path=str(weighted), edge_weight_col=2,
            checkpoint_dir=ck, resume=True,
        ))


# ---------------------------------------------------------------------------
# ingestion quarantine
# ---------------------------------------------------------------------------


def test_quarantine_bad_rows_and_nan_weights(tmp_path):
    """Malformed rows and non-finite weights are counted and set aside;
    the run completes and the counts surface as a quarantine record."""
    from graphmine_tpu.pipeline.driver import run_pipeline

    p = tmp_path / "dirty.txt"
    p.write_text(
        "a b 1.0\n"
        "b c 2.0\n"
        "c a 1.5\n"
        "d\n"                # too few columns -> bad_rows
        "e f not-a-float\n"  # unparseable weight -> bad_rows
        "x y 4.0\n"
        "y z nan\n"          # parseable but non-finite -> nan_weights
        "z x inf\n"          # idem
        "x z 2.0\n"
    )
    cfg = PipelineConfig(
        data_path=str(p), data_format="edgelist", edge_weight_col=2,
        outlier_method="none", num_devices=1, max_iter=3,
    )
    res = run_pipeline(cfg)
    et = res.edge_table
    assert et.quarantine == {"bad_rows": 2, "nan_weights": 2}
    assert et.num_edges == 5  # 9 rows - 2 bad - 2 non-finite
    q = res.metrics.of_phase("quarantine")
    assert q and q[0]["bad_rows"] == 2 and q[0]["nan_weights"] == 2


def test_mojibake_ids_stay_distinct(tmp_path):
    """Invalid byte sequences in vertex ids must not coalesce distinct
    vertices: 'a\\xff' and 'a\\xfe' decode to distinct ids under the
    tolerant parser (errors='replace' would merge both into 'a\\ufffd')."""
    from graphmine_tpu.io.edges import load_edge_list

    p = tmp_path / "moji.txt"
    p.write_bytes(b"a\xff b\nc\n" + b"a\xfe b\n")  # bad row forces tolerant
    et = load_edge_list(str(p), quarantine=True)
    assert et.quarantine == {"bad_rows": 1}
    assert et.num_edges == 2
    assert et.num_vertices == 3  # a\xff, b, a\xfe — NOT 2


def test_metrics_out_writes_recovery_records(tmp_path):
    """--metrics-out flushes every structured record (including recovery
    events) as JSON lines for offline triage."""
    import json

    from graphmine_tpu.pipeline.driver import run_pipeline

    out = str(tmp_path / "metrics.jsonl")
    inj = faults.FaultInjector()
    inj.add("lpa_superstep", faults.transient_error, at=2)
    with inj.installed():
        run_pipeline(_cfg(metrics_out=out))
    recs = [json.loads(ln) for ln in open(out)]
    phases = {r["phase"] for r in recs}
    assert "retry" in phases and "lpa_iter" in phases and "counts" in phases

    # a FAILED run still flushes: the triage data must survive the death
    # it is meant to explain
    out2 = str(tmp_path / "failed.jsonl")
    inj2 = faults.FaultInjector()
    inj2.add("lpa_superstep", faults.preemption, at=3)
    with inj2.installed():
        with pytest.raises(faults.SimulatedPreemption):
            run_pipeline(_cfg(metrics_out=out2))
    recs2 = [json.loads(ln) for ln in open(out2)]
    assert {r["phase"] for r in recs2} >= {"counts", "lpa_iter"}


def test_quarantine_preserves_clean_fast_path(tmp_path):
    """A well-formed file through quarantine mode ingests identically to
    strict mode (same ids, same edges) and records zero bad rows."""
    from graphmine_tpu.io.edges import load_edge_list

    p = tmp_path / "clean.txt"
    p.write_text("a b\nb c\nc a\n")
    strict = load_edge_list(str(p))
    tolerant = load_edge_list(str(p), quarantine=True)
    np.testing.assert_array_equal(strict.src, tolerant.src)
    np.testing.assert_array_equal(strict.dst, tolerant.dst)
    assert tolerant.quarantine == {"bad_rows": 0}


def test_quarantine_does_not_mask_misconfiguration(tmp_path):
    """A mistyped weight_col on a CLEAN file would tolerantly quarantine
    every row into an empty graph — that wholesale disagreement must
    surface as the configuration error it is."""
    from graphmine_tpu.io.edges import load_edge_list

    p = tmp_path / "clean.txt"
    p.write_text("a b\nb c\nc a\n")
    with pytest.raises(ValueError, match="misconfiguration"):
        load_edge_list(str(p), weight_col=5, quarantine=True)


def test_quarantine_out_of_range_ids():
    from graphmine_tpu.io.edges import from_arrays

    et = from_arrays(
        [0, 1, 2, -1, 5], [1, 2, 0, 0, 0],
        names=["a", "b", "c"], quarantine=True,
    )
    assert et.quarantine == {"out_of_range_ids": 2}  # -1 src, 5 >= len(names)
    assert et.num_edges == 3 and et.num_rows_raw == 5
    # strict mode keeps historic behavior: no filtering, no accounting
    et2 = from_arrays([0, 1], [1, 0])
    assert et2.quarantine is None


def test_quarantine_null_rows_parquet(tmp_path):
    """Parquet rows with null domains are filtered AND counted (the
    reference's :30 null filter, now with a structured record)."""
    pa = pytest.importorskip("pyarrow")
    import pyarrow.parquet as pq

    from graphmine_tpu.io.edges import load_parquet_edges
    from graphmine_tpu.pipeline.driver import run_pipeline

    table = pa.table({
        "_c0": ["p"] * 6,
        "_c1": ["a", "b", None, "c", "a", None],
        "_c2": ["b", "c", "x", None, "b", None],
        "_c3": ["q"] * 6,
    })
    p = str(tmp_path / "part.parquet")
    pq.write_table(table, p)
    et = load_parquet_edges(p)
    assert et.quarantine == {"null_rows": 3}
    assert et.num_edges == 3 and et.num_rows_raw == 6

    res = run_pipeline(PipelineConfig(
        data_path=p, outlier_method="none", num_devices=1, max_iter=2,
    ))
    q = res.metrics.of_phase("quarantine")
    assert q and q[0]["null_rows"] == 3

    # --no-quarantine-inputs: a strict-parsing run's metrics stream
    # carries no quarantine records (the parity null filter still runs)
    res_strict = run_pipeline(PipelineConfig(
        data_path=p, outlier_method="none", num_devices=1, max_iter=2,
        quarantine_inputs=False,
    ))
    assert not res_strict.metrics.of_phase("quarantine")
    assert res_strict.edge_table.num_edges == 3

    # streaming ingestion counts the same quarantine
    et_s = load_parquet_edges(p, batch_rows=2)
    assert et_s.quarantine == {"null_rows": 3}


# ---------------------------------------------------------------------------
# ISSUE 2: device-loss taxonomy, elastic mesh degradation, shard-aware
# checkpoints, divergence tripwires — every new fault site exercised here
# under the `faults` marker (file-level pytestmark)
# ---------------------------------------------------------------------------


def test_classify_device_loss_and_divergence():
    assert classify_error(faults.device_loss()) == resilience.DEGRADABLE_DEVICE
    # message-classified, like real PJRT reports — status prefix or phrase
    assert classify_error(
        RuntimeError("DATA_LOSS: checkpoint shard unreadable")
    ) == resilience.DEGRADABLE_DEVICE
    assert classify_error(
        RuntimeError("UNAVAILABLE: device failure on chip 0")
    ) == resilience.DEGRADABLE_DEVICE  # device markers beat retryable ones
    # a fatal error QUOTING a status token must not classify as device loss
    assert classify_error(
        ValueError("failed reading /data/DATA_LOSS_run/x")
    ) == FATAL

    de = resilience.DivergenceError("nonfinite_ranks", 3, 7)
    assert classify_error(de) == RETRYABLE
    assert de.kind == "nonfinite_ranks" and de.shard == 3 and de.iteration == 7
    # re-wrapped by an XLA callback boundary: still retryable via marker
    assert classify_error(
        RuntimeError(f"INTERNAL: CpuCallback error: {de}")
    ) == RETRYABLE


def test_tripwire_config_validation():
    ResilienceConfig(tripwire_every_k=4).validate()
    with pytest.raises(ValueError):
        ResilienceConfig(tripwire_every_k=-1).validate()


def test_run_phase_device_ladder_is_independent_of_memory_ladder():
    """An OOM walks the memory rungs, a device loss walks the device
    rungs; one run can walk both without either family consuming the
    other's rungs."""
    m = MetricsSink()

    def primary():
        raise faults.oom_error()

    def mem_rung():
        raise faults.device_loss()

    out = run_phase(
        "p", primary, ResilienceConfig(), m,
        ladder=(("leaner", mem_rung),),
        device_ladder=(("half-mesh", lambda: "elastic-ok"),),
        sleep=_no_sleep,
    )
    assert out == "elastic-ok"
    deg = m.of_phase("degrade")
    assert [d["to"] for d in deg] == ["leaner", "half-mesh"]
    assert "kind" not in deg[0] and deg[1]["kind"] == "device"

    # device ladder exhausted -> the device-loss error surfaces
    with pytest.raises(faults.InjectedDeviceLoss):
        run_phase(
            "p", lambda: (_ for _ in ()).throw(faults.device_loss()),
            ResilienceConfig(), MetricsSink(), sleep=_no_sleep,
        )

    # degradation="off" surfaces device loss without touching the ladder
    with pytest.raises(faults.InjectedDeviceLoss):
        run_phase(
            "p", lambda: (_ for _ in ()).throw(faults.device_loss()),
            ResilienceConfig(degradation="off"), MetricsSink(),
            device_ladder=(("half-mesh", lambda: "nope"),),
            sleep=_no_sleep,
        )


# ---------------------------------------------------------------------------
# sharded manifest checkpoints (API level)
# ---------------------------------------------------------------------------


def test_sharded_checkpoint_shard_corruption_rolls_back(tmp_path):
    """Corrupting any single shard file triggers rollback to the .prev
    generation (condemned generation preserved), never a crash or a
    silent bad resume."""
    d = str(tmp_path)
    good = np.arange(64, dtype=np.int32) % 11
    ckpt.save_sharded(d, good, 3, fingerprint="fp", num_shards=4)
    ckpt.save_sharded(d, good * 0, 4, fingerprint="fp", num_shards=4)
    faults.corrupt_shard(d, shard=2)
    m = MetricsSink()
    labels, it = ckpt.load_sharded(d, fingerprint="fp", sink=m)
    np.testing.assert_array_equal(labels, good)
    assert it == 3
    assert m.of_phase("checkpoint_rollback") and m.of_phase("checkpoint_rollback_ok")
    # promoted back to the current slot; condemned dir kept for forensics
    labels2, it2 = ckpt.load_sharded(d, fingerprint="fp")
    assert it2 == 3
    assert os.path.isdir(ckpt.sharded_dir(d) + ".corrupt")


def test_sharded_checkpoint_manifest_corruption_rolls_back(tmp_path):
    d = str(tmp_path)
    good = np.arange(32, dtype=np.int32)
    ckpt.save_sharded(d, good, 1, num_shards=2)
    ckpt.save_sharded(d, good + 1, 2, num_shards=2)
    faults.corrupt_manifest(d)
    labels, it = ckpt.load_sharded(d, sink=MetricsSink())
    assert it == 1
    np.testing.assert_array_equal(labels, good)


def test_sharded_checkpoint_both_generations_corrupt_is_clean_failure(tmp_path):
    d = str(tmp_path)
    ckpt.save_sharded(d, np.arange(8, dtype=np.int32), 1, num_shards=2)
    ckpt.save_sharded(d, np.arange(8, dtype=np.int32), 2, num_shards=2)
    faults.corrupt_shard(d, shard=0)
    faults.corrupt_file(os.path.join(
        ckpt.sharded_dir(d) + ".prev", "shard_00001.npy"
    ))
    with pytest.raises(ckpt.CheckpointCorruptionError, match="both"):
        ckpt.load_sharded(d)


def test_sharded_checkpoint_wrong_fingerprint_refuses_without_rollback(tmp_path):
    """A wrong-graph manifest must refuse — and must NOT roll back (every
    generation indexes the same wrong graph)."""
    d = str(tmp_path)
    ckpt.save_sharded(d, np.arange(8, dtype=np.int32), 1, fingerprint="A",
                      num_shards=2)
    ckpt.save_sharded(d, np.arange(8, dtype=np.int32), 2, fingerprint="A",
                      num_shards=2)
    m = MetricsSink()
    with pytest.raises(ckpt.FingerprintMismatch, match="different graph"):
        ckpt.load_sharded(d, fingerprint="B", sink=m)
    assert not m.of_phase("checkpoint_rollback")
    # both generations intact afterwards
    labels, it = ckpt.load_sharded(d, fingerprint="A")
    assert it == 2


# ---------------------------------------------------------------------------
# end-to-end: elastic mesh degradation + tripwires through the driver
# (8 virtual CPU devices via conftest; runs use a 4-device mesh)
# ---------------------------------------------------------------------------


def _cfg4(**kw):
    base = dict(num_devices=4, max_iter=5)
    base.update(kw)
    return _cfg(**base)


def test_device_loss_mid_lpa_degrades_mesh_and_completes(tmp_path):
    """A device-loss error at superstep 3 on a 4-device mesh walks the
    ELASTIC ladder: re-partition onto 2 devices, resume from the last
    good superstep, finish with labels identical to the no-fault run —
    and the distributed run checkpoints in the sharded manifest format."""
    from graphmine_tpu.pipeline.driver import run_pipeline

    ck = str(tmp_path / "ck")
    inj = faults.FaultInjector()
    inj.add("lpa_superstep", faults.device_loss, at=3)
    with inj.installed():
        res = run_pipeline(_cfg4(checkpoint_dir=ck))
    np.testing.assert_array_equal(res.labels, _baseline_labels())
    deg = res.metrics.of_phase("degrade")
    assert deg and deg[0]["kind"] == "device"
    assert deg[0]["to"] == "elastic@2dev"
    md0 = res.metrics.of_phase("mesh_degrade")[0]
    assert md0["schedule"] == "replicated"  # the variant current at descent
    md = res.metrics.of_phase("mesh_degrade")
    assert md and md[0]["from_devices"] == 4 and md[0]["to_devices"] == 2
    assert md[0]["iteration"] == 2  # resumed from the last good superstep
    # the implicated chip (parsed from the error message) is excluded
    # from the rebuilt rung meshes (mesh.surviving_mesh routing)
    assert md[0]["dead_devices"] == [2]
    iters = [r["iteration"] for r in res.metrics.of_phase("lpa_iter")]
    assert iters == [1, 2, 3, 4, 5]
    # the distributed rungs wrote the manifest format
    assert os.path.isdir(ckpt.sharded_dir(ck))
    saved = ckpt.load_sharded(ck)
    assert saved is not None and saved[1] == 5


def test_repeated_device_loss_walks_to_one_device(tmp_path):
    """Losing chips twice descends 4 -> 2 -> 1 (the single-device sort
    kernel floor) and still completes identically."""
    from graphmine_tpu.pipeline.driver import run_pipeline

    inj = faults.FaultInjector()
    inj.add("lpa_superstep", faults.device_loss, at=2)
    inj.add("lpa_superstep", faults.device_loss, at=4)
    with inj.installed():
        res = run_pipeline(_cfg4(checkpoint_dir=str(tmp_path / "ck")))
    np.testing.assert_array_equal(res.labels, _baseline_labels())
    md = res.metrics.of_phase("mesh_degrade")
    assert [(r["from_devices"], r["to_devices"]) for r in md] == [(4, 2), (2, 1)]


def test_kill_at_superstep_resumes_on_fewer_devices(tmp_path):
    """Acceptance: kill (preemption) at superstep 3 of a 4-device run ->
    a NEW run restores the sharded checkpoint onto 2 devices (re-shard on
    restore) -> final labels bit-identical to the uninterrupted run."""
    from graphmine_tpu.pipeline.driver import run_pipeline

    ck = str(tmp_path / "ck")
    inj = faults.FaultInjector()
    inj.add("lpa_superstep", faults.preemption, at=3)
    with inj.installed():
        with pytest.raises(faults.SimulatedPreemption):
            run_pipeline(_cfg4(checkpoint_dir=ck))
    saved = ckpt.load_sharded(ck)
    assert saved is not None and saved[1] == 2  # last good superstep
    # the replacement mesh has half the chips
    res = run_pipeline(_cfg4(checkpoint_dir=ck, resume=True, num_devices=2))
    np.testing.assert_array_equal(res.labels, _baseline_labels())
    resume = res.metrics.of_phase("resume")
    assert resume and resume[0]["iteration"] == 2


def test_poisoned_shard_trips_wire_rolls_back_and_completes(tmp_path):
    """Acceptance: silently corrupted labels in one shard (no error
    raised by the fault!) are caught by the tripwire within K supersteps,
    recorded with the offending shard index, rolled back to the last
    checkpoint, and the retried run completes identically."""
    from graphmine_tpu.pipeline.driver import run_pipeline

    ck = str(tmp_path / "ck")
    inj = faults.FaultInjector()
    inj.add("lpa_superstep", faults.poison_labels(shard=1, num_shards=4), at=3)
    cfg = _cfg4(
        checkpoint_dir=ck,
        resilience=ResilienceConfig(
            backoff_base_s=0.001, backoff_max_s=0.01, tripwire_every_k=1,
        ),
    )
    with inj.installed():
        res = run_pipeline(cfg)
    assert inj.fired() == 1
    np.testing.assert_array_equal(res.labels, _baseline_labels())
    tw = res.metrics.of_phase("tripwire")
    assert tw and tw[0]["kind"] == "label_out_of_range"
    assert tw[0]["iteration"] == 3 and 0 <= tw[0]["shard"] < 4
    assert tw[0]["bad_vertices"] > 0
    # rolled back to the superstep-2 checkpoint, then retried through
    resume = res.metrics.of_phase("resume")
    assert resume and resume[0]["iteration"] == 2
    assert resume[0]["reason"] == "tripwire"
    assert res.metrics.of_phase("retry")


def test_poisoned_shard_without_checkpoint_still_raises(tmp_path):
    """No checkpoint_dir: the tripwire still refuses to return garbage —
    the run dies with the classified DivergenceError (here: retries
    exhausted re-deriving from the same poisoned state) rather than
    silently converging to nonsense."""
    from graphmine_tpu.pipeline.driver import run_pipeline

    inj = faults.FaultInjector()
    inj.add("lpa_superstep", faults.poison_labels(shard=0, num_shards=4), at=2)
    cfg = _cfg4(resilience=ResilienceConfig(
        max_retries=1, backoff_base_s=0.001, backoff_max_s=0.01,
        tripwire_every_k=1,
    ))
    with inj.installed():
        with pytest.raises(resilience.RetriesExhausted) as ei:
            run_pipeline(cfg)
    assert isinstance(ei.value.__cause__, resilience.DivergenceError)


def test_load_newest_survives_one_corrupt_format(tmp_path):
    """One checkpoint format corrupt beyond its own rollback must not
    veto the other: load_newest holds the corruption error, tries the
    other format, and only re-raises when NOTHING loads."""
    d = str(tmp_path)
    # sharded format: both generations destroyed
    ckpt.save_sharded(d, np.arange(16, dtype=np.int32), 3, num_shards=2)
    ckpt.save_sharded(d, np.arange(16, dtype=np.int32), 4, num_shards=2)
    faults.corrupt_shard(d, shard=0)
    faults.corrupt_file(os.path.join(
        ckpt.sharded_dir(d) + ".prev", "shard_00000.npy"
    ))
    # npz format: intact, older iteration — still the right answer
    good = np.arange(16, dtype=np.int32) * 2
    ckpt.save_labels(d, good, 2)
    labels, it = ckpt.load_newest(d, sink=MetricsSink())
    assert it == 2
    np.testing.assert_array_equal(labels, good)
    # the higher iteration wins when both formats are healthy
    ckpt.save_sharded(d, good + 1, 9, num_shards=2)
    labels2, it2 = ckpt.load_newest(d)
    assert it2 == 9
    # nothing loadable at all -> the held corruption error surfaces
    import shutil

    shutil.rmtree(ckpt.sharded_dir(d))
    shutil.rmtree(ckpt.sharded_dir(d) + ".prev", ignore_errors=True)
    ckpt.save_sharded(d, good, 1, num_shards=2)
    faults.corrupt_shard(d, shard=0)
    for f in ("lpa_labels.npz", "lpa_labels.prev.npz"):
        if os.path.exists(os.path.join(d, f)):
            os.remove(os.path.join(d, f))
    with pytest.raises(ckpt.CheckpointCorruptionError):
        ckpt.load_newest(d)
    # empty dir -> None
    assert ckpt.load_newest(str(tmp_path / "nothing")) is None


def test_save_sharded_sweeps_orphaned_tmp_generations(tmp_path):
    """A SIGKILL mid-save leaves <gen>.tmp.<pid> behind; the next save —
    from a NEW pid in the crash-resume loop — must sweep it rather than
    leak one label-vector copy per kill."""
    d = str(tmp_path)
    orphan = ckpt.sharded_dir(d) + ".tmp.99999"
    os.makedirs(orphan)
    with open(os.path.join(orphan, "shard_00000.npy"), "wb") as f:
        f.write(b"leftover bytes from a killed save")
    ckpt.save_sharded(d, np.arange(8, dtype=np.int32), 1, num_shards=2)
    assert not os.path.exists(orphan)
    leftovers = [p for p in os.listdir(d) if ".tmp." in p]
    assert leftovers == []


def test_device_loss_after_memory_degradation_keeps_the_leaner_schedule(tmp_path):
    """A memory degradation already moved the run replicated -> ring;
    a later chip loss must rebuild RING on the smaller mesh — re-running
    the schedule that just OOM'd would burn the descent on a rung whose
    memory ladder is already consumed."""
    from graphmine_tpu.pipeline.driver import run_pipeline

    inj = faults.FaultInjector()
    inj.add("lpa_superstep", faults.oom_error, at=2)
    inj.add("lpa_superstep", faults.device_loss, at=4)
    with inj.installed():
        res = run_pipeline(_cfg4(checkpoint_dir=str(tmp_path / "ck")))
    np.testing.assert_array_equal(res.labels, _baseline_labels())
    deg = res.metrics.of_phase("degrade")
    assert [(d["to"], d.get("kind")) for d in deg] == [
        ("ring", None), ("elastic@2dev", "device"),
    ]
    md = res.metrics.of_phase("mesh_degrade")
    assert md and md[0]["schedule"] == "ring"  # the rescued variant survives


def test_checkpointed_supersteps_are_always_tripwire_guarded(tmp_path):
    """A superstep that will checkpoint is guarded even off the K
    cadence: persisting unverified labels would rotate the last
    validated generation away, and the tripwire's rollback would then
    restore intact-but-garbage bytes."""
    from graphmine_tpu.pipeline.driver import run_pipeline

    inj = faults.FaultInjector()
    # poison lands at superstep 3 — NOT a multiple of tripwire_every_k=2,
    # but checkpoint_every=1 means superstep 3 would be persisted
    inj.add("lpa_superstep", faults.poison_labels(shard=1, num_shards=4), at=3)
    cfg = _cfg4(
        checkpoint_dir=str(tmp_path / "ck"),
        resilience=ResilienceConfig(
            backoff_base_s=0.001, backoff_max_s=0.01, tripwire_every_k=2,
        ),
    )
    with inj.installed():
        res = run_pipeline(cfg)
    np.testing.assert_array_equal(res.labels, _baseline_labels())
    tw = res.metrics.of_phase("tripwire")
    assert tw and tw[0]["iteration"] == 3  # caught AT the save boundary
    # the rollback restored superstep 2, proving no garbage was persisted
    resume = res.metrics.of_phase("resume")
    assert resume and resume[0]["iteration"] == 2


def test_legacy_orbax_checkpoint_refuses_loudly(tmp_path):
    """A checkpoint written by the removed orbax format must not read as
    'no checkpoint' — silently restarting a multi-day run from iteration
    0 across the upgrade would discard every superstep."""
    d = str(tmp_path)
    os.makedirs(os.path.join(d, "lpa_orbax"))
    with pytest.raises(ckpt.CheckpointCorruptionError, match="orbax"):
        ckpt.load_sharded(d)
    with pytest.raises(ckpt.CheckpointCorruptionError, match="orbax"):
        ckpt.load_newest(d)
    # a valid checkpoint in a CURRENT format still wins (the orbax dir is
    # then stale leftovers, not the resume point)
    ckpt.save_labels(d, np.arange(8, dtype=np.int32), 4)
    labels, it = ckpt.load_newest(d)
    assert it == 4
    ckpt.save_sharded(d, np.arange(8, dtype=np.int32), 6, num_shards=2)
    _, it2 = ckpt.load_newest(d)
    assert it2 == 6
