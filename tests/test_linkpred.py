"""Link-prediction scores vs the NetworkX oracles."""

import numpy as np
import pytest

from graphmine_tpu.graph.container import build_graph
from graphmine_tpu.ops.linkpred import link_prediction

nx = pytest.importorskip("networkx")


def setup_graph(seed=0, v=50, e=260):
    rng = np.random.default_rng(seed)
    src = rng.integers(0, v, e).astype(np.int32)
    dst = rng.integers(0, v, e).astype(np.int32)
    g = build_graph(src, dst, num_vertices=v)  # dups/self-loops simplified inside
    G = nx.Graph()
    G.add_nodes_from(range(v))
    G.add_edges_from((int(a), int(b)) for a, b in zip(src, dst) if a != b)
    pairs = rng.integers(0, v, (80, 2))
    pairs = pairs[pairs[:, 0] != pairs[:, 1]]
    return g, G, pairs


@pytest.mark.parametrize("method,nx_fn", [
    ("jaccard", "jaccard_coefficient"),
    ("adamic_adar", "adamic_adar_index"),
    ("preferential_attachment", "preferential_attachment"),
    ("resource_allocation", "resource_allocation_index"),
])
def test_scores_match_networkx(method, nx_fn):
    g, G, pairs = setup_graph()
    got = link_prediction(g, pairs, method=method)
    ebunch = [tuple(map(int, p)) for p in pairs]
    expected = {(a, b): s for a, b, s in getattr(nx, nx_fn)(G, ebunch)}
    for (a, b), score in zip(ebunch, got):
        assert score == pytest.approx(expected[(a, b)], rel=1e-9), (a, b, method)


def test_common_neighbors_oracle():
    g, G, pairs = setup_graph(seed=3)
    got = link_prediction(g, pairs, method="common_neighbors")
    for (a, b), score in zip(pairs, got):
        assert score == len(list(nx.common_neighbors(G, int(a), int(b))))


def test_empty_pairs_and_orientation_invariance():
    g, G, pairs = setup_graph(seed=5)
    assert link_prediction(g, []).shape == (0,)
    # symmetric measures are pair-orientation invariant (the hub/leaf
    # swap optimization must not change scores)
    fwd = link_prediction(g, pairs, method="adamic_adar")
    rev = link_prediction(g, pairs[:, ::-1], method="adamic_adar")
    np.testing.assert_allclose(fwd, rev)


def test_validation_and_shapes():
    g, _, _ = setup_graph()
    with pytest.raises(ValueError, match="unknown method"):
        link_prediction(g, [(0, 1)], method="sorcery")
    with pytest.raises(ValueError, match="out of range"):
        link_prediction(g, [(0, 10_000)])
    with pytest.raises(ValueError, match="self-pairs"):
        link_prediction(g, [(3, 3)])
    one = link_prediction(g, (0, 1))
    assert one.shape == (1,)
